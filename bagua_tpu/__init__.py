"""bagua_tpu — a TPU-native distributed training acceleration framework.

A from-scratch JAX/XLA rebuild of the capabilities of Bagua
(github.com/Youhe-Jiang/bagua, surveyed in /root/repo/SURVEY.md): pluggable
communication *algorithms* (centralized / decentralized / low-precision /
asynchronous / MoE expert-parallel) decoupled from the communication substrate,
which here is XLA collectives over ICI/DCN on a named device mesh instead of a
Rust scheduler driving NCCL streams.
"""

from .version import __version__  # noqa: F401

from . import env  # noqa: F401

# the lockdep witness must wrap the lock factories BEFORE the imports below
# create the package's module-level locks (no-op unless BAGUA_LOCKDEP=on)
from .analysis import lockdep as _lockdep

_lockdep.maybe_install()

from .communication import (  # noqa: F401
    BaguaAborted,
    BaguaBackend,
    BaguaCommunicator,
    ReduceOp,
    abort,
    allgather,
    check_abort,
    is_aborted,
    reset_abort,
    allgather_inplace,
    allreduce,
    allreduce_inplace,
    alltoall,
    alltoall_inplace,
    alltoall_v,
    barrier,
    broadcast,
    gather,
    get_backend,
    init_process_group,
    reduce,
    reduce_scatter,
    reduce_scatter_inplace,
    scatter,
    send_recv,
)
from .bucket import BucketPlan, BucketSpec, split_bucket_by_bucket_size  # noqa: F401
from .core.backend import BaguaTrainer, TrainState  # noqa: F401
from .define import BaguaHyperparameter, TensorDeclaration, TensorDtype  # noqa: F401
from .env import (  # noqa: F401
    get_local_rank,
    get_local_size,
    get_rank,
    get_world_size,
)
from .parallel.mesh import (  # noqa: F401
    build_mesh,
    get_global_mesh,
    hierarchical_mesh,
    set_global_mesh,
)
from .tensor import NamedParam, build_params  # noqa: F401
