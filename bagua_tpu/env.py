"""Environment / flag accessors, backed by a declarative env-var registry.

TPU-native counterpart of the reference's ``bagua/torch_api/env.py`` (see
/root/reference/bagua/torch_api/env.py:1-101).  The reference reads
``RANK``/``WORLD_SIZE``/``LOCAL_RANK``/... injected by its launcher; under JAX the
process-level topology comes from :mod:`jax` itself (``jax.process_index`` /
``jax.device_count``), while in-program data-parallel "ranks" are positions on a
:class:`jax.sharding.Mesh` axis.  The ``BAGUA_*`` tunables keep their reference
names so launcher scripts port over unchanged.

Every ``BAGUA_*`` variable the package consumes is DECLARED here in
:data:`ENV_REGISTRY` (name, type, default, doc) and read through the typed
accessors below.  ``bagua-lint``'s ``raw-env-read`` rule enforces the
discipline: any ``os.environ`` read of a ``BAGUA_*`` name outside this module
is a finding, so a tunable cannot exist without a registry row — and
``docs/env_vars.md`` (generated from the registry by
``scripts/gen_env_docs.py``) cannot go stale.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple


# ---- registry ------------------------------------------------------------


@dataclass(frozen=True)
class EnvVar:
    """One declared environment variable: the single source of truth for its
    type, default, and operator-facing documentation."""

    name: str
    type: str  # "int" | "float" | "bool" | "str" | "enum"
    default: str  # raw (string) default, as the operator would spell it
    doc: str
    choices: Tuple[str, ...] = ()


ENV_REGISTRY = {}


def _declare(name: str, type: str, default: str, doc: str,
             choices: Tuple[str, ...] = ()) -> None:
    ENV_REGISTRY[name] = EnvVar(name, type, default, doc, choices)


# -- core comm / bucketing --
_declare("BAGUA_DEFAULT_BUCKET_SIZE", "int", str(10 * 1024 ** 2),
         "Default communication bucket size in bytes (reference env.py:50-57).")
_declare("BAGUA_OVERLAP", "enum", "auto",
         "Overlap-scheduler dispatch gate: stream per-bucket gradient "
         "collectives into backward/accumulation compute (`on`), keep the "
         "exact serialized step construction (`off`), or take whichever "
         "path measured faster (`auto`, see BENCH_OVERLAP.json).",
         choices=("auto", "on", "off"))
_declare("BAGUA_OVERLAP_CHUNK_BYTES", "int", "0",
         "Target per-rank bytes of one independent ring sub-collective under "
         "the overlap scheduler; 0 keeps the fused XLA collectives.")
_declare("BAGUA_OVERLAP_CHUNK_BYTES_INTRA", "int", "0",
         "Per-tier ring chunk target for the slice-local ICI stages of the "
         "hierarchical two-level collectives (and the flat single-axis "
         "ring); 0 falls back to BAGUA_OVERLAP_CHUNK_BYTES.  See "
         "docs/hierarchical.md.")
_declare("BAGUA_OVERLAP_CHUNK_BYTES_INTER", "int", "0",
         "Per-tier ring chunk target for the cross-slice DCN stage of the "
         "hierarchical two-level collectives — size it larger than the ICI "
         "target (a chunk that amortizes an ICI hop is far too small for a "
         "DCN hop); 0 falls back to BAGUA_OVERLAP_CHUNK_BYTES.")
_declare("BAGUA_COMPRESS_INTRA", "str", "auto",
         "Per-link codec policy for the slice-local ICI tier (and the flat "
         "single-axis ring): `auto` (default) keeps ICI full-precision — "
         "slice-local bytes are cheap; `off` forces full precision; a "
         "codec name (minmax_uint8|int8|fp8_e4m3|fp8_e5m2|onebit_ef|topk) "
         "makes the flat/intra ring hops carry that codec's payload — an "
         "explicit opt-in to lossy gradient communication.  The stateful "
         "codecs (onebit_ef, topk) additionally engage the per-bucket "
         "error-feedback residual on the families that support it.  See "
         "docs/compression.md.")
_declare("BAGUA_COMPRESS_INTER", "str", "auto",
         "Per-link codec policy for the cross-slice DCN tier of the "
         "hierarchical two-level collectives: `auto` (default) defers to "
         "the algorithm family — ByteGrad/QAdam compress the DCN stage "
         "natively (quantized ring hops, fp32 accumulation), exact "
         "families stay full precision; `off` forces full precision even "
         "for the compression families; a codec name "
         "(minmax_uint8|int8|fp8_e4m3|fp8_e5m2|onebit_ef|topk) compresses "
         "the DCN hops for EVERY family.  The autopilot's compress_dcn "
         "trend hint actuates this knob through the autotune "
         "recommendation path, escalating along the codec ladder "
         "uint8 -> fp8 -> onebit_ef -> topk on sustained DCN dominance.")
_declare("BAGUA_TOPK_RATIO", "float", "0.01",
         "Compression-ratio knob of the `topk` ring codec: fraction of "
         "each chunk's elements kept on the wire (indices + f32 values; "
         "0.01 keeps the top 1% by magnitude, ~50x fewer DCN bytes than "
         "f32).  Resolved when the codec is looked up (trainer "
         "construction / step trace) and keyed into the step cache, so a "
         "changed value retraces the compiled payload shapes.  See "
         "docs/compression.md.")
_declare("BAGUA_EF_RESIDUAL", "enum", "on",
         "Error-feedback residual for the stateful ring codecs "
         "(onebit_ef/topk): `on` (default) accumulates the per-bucket "
         "quantization error and folds it into the next step's gradient "
         "— the convergence contract of 1-bit compression; `off` lets the "
         "codec ride STATELESSLY (biased sign-SGD — diverges on real "
         "tasks; the BENCH_COMPRESS honesty control).  Set before trainer "
         "construction: flipping it mid-run changes the train-state "
         "structure.", choices=("on", "off"))
_declare("BAGUA_FLAT_RESIDENT", "enum", "auto",
         "Flat-resident training state: keep params/grads/optimizer state "
         "as bucket-flat buffers across steps (`on`), keep the leaf pytree "
         "layout (`off`), or engage it wherever the algorithm family "
         "supports it on a pure-data-parallel mesh (`auto`, see "
         "docs/flat_layout.md and BENCH_FLAT.json).",
         choices=("auto", "on", "off"))
_declare("BAGUA_MAX_EXCHANGE_PERIOD", "int", "128",
         "Largest step-pairing period precompiled into one program by "
         "`exchange_with_peer` (compile-size guard for pod-scale gossip).")
_declare("BAGUA_MAX_RING_CHUNKS", "int", "32",
         "Compile-size guard for the chunked ring collectives: max "
         "independent sub-collectives per bucket.")
_declare("BAGUA_COORDINATOR_ADDR", "str", "",
         "host:port of the JAX coordination service for multi-process "
         "bring-up (consumed by `init_process_group`).")
_declare("BAGUA_COMM_TIMEOUT_S", "str", "300",
         "Hang-watchdog timeout for watched collectives, in seconds; "
         "``0``/``off``/``false``/``none`` disables the watchdog.")
_declare("BAGUA_LOCKDEP", "enum", "off",
         "Runtime lockdep witness (bagua-lint v2, docs/analysis.md): `on` "
         "wraps every lock the package creates so real acquisition orders "
         "are recorded and opposite-order pairs (live deadlock windows) "
         "are detected; the witness JSON is cross-checked against the "
         "static concurrency engine's graph in CI.  Diagnostics only — "
         "adds per-acquisition bookkeeping, keep `off` in production.",
         choices=("off", "on"))
_declare("BAGUA_LOCKDEP_OUT", "str", "",
         "Output path for the lockdep witness JSON (edges, inversions, "
         "per-site acquisition counts), written at process exit.  Empty "
         "falls back to ./bagua_lockdep_witness.json.")
# -- robustness / fault handling --
_declare("BAGUA_GRAD_GUARD", "enum", "off",
         "Gradient-health sentinel policy: per-bucket isfinite checks on "
         "every step's gradients.  `warn` logs unhealthy steps, `skip` "
         "rewinds them (params/optimizer state untouched) and escalates to "
         "abort after a consecutive-skip budget, `abort` raises the comm "
         "abort flag on the first unhealthy step.  See docs/robustness.md.",
         choices=("off", "warn", "skip", "abort"))
_declare("BAGUA_FAULT_PLAN", "str", "",
         "Deterministic fault-injection plan (JSON list of specs: point, "
         "kind, step/op trigger, count, seed) armed at process start — "
         "drills and chaos tests only, never production.  Points: "
         "store.op, elastic.heartbeat, ckpt.write, ckpt.sidecar, "
         "collective.hang, grad.poison, step.straggle, async.partition, "
         "podsim.link.  See bagua_tpu.faults.inject.")
_declare("BAGUA_ASYNC_MAX_STALENESS", "int", "4",
         "Bounded-staleness cap for async model averaging: when any rank's "
         "applied-round counter reaches this many rounds behind the "
         "launched count (grad-guard rewinds or async.partition drops "
         "stall it), that negotiated boundary forces a synchronous "
         "catch-up average that leaves every rank's replica bit-identical "
         "— the lag never exceeds the cap.  0 disables the bound (purely "
         "asynchronous).  Constructor knob: "
         "AsyncModelAverageAlgorithm(max_staleness_rounds=).")
# -- autotune sidecar --
_declare("BAGUA_SERVICE_PORT", "int", "-1",
         "Port of the autotune hyperparameter service; -1 disables.")
_declare("BAGUA_AUTOTUNE", "int", "0",
         "Autotune level: 0 off, 1 bucket-size search, 2 adds the "
         "tensor-readiness telemetry pipeline.")
_declare("BAGUA_AUTOTUNE_MAX_SAMPLES", "int", "60",
         "Max hyperparameter samples the Bayesian optimizer may score.")
_declare("BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S", "float", "5.0",
         "Seconds of speed samples per hyperparameter config before scoring.")
_declare("BAGUA_AUTOTUNE_WARMUP_TIME_S", "float", "30.0",
         "Warmup seconds before the autotuner starts scoring configs.")
_declare("BAGUA_AUTOTUNE_ALGORITHM", "bool", "0",
         "Let the autotuner search over algorithm families too "
         "(centralized / low-precision selectable; TPU extension).")
_declare("BAGUA_AUTOTUNE_GOODPUT", "bool", "1",
         "Score autotune sampling windows on fleet-min goodput (windowed "
         "goodput_fraction/MFU/DCN-share observations ride each check-in "
         "when the obs plane is on); 0 reports no observations, falling "
         "back to the summed-speed score.")
_declare("BAGUA_AUTOTUNE_SPACE", "str", "auto",
         "Autotune search space: 'auto' reports trainer capabilities at "
         "registration so the service searches the full capability-gated "
         "v2 knob space (overlap + per-tier chunk bytes, codec ladder, "
         "flat residency, family switching); 'legacy' keeps the "
         "bucket-size x hierarchical two-knob space.")
_declare("BAGUA_REPORT_METRICS", "bool", "0",
         "Report training metrics to the autotune service.")
_declare("BAGUA_IS_OUTPUT_AUTOTUNE_LOG", "bool", "0",
         "Write the autotune search log to disk.")
# -- profiling --
_declare("BAGUA_PROFILE_DIR", "str", "",
         "Directory for jax profiler traces; empty disables auto-capture.")
_declare("BAGUA_PROFILE_STEPS", "str", "2:5",
         "``start:stop`` step window (half-open) for trainer auto-capture.")
# -- kernels / codecs --
_declare("BAGUA_FLASH_ATTENTION", "bool", "1",
         "Enable the Pallas flash-attention kernel above the measured "
         "sequence-length crossover; 0 forces XLA's fused attention.")
_declare("BAGUA_DISABLE_PALLAS_CODEC", "bool", "0",
         "Force the jnp (XLA) MinMaxUInt8 codec lowering even on TPU "
         "(A/B checks against the Pallas kernel).")
# -- elastic membership (injected by the launcher, see distributed/run.py) --
_declare("BAGUA_ELASTIC", "bool", "0",
         "Set by the launcher when lease-based elastic membership is on.")
_declare("BAGUA_ELASTIC_EPOCH", "int", "0",
         "Rendezvous epoch fencing counter (launcher-injected).")
_declare("BAGUA_ELASTIC_NODE_ID", "int", "0",
         "This node's stable identity slot (launcher-injected).")
_declare("BAGUA_ELASTIC_STORE_ADDR", "str", "",
         "host:port of the restart TCPStore carrying membership leases.")
_declare("BAGUA_ELASTIC_MIN_NNODES", "int", "1",
         "Lower bound of the elastic world size (launcher-injected).")
_declare("BAGUA_ELASTIC_MAX_NNODES", "int", "",
         "Upper bound of the elastic world size (launcher-injected); "
         "defaults to the launched node count when unset.")
_declare("BAGUA_ELASTIC_JOIN_WINDOW_S", "float", "30",
         "Seconds a rendezvous round stays open for late joiners.")
_declare("BAGUA_ELASTIC_LEASE_TTL_S", "float", "15",
         "Membership lease TTL; an expired lease shrinks the world.")
_declare("BAGUA_ELASTIC_TELEMETRY_OUT", "str", "",
         "Path where membership counters + transitions are dumped on exit.")
_declare("BAGUA_ELASTIC_HEALTH_FILE", "str", "",
         "Path of this worker's health beacon file (launcher-injected, one "
         "file per local rank): the worker's gradient-guard / "
         "async-staleness event counters are published here; the launcher "
         "merges all local beacons and carries them on its lease heartbeat "
         "to the coordinator as a health payload.")
# -- restart-store replication / coordinator failover (docs/robustness.md) --
_declare("BAGUA_RESTART_STORE_ENDPOINTS", "str", "",
         "Comma-separated ``host:port`` list (priority order) of replicated "
         "restart-store endpoints.  Entry 0 is the initial primary; later "
         "entries are standby followers the primary streams its op log to, "
         "and the clients fail over to (promoting the first reachable one) "
         "when the primary dies.  Empty = the single coordinator-hosted "
         "store, byte-identical to the pre-replication path.")
_declare("BAGUA_RESTART_STORE_OP_DEADLINE_S", "float", "45",
         "Total retry budget (seconds) for one restart-store op across "
         "reconnects and endpoint failovers; exhausting it raises instead "
         "of retrying forever inside watchdog sections.  0 disables the "
         "budget (the pre-failover unbounded behavior).")
_declare("BAGUA_RESTART_COORD_LEASE_TTL_S", "float", "5",
         "Coordinator leadership lease TTL: the active coordinator renews "
         "a lease key in the (replicated) restart store at TTL/3; a "
         "standby that sees no renewal for a full TTL on its own clock "
         "promotes the store and takes the coordinator role over.")
_declare("BAGUA_RESTART_TAKEOVER_GRACE_S", "float", "0",
         "Grace window after a coordinator takeover during which member "
         "leases are re-armed rather than expired (heartbeats queued "
         "against the dead primary need time to drain to the promoted "
         "store).  0 = auto: 2x BAGUA_ELASTIC_LEASE_TTL_S.")
# -- observability plane (docs/observability.md) --
_declare("BAGUA_OBS", "enum", "on",
         "Unified observability plane master switch: step-span tracing, the "
         "crash flight recorder, and the metrics exporter.  Host-side only "
         "— the compiled step program is identical in both modes "
         "(jaxpr-equality-pinned); `off` restores the exact pre-obs host "
         "behavior.",
         choices=("on", "off"))
_declare("BAGUA_OBS_RING", "int", "512",
         "Span ring-buffer capacity per process; the oldest spans drop "
         "(drop count retained) so long runs keep a bounded, readable "
         "tail for the flight recorder.")
_declare("BAGUA_OBS_DUMP_DIR", "str", "",
         "Directory for flight-recorder post-mortem dumps (watchdog abort, "
         "grad-guard escalation, health fence, armed-fault fires, SIGTERM): "
         "last-N spans + counters snapshot + step metrics, rank-tagged "
         "JSON.  Empty disables the recorder.")
_declare("BAGUA_OBS_EXPORT_DIR", "str", "",
         "Directory the background metrics exporter writes into "
         "(`metrics.jsonl` one snapshot per line + `metrics.prom` "
         "Prometheus textfile).  Empty disables the exporter thread.")
_declare("BAGUA_OBS_EXPORT_INTERVAL_S", "float", "10",
         "Metrics exporter snapshot period in seconds.")
_declare("BAGUA_OBS_EXPORT_MAX_BYTES", "int", str(64 * 1024 ** 2),
         "Size cap for the exporter's append-only `metrics.jsonl`: at the "
         "cap the file rotates to `metrics.jsonl.1` (replacing the "
         "previous rotation) so a long run keeps at most two generations "
         "on disk.  0 disables rotation (unbounded growth).")
_declare("BAGUA_OBS_FLEET_OUT", "str", "",
         "Coordinator-side fleet snapshot path: the elastic monitor merges "
         "every member's heartbeat health payload (per-rank step, "
         "staleness, skip counts, step-dt percentiles) into one atomic "
         "JSON.  Empty disables.")
_declare("BAGUA_OBS_ANOMALY", "enum", "on",
         "Step-time anomaly detector: rolling median/MAD baseline over "
         "the raw host step cadence and per-phase durations; anomalies "
         "count (`obs/step_anomalies`), trigger a throttled flight dump, "
         "publish a `straggler_suspect` phase breakdown into the health "
         "beacon, and feed perf hints to the autotune service.  Host-side "
         "only (no effect on the compiled step); rides the BAGUA_OBS "
         "master switch.",
         choices=("on", "off"))
_declare("BAGUA_OBS_ANOMALY_WINDOW", "int", "64",
         "Rolling-baseline window (steps) of the step-time anomaly "
         "detector.")
_declare("BAGUA_OBS_ANOMALY_WARMUP", "int", "16",
         "Baseline samples required before the anomaly detector may flag "
         "(compile steps and cold caches must not poison the yardstick).")
_declare("BAGUA_OBS_ANOMALY_THRESHOLD", "float", "5.0",
         "Robust-z threshold (MAD multiples) a step's raw cadence must "
         "exceed over the rolling median to count as anomalous.")
_declare("BAGUA_OBS_DUMP_MAX_FILES", "int", "64",
         "Retention cap for flight-recorder dumps under "
         "BAGUA_OBS_DUMP_DIR: when a new dump would leave more than this "
         "many flight_*.json files, the oldest (by mtime) are pruned "
         "first (counted in obs/flight_dumps_pruned).  Dumps are already "
         "overwritten per (trigger, fault point, rank, pid), so growth "
         "comes from restarts minting new pids — a long run with "
         "recurring throttled faults previously accumulated dumps "
         "without limit.  0 disables pruning (unbounded).")
_declare("BAGUA_OBS_HTTP_PORT", "int", "0",
         "Port of the per-process HTTP status plane "
         "(bagua_tpu.obs.http): `/metrics` serves the SAME Prometheus "
         "text the exporter writes to metrics.prom, `/healthz` liveness, "
         "`/ledger` the goodput report; the elastic coordinator "
         "additionally serves `/fleet` (latest bagua-obs-fleet-v1 "
         "snapshot) and `/history?metric=&window=` (historian windows).  "
         "0 (default) disables the server; a taken port falls back to an "
         "ephemeral one (logged, and published as the obs/http_port "
         "gauge).  The elastic launcher offsets each local worker's port "
         "(base + 1 + local_rank) so one host's processes never collide.")
_declare("BAGUA_OBS_HTTP_ADDR", "str", "127.0.0.1",
         "Bind address of the HTTP status plane.  The default stays on "
         "loopback — expose it beyond the host deliberately (0.0.0.0) "
         "only where the network is trusted; the endpoints are "
         "read-only but unauthenticated.")
_declare("BAGUA_OBS_HISTORIAN", "enum", "off",
         "Coordinator-side fleet telemetry historian "
         "(bagua_tpu.obs.historian): bounded per-rank per-metric "
         "time-series rings fed by the beacon->heartbeat obs summaries "
         "in every fleet snapshot, with windowed rate/percentile/"
         "least-squares-slope queries.  Publishes derived trend gauges "
         "(obs/goodput_slope, obs/hbm_headroom_slope, "
         "obs/dcn_comm_share) back into the snapshot — the evidence the "
         "autopilot's trend rules (pre-OOM resize, DCN compression "
         "escalation) consume — and persists its rings through the "
         "restart TCPStore so a relaunched coordinator keeps history.",
         choices=("off", "on"))
_declare("BAGUA_OBS_HISTORIAN_CAPACITY", "int", "512",
         "Samples retained per (rank, metric) historian ring; the oldest "
         "drop first.  At the default ~1/s monitor cadence this is ~8.5 "
         "minutes of full-rate history per series (slower snapshot "
         "writers keep proportionally longer windows).")
_declare("BAGUA_OBS_HISTORIAN_WINDOW_S", "float", "600",
         "Trend window in seconds: slopes, percentiles, and the DCN "
         "comm share are computed over the trailing window of this "
         "length (the `sustained` horizon behind obs/goodput_slope and "
         "friends; /history defaults to it too).")
# -- serving plane (docs/serving.md) --
_declare("BAGUA_SERVE_MAX_SLOTS", "int", "8",
         "Batch slots of the continuous-batching inference engine: the "
         "static batch dimension of the compiled decode tick.  Requests "
         "join/evict mid-batch without recompiling; more slots raise "
         "throughput at the cost of per-tick latency and pool pressure.")
_declare("BAGUA_SERVE_PAGE_SIZE", "int", "16",
         "Tokens per KV-cache page of the serving engine's paged pool; "
         "must divide the model's max_seq_len.  Smaller pages waste less "
         "memory on short tails, larger pages gather more contiguously.")
_declare("BAGUA_SERVE_NUM_PAGES", "int", "0",
         "Page-pool capacity per layer (including the 2 reserved "
         "zero/trash pages).  0 (default) auto-sizes to max_slots full-"
         "length sequences — no preemption pressure; set lower to "
         "oversubscribe HBM and rely on the queue-then-preempt "
         "backpressure instead.")
_declare("BAGUA_SERVE_QUEUE_DEPTH", "int", "256",
         "Admission-queue depth of the serving engine; submissions beyond "
         "it raise ServeQueueFull (explicit shed/retry backpressure, "
         "never an OOM).")
_declare("BAGUA_SERVE_PREFILL_CHUNK", "int", "8",
         "Prompt tokens one chunked-prefill call consumes for a single "
         "slot (at most one such call per scheduler tick, so long prompts "
         "cannot stall running decodes); 1 disables the chunked program — "
         "prompts then stream through the batched tick one token per "
         "tick, generate()-style.")
_declare("BAGUA_SERVE_TICK_IDLE_S", "float", "0.001",
         "Scheduler idle-poll granularity in seconds: how long one wait "
         "slice lasts while the engine is empty and ahead of the next "
         "trace arrival (the wall it books as batch_formation_idle).")
_declare("BAGUA_ELASTIC_FENCE_UNHEALTHY", "int", "0",
         "Coordinator-side health fence: expel a member whose heartbeat "
         "health payload reports at least this many unhealthy events "
         "(non-finite-gradient steps, missed async negotiation "
         "boundaries).  The fenced node's launcher exits instead of "
         "rejoining; survivors resize through the normal epoch machinery.  "
         "0 (default) disables fencing.")
# -- fleet autopilot (docs/autopilot.md) --
_declare("BAGUA_AUTOPILOT", "enum", "off",
         "Closed-loop fleet autopilot: the coordinator-side policy engine "
         "over the fleet snapshot stream.  `off` (default) never "
         "constructs the engine — coordinator behavior and the compiled "
         "step are exactly the pre-autopilot ones; `observe` runs the full "
         "decision matrix and flight-records every decision WITHOUT "
         "actuating (the dry-run rollout mode); `act` additionally "
         "actuates through the existing machinery (health fence/resize, "
         "autotune perf hints, algorithm-family switch, checkpoint "
         "storage quarantine).",
         choices=("off", "observe", "act"))
_declare("BAGUA_AUTOPILOT_SLO_GOODPUT", "float", "0",
         "Goodput-fraction SLO for the autopilot's escalation ladder: a "
         "fleet whose worst rank sits below this fraction for "
         "BAGUA_AUTOPILOT_SUSTAIN consecutive snapshots walks hint -> "
         "retune -> algorithm-family switch -> resize.  0 (default) "
         "disables the SLO rule.")
_declare("BAGUA_AUTOPILOT_SUSTAIN", "int", "3",
         "Hysteresis: consecutive fleet snapshots a rule's condition must "
         "hold before its action fires (one blip never actuates).")
_declare("BAGUA_AUTOPILOT_COOLDOWN_S", "float", "300",
         "Per-action-kind cooldown: after an autopilot action of a kind "
         "fires, further actions of that kind are suppressed for this "
         "many seconds (counted in autopilot/suppressed_cooldown).")
_declare("BAGUA_AUTOPILOT_BUDGET", "int", "8",
         "Global action budget per run: once the autopilot has taken this "
         "many actions it stops actuating entirely (counted in "
         "autopilot/suppressed_budget) — a mis-tuned policy can never "
         "flap a fleet indefinitely.  0 disables the autopilot's actions.")
_declare("BAGUA_AUTOPILOT_STALENESS_S", "float", "60",
         "Fleet-snapshot freshness bound: the policy engine refuses to "
         "decide on a snapshot older than this (a wedged snapshot writer "
         "must not cause actions from stale evidence; counted in "
         "autopilot/stale_snapshots).")
_declare("BAGUA_AUTOPILOT_STRAGGLER_RATIO", "float", "3.0",
         "Minimum straggler_suspect step-time ratio for the autopilot's "
         "chronic-straggler / victim rules to count a snapshot toward "
         "their sustain streak (blips below it are the anomaly "
         "detector's business, not the autopilot's).")
_declare("BAGUA_AUTOPILOT_SUSPECT_TTL_S", "float", "120",
         "How long a straggler_suspect stays live evidence: a suspect "
         "detected longer ago than this no longer feeds the straggler/"
         "victim streaks (the beacon keeps re-publishing the LATEST "
         "suspect even after the rank recovers).")
_declare("BAGUA_AUTOPILOT_CKPT_FAILURES", "int", "3",
         "Checkpoint-integrity threshold: a rank reporting at least this "
         "many integrity failures + fallback restores gets its storage "
         "path quarantined (saves redirect; see docs/autopilot.md).")
_declare("BAGUA_AUTOPILOT_FAMILY", "str", "async",
         "Algorithm family the escalation ladder's switch rung commands "
         "(through the autotune service's recommendation path; must be a "
         "SWITCHABLE_ALGORITHMS name).")
_declare("BAGUA_AUTOPILOT_MODEL", "str", "bagua_module",
         "Autotune task (model_name) the autopilot's perf hints and "
         "family-switch commands address — the BaguaTrainer model_name "
         "default unless the job names its model.")
_declare("BAGUA_AUTOPILOT_DCN_SHARE", "float", "0.5",
         "DCN-dominance threshold for the autopilot's trend rule: when "
         "the historian's obs/dcn_comm_share (windowed mean DCN device "
         "seconds over windowed mean step time) sits at or above this "
         "fraction for BAGUA_AUTOPILOT_SUSTAIN snapshots, the autopilot "
         "emits a compression-family escalation hint — compress the slow "
         "tier (docs/hierarchical.md).  Requires the historian "
         "(BAGUA_OBS_HISTORIAN=on): without trend windows the rule never "
         "fires.  0 disables the rule.")
_declare("BAGUA_AUTOPILOT_COMPRESS_FAMILY", "str", "bytegrad",
         "Compression algorithm family the DCN-dominance hint names "
         "(its hierarchical path compresses only the cross-slice DCN "
         "stage; delivered as an autotune perf hint, never a forced "
         "switch).")
_declare("BAGUA_AUTOPILOT_COMPRESS_CODEC", "str", "minmax_uint8",
         "DCN wire codec the autopilot's compress_dcn hint ACTUATES: the "
         "autotune service applies it to the recommended "
         "`compress_inter` policy, so every rank's next check-in re-jits "
         "its hierarchical collectives with compressed cross-slice ring "
         "hops (minmax_uint8|int8|fp8_e4m3|fp8_e5m2; "
         "docs/compression.md).")
_declare("BAGUA_AUTOPILOT_HBM_HORIZON_S", "float", "600",
         "Pre-OOM horizon for the autopilot's HBM trend rule: when a "
         "rank's historian headroom slope (obs/hbm_headroom_slope) is "
         "negative and projects exhaustion within this many seconds "
         "(headroom / -slope), sustained BAGUA_AUTOPILOT_SUSTAIN "
         "snapshots, the autopilot resizes that node away BEFORE the "
         "OOM kills the gang mid-collective.  Requires the historian; "
         "0 disables the rule.")
_declare("BAGUA_CKPT_QUARANTINED_PATHS", "str", "",
         "Newline-separated checkpoint directories under storage "
         "quarantine (newline, not os.pathsep — ':' appears inside "
         "gs://-style URIs): BaguaCheckpointManager redirects saves for "
         "them to a `<dir>.redirect` sibling while restores keep walking "
         "the verified pre-quarantine history.  Injected by the elastic "
         "launcher at restart boundaries when the autopilot (in act mode) "
         "quarantined a path; operators can set it by hand.")
# -- pod-scale drill (docs/podsim.md) --
_declare("BAGUA_SCALE_RANKS", "str", "32,64,128",
         "Comma-separated world sizes scripts/scale_drill.py sweeps: the "
         "first (largest-affordable full) size runs the end-to-end "
         "scenario — shaped collectives, elastic shrink/regrow, autopilot "
         "fence — and every size runs the rendezvous + control-plane "
         "benches recorded in BENCH_SCALE.json.")
_declare("BAGUA_SCALE_SHAPE", "str", "pod",
         "Link-shape model for the pod simulator's data plane: a preset "
         "name (off|pod|wan) or a JSON ShapeSpec object — per-class "
         "latency/bandwidth/jitter for ICI vs DCN edges.  See "
         "bagua_tpu.podsim.shaping.SHAPE_PRESETS and docs/podsim.md.")
_declare("BAGUA_SCALE_SEED", "int", "0",
         "Determinism seed for the pod simulator: the shaped links' "
         "jitter hash and the drill's per-rank gradient vectors both "
         "derive from it, so two runs at one seed inject identical "
         "network time.")
_declare("BAGUA_SCALE_DCN_CODEC", "str", "minmax_uint8",
         "Wire codec for the pod simulator's cross-slice DCN ring "
         "(f32|minmax_uint8|onebit_ef|topk): scale_drill.py exercises the "
         "selected codec's numpy mirror cross-process and verifies the "
         "hierarchical allreduce within its quantization tolerance.  See "
         "bagua_tpu.podsim.collectives and docs/podsim.md.")


# ---- typed accessors -----------------------------------------------------


def _raw(name: str) -> Optional[str]:
    """The ambient value of a REGISTERED variable (None/'' -> None).  The one
    sanctioned ``os.environ`` read for ``BAGUA_*`` names — call sites outside
    this module go through here (or the typed wrappers below) so bagua-lint's
    ``raw-env-read`` rule can hold the line."""
    if name not in ENV_REGISTRY:
        raise KeyError(f"{name} is not declared in env.ENV_REGISTRY")
    v = os.environ.get(name)
    return None if v in (None, "") else v


def env_str(name: str) -> str:
    v = _raw(name)
    return ENV_REGISTRY[name].default if v is None else v


def env_int(name: str) -> int:
    v = _raw(name)
    if v is None:
        return int(ENV_REGISTRY[name].default)
    try:
        return int(v)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {v!r}"
        ) from None


def env_float(name: str) -> float:
    v = _raw(name)
    if v is None:
        return float(ENV_REGISTRY[name].default)
    try:
        return float(v)
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {v!r}"
        ) from None


def env_bool(name: str) -> bool:
    """Reference-compatible boolean: ``"1"`` is on, anything else off —
    except vars whose DEFAULT is on, where only ``"0"`` turns them off
    (matches the historical ``!= "0"`` gates)."""
    v = _raw(name)
    spec = ENV_REGISTRY[name]
    if v is None:
        return spec.default == "1"
    return v != "0" if spec.default == "1" else v == "1"


#: values that read as "disabled" for off-switchable duration vars
#: (:func:`env_seconds_or_off`); the empty string counts too
_OFF_VALUES = ("", "0", "off", "false", "no", "none")


def env_seconds_or_off(name: str) -> Optional[float]:
    """Float seconds with an off switch: ``0``/``off``/``false``/``no``/
    ``none``/empty mean disabled (None).  An explicitly EMPTY value is
    honored as off — only an unset variable falls back to the registry
    default (the ``BAGUA_COMM_TIMEOUT_S`` contract: collapsing ``""`` to
    the default would silently re-enable the watchdog)."""
    v = os.environ.get(name)
    if v is None:
        v = ENV_REGISTRY[name].default
    if v.strip().lower() in _OFF_VALUES:
        return None
    try:
        return float(v)
    except ValueError:
        raise ValueError(
            f"{name} must be a number of seconds or one of "
            f"{'/'.join(repr(x) for x in _OFF_VALUES)}, got {v!r}"
        ) from None


def env_enum(name: str) -> str:
    v = env_str(name).strip().lower() or ENV_REGISTRY[name].default
    choices = ENV_REGISTRY[name].choices
    if choices and v not in choices:
        raise ValueError(
            f"{name} must be {'|'.join(choices)}, got {v!r}"
        )
    return v


def _int_env(name: str, default: int) -> int:
    """Unregistered int read (RANK/WORLD_SIZE-family launcher vars)."""
    v = os.environ.get(name)
    if v in (None, ""):
        return default
    try:
        return int(v)
    except ValueError:
        raise ValueError(
            f"environment variable {name} must be an integer, got {v!r}"
        ) from None


# ---- process topology (launcher-injected, reference names) ---------------


def get_rank() -> int:
    """Global process rank (multi-host: one JAX process per host)."""
    v = os.environ.get("RANK")
    if v not in (None, ""):
        return int(v)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Number of processes in the job (reference env.py:24-31)."""
    v = os.environ.get("WORLD_SIZE")
    if v not in (None, ""):
        return int(v)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def get_local_rank() -> int:
    return _int_env("LOCAL_RANK", 0)


def get_local_size() -> int:
    return _int_env("LOCAL_WORLD_SIZE", 1)


def get_node_rank() -> int:
    return _int_env("NODE_RANK", get_rank() // max(get_local_size(), 1))


def get_master_addr() -> str:
    return os.environ.get("MASTER_ADDR", "127.0.0.1")


# ---- named accessors (one per consumer call site family) -----------------


def get_default_bucket_size() -> int:
    """Default bucket size in bytes; 10MB like the reference (env.py:50-57)."""
    return env_int("BAGUA_DEFAULT_BUCKET_SIZE")


def get_overlap_mode() -> str:
    """Overlap-scheduler dispatch gate: ``auto`` (default — the path that
    measured faster, see BENCH_OVERLAP.json), ``on``, or ``off`` (the exact
    serialized step construction)."""
    return env_enum("BAGUA_OVERLAP")


def get_overlap_chunk_bytes() -> int:
    """Target per-rank bytes of one independent ring sub-collective under
    the overlap scheduler; 0 (default) keeps the fused XLA collectives."""
    return env_int("BAGUA_OVERLAP_CHUNK_BYTES")


def get_overlap_chunk_bytes_intra() -> int:
    """Per-tier ring chunk target for the slice-local ICI stages of the
    hierarchical two-level collectives; 0 (default) falls back to
    :func:`get_overlap_chunk_bytes`."""
    return env_int("BAGUA_OVERLAP_CHUNK_BYTES_INTRA")


def get_overlap_chunk_bytes_inter() -> int:
    """Per-tier ring chunk target for the cross-slice DCN stage of the
    hierarchical two-level collectives; 0 (default) falls back to
    :func:`get_overlap_chunk_bytes`."""
    return env_int("BAGUA_OVERLAP_CHUNK_BYTES_INTER")


def get_compress_intra() -> str:
    """Per-link codec policy for the ICI tier / flat single-axis ring
    (``auto`` default — full precision; validation lives in
    :func:`bagua_tpu.compression.codecs.validate_codec_policy`)."""
    return env_str("BAGUA_COMPRESS_INTRA")


def get_compress_inter() -> str:
    """Per-link codec policy for the cross-slice DCN tier (``auto``
    default — defer to the algorithm family's wire codec)."""
    return env_str("BAGUA_COMPRESS_INTER")


def get_topk_ratio() -> float:
    """Fraction of each chunk's elements the ``topk`` ring codec keeps on
    the wire (default 0.01).  Read each time the codec is resolved
    (``get_codec`` re-constructs env-tuned codecs) and keyed into the
    step cache — the compiled payload shapes follow the knob."""
    return env_float("BAGUA_TOPK_RATIO")


def is_ef_residual_disabled() -> bool:
    """True when ``BAGUA_EF_RESIDUAL=off`` — the stateful codecs ride
    statelessly (biased; the BENCH_COMPRESS honesty control)."""
    return env_enum("BAGUA_EF_RESIDUAL") == "off"


def get_flat_resident_mode() -> str:
    """Flat-resident training state: ``auto`` (default — engage wherever
    the algorithm family supports it on a pure-dp mesh), ``on``, or
    ``off`` (the leaf pytree layout)."""
    return env_enum("BAGUA_FLAT_RESIDENT")


def get_max_exchange_period() -> int:
    return env_int("BAGUA_MAX_EXCHANGE_PERIOD")


def get_max_ring_chunks() -> int:
    return env_int("BAGUA_MAX_RING_CHUNKS")


def get_coordinator_addr() -> Optional[str]:
    return _raw("BAGUA_COORDINATOR_ADDR")


def get_comm_timeout_s() -> Optional[float]:
    """Hang-watchdog timeout in seconds, or None when disabled — the
    registry-backed accessor behind
    :func:`bagua_tpu.watchdog.get_comm_timeout_s`."""
    return env_seconds_or_off("BAGUA_COMM_TIMEOUT_S")


def get_lockdep_mode() -> str:
    """Runtime lockdep witness: ``off`` (default) or ``on``.  Read once at
    package import (the shim must wrap locks as they are created), so it
    can only be set in the environment, never flipped at runtime."""
    return env_enum("BAGUA_LOCKDEP")


def get_lockdep_out() -> str:
    """Lockdep witness JSON output path ("" = the default
    ``./bagua_lockdep_witness.json``)."""
    return env_str("BAGUA_LOCKDEP_OUT")


def get_grad_guard_mode() -> str:
    """Gradient-health sentinel policy: ``off`` (default), ``warn``,
    ``skip`` (rewind unhealthy steps), or ``abort``."""
    return env_enum("BAGUA_GRAD_GUARD")


def get_fault_plan_raw() -> Optional[str]:
    """Raw JSON fault-injection plan (None when unset); parsing lives in
    :mod:`bagua_tpu.faults.inject`."""
    return _raw("BAGUA_FAULT_PLAN")


def get_async_max_staleness() -> int:
    """Bounded-staleness cap for async model averaging (0 = unbounded)."""
    return env_int("BAGUA_ASYNC_MAX_STALENESS")


def get_bagua_service_port() -> int:
    return env_int("BAGUA_SERVICE_PORT")


def get_autotune_level() -> int:
    return env_int("BAGUA_AUTOTUNE")


def get_autotune_max_samples() -> int:
    return env_int("BAGUA_AUTOTUNE_MAX_SAMPLES")


def get_autotune_sampling_confidence_time_s() -> float:
    return env_float("BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S")


def get_autotune_warmup_time_s() -> float:
    return env_float("BAGUA_AUTOTUNE_WARMUP_TIME_S")


def is_autotune_algorithm_on() -> bool:
    """Let the autotuner search over algorithm families too (TPU extension;
    BASELINE.json wants centralized/low-precision selectable)."""
    return env_bool("BAGUA_AUTOTUNE_ALGORITHM")


def get_autotune_goodput() -> bool:
    """Whether check-ins carry windowed goodput/MFU/DCN observations (the
    v2 fleet-min-goodput score input; needs the obs plane on to matter)."""
    return env_bool("BAGUA_AUTOTUNE_GOODPUT")


def get_autotune_space() -> str:
    """'auto' (capability-gated v2 knob space) or 'legacy' (two-knob)."""
    v = env_str("BAGUA_AUTOTUNE_SPACE").strip().lower()
    return v if v in ("auto", "legacy") else "auto"


def is_report_metrics_switch_on() -> bool:
    return env_bool("BAGUA_REPORT_METRICS")


def is_output_autotune_log() -> bool:
    return env_bool("BAGUA_IS_OUTPUT_AUTOTUNE_LOG")


def get_autotune_server_addr() -> Optional[str]:
    return os.environ.get("AUTO_TUNE_SERVER_ADDR") or None


def get_profile_dir() -> Optional[str]:
    return _raw("BAGUA_PROFILE_DIR")


def get_profile_steps_raw() -> str:
    """Raw ``start:stop`` window; parsing (and the fallback on malformed
    values) lives in :func:`bagua_tpu.profiling.profile_steps`."""
    return env_str("BAGUA_PROFILE_STEPS")


def is_flash_attention_enabled() -> bool:
    return env_bool("BAGUA_FLASH_ATTENTION")


def is_pallas_codec_disabled() -> bool:
    return env_bool("BAGUA_DISABLE_PALLAS_CODEC")


def get_elastic_join_window_s() -> float:
    return env_float("BAGUA_ELASTIC_JOIN_WINDOW_S")


def get_elastic_lease_ttl_s() -> float:
    return env_float("BAGUA_ELASTIC_LEASE_TTL_S")


def get_elastic_telemetry_out() -> Optional[str]:
    return _raw("BAGUA_ELASTIC_TELEMETRY_OUT")


def get_elastic_health_file() -> Optional[str]:
    """This worker's health beacon path (launcher-injected, per local
    rank); None disables the worker->launcher health channel."""
    return _raw("BAGUA_ELASTIC_HEALTH_FILE")


def get_elastic_fence_unhealthy() -> int:
    """Health-fence threshold (0 = fencing disabled)."""
    return env_int("BAGUA_ELASTIC_FENCE_UNHEALTHY")


def get_obs_mode() -> str:
    """Observability-plane master switch: ``on`` (default) or ``off`` (the
    exact pre-obs host behavior; the compiled step is identical either
    way)."""
    return env_enum("BAGUA_OBS")


def get_obs_ring_size() -> int:
    return env_int("BAGUA_OBS_RING")


def get_obs_dump_dir() -> Optional[str]:
    """Flight-recorder dump directory; None disables the recorder."""
    return _raw("BAGUA_OBS_DUMP_DIR")


def get_obs_export_dir() -> Optional[str]:
    """Metrics-exporter output directory; None disables the exporter."""
    return _raw("BAGUA_OBS_EXPORT_DIR")


def get_obs_export_interval_s() -> float:
    return env_float("BAGUA_OBS_EXPORT_INTERVAL_S")


def get_obs_export_max_bytes() -> int:
    """metrics.jsonl rotation cap in bytes (0 = unbounded)."""
    return env_int("BAGUA_OBS_EXPORT_MAX_BYTES")


def get_obs_fleet_out() -> Optional[str]:
    """Coordinator-side fleet snapshot path; None disables."""
    return _raw("BAGUA_OBS_FLEET_OUT")


def get_obs_anomaly_mode() -> str:
    """Step-time anomaly detector switch: ``on`` (default) or ``off``;
    also off whenever the obs plane itself is off."""
    return env_enum("BAGUA_OBS_ANOMALY")


def get_obs_anomaly_window() -> int:
    return env_int("BAGUA_OBS_ANOMALY_WINDOW")


def get_obs_anomaly_warmup() -> int:
    return env_int("BAGUA_OBS_ANOMALY_WARMUP")


def get_obs_anomaly_threshold() -> float:
    return env_float("BAGUA_OBS_ANOMALY_THRESHOLD")


def get_obs_dump_max_files() -> int:
    """Flight-dump retention cap (0 = unbounded)."""
    return env_int("BAGUA_OBS_DUMP_MAX_FILES")


def get_obs_http_port() -> int:
    """HTTP status-plane port (0 = server disabled)."""
    return env_int("BAGUA_OBS_HTTP_PORT")


def get_obs_http_addr() -> str:
    """HTTP status-plane bind address (default loopback)."""
    return env_str("BAGUA_OBS_HTTP_ADDR")


def is_obs_historian_on() -> bool:
    """Whether the coordinator-side telemetry historian is enabled."""
    return env_enum("BAGUA_OBS_HISTORIAN") == "on"


def get_obs_historian_capacity() -> int:
    """Samples retained per (rank, metric) historian ring."""
    return env_int("BAGUA_OBS_HISTORIAN_CAPACITY")


def get_obs_historian_window_s() -> float:
    """Trend window (seconds) for historian slope/percentile queries."""
    return env_float("BAGUA_OBS_HISTORIAN_WINDOW_S")


def get_serve_max_slots() -> int:
    """Batch slots of the continuous-batching serving engine."""
    return env_int("BAGUA_SERVE_MAX_SLOTS")


def get_serve_page_size() -> int:
    """Tokens per KV-cache page of the serving page pool."""
    return env_int("BAGUA_SERVE_PAGE_SIZE")


def get_serve_num_pages() -> int:
    """Page-pool capacity per layer (0 = auto-size to max_slots
    full-length sequences)."""
    return env_int("BAGUA_SERVE_NUM_PAGES")


def get_serve_queue_depth() -> int:
    """Admission-queue depth of the serving engine."""
    return env_int("BAGUA_SERVE_QUEUE_DEPTH")


def get_serve_prefill_chunk() -> int:
    """Prompt tokens per chunked-prefill call (1 disables chunking)."""
    return env_int("BAGUA_SERVE_PREFILL_CHUNK")


def get_serve_tick_idle_s() -> float:
    """Scheduler idle-poll granularity in seconds."""
    return env_float("BAGUA_SERVE_TICK_IDLE_S")


def get_autopilot_mode() -> str:
    """Fleet-autopilot mode: ``off`` (default — no engine), ``observe``
    (decide + flight-record, never actuate), or ``act``."""
    return env_enum("BAGUA_AUTOPILOT")


def get_autopilot_slo_goodput() -> float:
    """Goodput-fraction SLO for the escalation ladder (0 = rule off)."""
    return env_float("BAGUA_AUTOPILOT_SLO_GOODPUT")


def get_autopilot_sustain() -> int:
    """Consecutive snapshots a rule must hold before acting."""
    return env_int("BAGUA_AUTOPILOT_SUSTAIN")


def get_autopilot_cooldown_s() -> float:
    """Per-action-kind cooldown in seconds."""
    return env_float("BAGUA_AUTOPILOT_COOLDOWN_S")


def get_autopilot_budget() -> int:
    """Global autopilot action budget per run."""
    return env_int("BAGUA_AUTOPILOT_BUDGET")


def get_autopilot_staleness_s() -> float:
    """Fleet-snapshot freshness bound in seconds."""
    return env_float("BAGUA_AUTOPILOT_STALENESS_S")


def get_autopilot_straggler_ratio() -> float:
    """Minimum suspect ratio feeding the straggler/victim streaks."""
    return env_float("BAGUA_AUTOPILOT_STRAGGLER_RATIO")


def get_autopilot_suspect_ttl_s() -> float:
    """Straggler-suspect evidence time-to-live in seconds."""
    return env_float("BAGUA_AUTOPILOT_SUSPECT_TTL_S")


def get_autopilot_ckpt_failures() -> int:
    """Checkpoint-integrity event threshold for storage quarantine."""
    return env_int("BAGUA_AUTOPILOT_CKPT_FAILURES")


def get_autopilot_family() -> str:
    """Algorithm family the ladder's switch rung commands."""
    return env_str("BAGUA_AUTOPILOT_FAMILY")


def get_autopilot_model() -> str:
    """Autotune task (model_name) autopilot hints address."""
    return env_str("BAGUA_AUTOPILOT_MODEL")


def get_autopilot_dcn_share() -> float:
    """DCN-dominance share threshold for the trend rule (0 = off)."""
    return env_float("BAGUA_AUTOPILOT_DCN_SHARE")


def get_autopilot_compress_family() -> str:
    """Compression family the DCN-dominance hint names."""
    return env_str("BAGUA_AUTOPILOT_COMPRESS_FAMILY")


def get_autopilot_compress_codec() -> str:
    """DCN wire codec the compress_dcn hint actuates through autotune."""
    return env_str("BAGUA_AUTOPILOT_COMPRESS_CODEC")


def get_autopilot_hbm_horizon_s() -> float:
    """Pre-OOM projection horizon for the HBM trend rule (0 = off)."""
    return env_float("BAGUA_AUTOPILOT_HBM_HORIZON_S")


def get_ckpt_quarantined_paths() -> list:
    """Checkpoint directories under storage quarantine (possibly []).
    Newline-separated: ``os.pathsep`` is ``:`` on POSIX and would split
    ``gs://``-style URI directories apart."""
    raw = _raw("BAGUA_CKPT_QUARANTINED_PATHS")
    if not raw:
        return []
    return [p.strip() for p in raw.splitlines() if p.strip()]


def get_scale_ranks() -> list:
    """World sizes the scale drill sweeps, parsed to ints (bad entries
    raise — a silently skipped size would fake coverage)."""
    return [int(p) for p in env_str("BAGUA_SCALE_RANKS").split(",")
            if p.strip()]


def get_scale_shape() -> str:
    """Raw link-shape selector (preset name or JSON); parsing lives in
    :func:`bagua_tpu.podsim.shaping.resolve_shape`."""
    return env_str("BAGUA_SCALE_SHAPE")


def get_scale_seed() -> int:
    return env_int("BAGUA_SCALE_SEED")


def get_scale_dcn_codec() -> str:
    """Wire codec for the pod simulator's cross-slice DCN ring (numpy
    mirror; default ``minmax_uint8``)."""
    return env_str("BAGUA_SCALE_DCN_CODEC")


def get_elastic_store_addr() -> Optional[str]:
    return _raw("BAGUA_ELASTIC_STORE_ADDR")


def get_restart_store_endpoints() -> List[str]:
    """Priority-ordered ``host:port`` endpoints of the replicated restart
    store; empty list = single-store mode (no replication, no failover)."""
    raw = _raw("BAGUA_RESTART_STORE_ENDPOINTS") or ""
    return [part.strip() for part in raw.split(",") if part.strip()]


def get_restart_store_op_deadline_s() -> float:
    return env_float("BAGUA_RESTART_STORE_OP_DEADLINE_S")


def get_restart_coord_lease_ttl_s() -> float:
    return env_float("BAGUA_RESTART_COORD_LEASE_TTL_S")


def get_restart_takeover_grace_s() -> float:
    """Post-takeover lease re-arm grace; 0 = auto (2x the member lease
    TTL, resolved by the caller who knows the effective TTL)."""
    return env_float("BAGUA_RESTART_TAKEOVER_GRACE_S")


def get_elastic_epoch() -> int:
    return env_int("BAGUA_ELASTIC_EPOCH")


def get_elastic_node_id() -> int:
    return env_int("BAGUA_ELASTIC_NODE_ID")


#: env vars that register remote-accelerator PJRT plugins via sitecustomize;
#: a registered plugin initializes on ``jax.devices()`` regardless of
#: JAX_PLATFORMS and hangs every process when its transport is wedged
ACCELERATOR_PLUGIN_ENV_VARS = ("PALLAS_AXON_POOL_IPS",)


def sanitize_cpu_sim_env(env: dict) -> dict:
    """Strip accelerator-plugin triggers from a CPU-simulation child's env
    (launcher ``--simulate_cpu_devices``, test harnesses, dryruns)."""
    for var in ACCELERATOR_PLUGIN_ENV_VARS:
        env.pop(var, None)
    return env


def render_env_vars_md() -> str:
    """The ``docs/env_vars.md`` reference table, emitted straight from
    :data:`ENV_REGISTRY` (``scripts/gen_env_docs.py`` writes/checks it)."""
    lines = [
        "# Environment variables",
        "",
        "Generated by `scripts/gen_env_docs.py` from "
        "`bagua_tpu.env.ENV_REGISTRY` — do not edit by hand.",
        "",
        "Every `BAGUA_*` tunable is declared in the registry and read through",
        "`bagua_tpu.env` accessors; `bagua-lint`'s `raw-env-read` rule fails",
        "CI on any ad-hoc `os.environ` read of a `BAGUA_*` name elsewhere.",
        "",
        "| Variable | Type | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for name in sorted(ENV_REGISTRY):
        v = ENV_REGISTRY[name]
        typ = v.type if not v.choices else "|".join(v.choices)
        default = v.default if v.default != "" else "*(unset)*"
        doc = " ".join(v.doc.split())
        lines.append(f"| `{name}` | {typ} | `{default}` | {doc} |")
    return "\n".join(lines) + "\n"
