"""Environment / flag accessors.

TPU-native counterpart of the reference's ``bagua/torch_api/env.py`` (see
/root/reference/bagua/torch_api/env.py:1-101).  The reference reads
``RANK``/``WORLD_SIZE``/``LOCAL_RANK``/... injected by its launcher; under JAX the
process-level topology comes from :mod:`jax` itself (``jax.process_index`` /
``jax.device_count``), while in-program data-parallel "ranks" are positions on a
:class:`jax.sharding.Mesh` axis.  The ``BAGUA_*`` tunables keep their reference
names so launcher scripts port over unchanged.
"""

from __future__ import annotations

import os


def _int_env(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(v) if v not in (None, "") else default


def get_rank() -> int:
    """Global process rank (multi-host: one JAX process per host)."""
    v = os.environ.get("RANK")
    if v not in (None, ""):
        return int(v)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    """Number of processes in the job (reference env.py:24-31)."""
    v = os.environ.get("WORLD_SIZE")
    if v not in (None, ""):
        return int(v)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def get_local_rank() -> int:
    return _int_env("LOCAL_RANK", 0)


def get_local_size() -> int:
    return _int_env("LOCAL_WORLD_SIZE", 1)


def get_node_rank() -> int:
    return _int_env("NODE_RANK", get_rank() // max(get_local_size(), 1))


def get_master_addr() -> str:
    return os.environ.get("MASTER_ADDR", "127.0.0.1")


def get_default_bucket_size() -> int:
    """Default bucket size in bytes; 10MB like the reference (env.py:50-57)."""
    return _int_env("BAGUA_DEFAULT_BUCKET_SIZE", 10 * 1024 ** 2)


def get_overlap_mode() -> str:
    """Overlap-scheduler dispatch gate: ``auto`` (default — the path that
    measured faster, see BENCH_OVERLAP.json), ``on``, or ``off`` (the exact
    serialized step construction)."""
    v = os.environ.get("BAGUA_OVERLAP", "auto").strip().lower() or "auto"
    if v not in ("auto", "on", "off"):
        raise ValueError(f"BAGUA_OVERLAP must be auto|on|off, got {v!r}")
    return v


def get_overlap_chunk_bytes() -> int:
    """Target per-rank bytes of one independent ring sub-collective under
    the overlap scheduler; 0 (default) keeps the fused XLA collectives."""
    return _int_env("BAGUA_OVERLAP_CHUNK_BYTES", 0)


def get_bagua_service_port() -> int:
    return _int_env("BAGUA_SERVICE_PORT", -1)


def get_autotune_level() -> int:
    return _int_env("BAGUA_AUTOTUNE", 0)


def get_autotune_max_samples() -> int:
    return _int_env("BAGUA_AUTOTUNE_MAX_SAMPLES", 60)


def get_autotune_sampling_confidence_time_s() -> float:
    return float(os.environ.get("BAGUA_AUTOTUNE_SAMPLING_CONFIDENCE_TIME_S", 5.0))


def get_autotune_warmup_time_s() -> float:
    return float(os.environ.get("BAGUA_AUTOTUNE_WARMUP_TIME_S", 30.0))


def is_autotune_algorithm_on() -> bool:
    """Let the autotuner search over algorithm families too (TPU extension;
    BASELINE.json wants centralized/low-precision selectable)."""
    return _int_env("BAGUA_AUTOTUNE_ALGORITHM", 0) == 1


def is_report_metrics_switch_on() -> bool:
    return _int_env("BAGUA_REPORT_METRICS", 0) == 1


def is_output_autotune_log() -> bool:
    return _int_env("BAGUA_IS_OUTPUT_AUTOTUNE_LOG", 0) == 1


def get_autotune_server_addr() -> str | None:
    return os.environ.get("AUTO_TUNE_SERVER_ADDR") or None


#: env vars that register remote-accelerator PJRT plugins via sitecustomize;
#: a registered plugin initializes on ``jax.devices()`` regardless of
#: JAX_PLATFORMS and hangs every process when its transport is wedged
ACCELERATOR_PLUGIN_ENV_VARS = ("PALLAS_AXON_POOL_IPS",)


def sanitize_cpu_sim_env(env: dict) -> dict:
    """Strip accelerator-plugin triggers from a CPU-simulation child's env
    (launcher ``--simulate_cpu_devices``, test harnesses, dryruns)."""
    for var in ACCELERATOR_PLUGIN_ENV_VARS:
        env.pop(var, None)
    return env
