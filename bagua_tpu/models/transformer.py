"""Decoder-only Transformer LM — the flagship model for benchmarks and the
driver's compile checks.

The reference's headline language workload is BERT-Large SQuAD finetuning
(/root/reference/examples/squad/main.py); this is the equivalent first-class
transformer family, designed TPU-first rather than ported:

- all matmuls in bfloat16 (MXU-native), params kept in f32,
- static shapes and a static causal mask (XLA tiles cleanly onto the MXU),
- head/ffn dims kept at multiples of 128 (MXU lane width),
- optional ``jax.checkpoint`` over blocks to trade FLOPs for HBM,
- attention pluggable so the sequence-parallel paths (ring attention /
  Ulysses all-to-all, SURVEY.md §5.7) drop in without touching the model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


from ..parallel.mesh import axis_bound as _axis_bound


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 1024
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    #: rematerialization policy when ``remat`` is on: None = recompute the
    #: whole block (lowest memory), "dots" = save every matmul output,
    #: "dots_no_batch" = save matmul outputs without batch dims.  Saving
    #: dots skips recomputing the projections/FFN in the backward at
    #: ~b*s*d_ff bytes per layer of extra HBM — measured +5.7% tokens/s on
    #: the seq-4096 LM on v5e (100.0k -> 105.7k, "dots_no_batch")
    remat_policy: Optional[str] = None
    #: sequence-parallel mesh axis: when set and bound (inside shard_map),
    #: each shard holds a contiguous sequence chunk and position embeddings
    #: are offset by axis_index * local_len
    sp_axis: Optional[str] = None
    #: tensor-parallel mesh axis (Megatron-style): attention heads and FFN
    #: width are sharded tp_size ways; params are LOCAL slices inside the
    #: step (see parallel/tensor_parallel.py).  n_heads and d_ff must be
    #: divisible by tp_size.
    tp_axis: Optional[str] = None
    tp_size: int = 1
    #: autoregressive decode mode: attention keeps a KV cache ("cache"
    #: variable collection) and consumes one token per call.  Only valid
    #: through models/generate.py — a decode=True config cannot train
    #: (single-token attention, mutable cache).
    decode: bool = False
    #: paged KV-cache decode (the serving plane, docs/serving.md): instead
    #: of one dense ``[b, max_seq_len, h, d]`` cache per layer, each layer
    #: keeps a shared **page pool** ``[num_pages, page_size, h, d]`` and
    #: requests map positions onto pool pages through a per-slot block
    #: table passed via the ``slots`` call argument — requests of different
    #: lengths share the pool while the compiled program stays one static
    #: shape.  ``page_size`` must divide ``max_seq_len``; 0 keeps the dense
    #: decode cache.  Only meaningful with ``decode=True``.
    page_size: int = 0
    #: page-pool capacity (pages per layer) for paged decode.  Pages 0 and
    #: 1 are reserved by convention: page 0 is the permanent ZERO page
    #: (unallocated block-table entries gather zeros, exactly like the
    #: dense cache's untouched rows) and page 1 is the TRASH page
    #: (masked writes of inactive slots land there) — the serving
    #: allocator never hands either out.
    num_pages: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def bert_large_config(**kw) -> TransformerConfig:
    """BERT-Large-scale shapes (the reference's SQuAD workload scale).
    Keyword overrides (e.g. ``max_seq_len=384`` for SQuAD) replace defaults."""
    defaults = dict(
        vocab_size=30528, d_model=1024, n_heads=16, n_layers=24, d_ff=4096,
        max_seq_len=512,
    )
    defaults.update(kw)
    return TransformerConfig(**defaults)


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.initializers.ones, (x.shape[-1],), self.param_dtype
        )
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + 1e-6)
        return (y * scale).astype(self.dtype)


def causal_attention(q, k, v, dtype):
    """Causal attention; softmax in f32, matmuls in ``dtype``.

    ``q/k/v``: [batch, seq, heads, head_dim].  The SP paths (ring/Ulysses)
    provide drop-in replacements with the same signature.

    On TPU with block-aligned sequence lengths this dispatches to the fused
    Pallas flash-attention kernel (:mod:`bagua_tpu.ops.flash_attention`),
    which never materializes the [seq, seq] score matrix; elsewhere it runs
    the plain jnp form (identical math).  ``BAGUA_FLASH_ATTENTION=0``
    disables the kernel.
    """
    from ..ops.flash_attention import flash_attention

    return flash_attention(q, k, v, dtype, causal=True)


#: reserved page ids of the paged decode pool (see
#: ``TransformerConfig.num_pages``): ZERO_PAGE is never written (gathers as
#: zeros for unallocated block-table entries), TRASH_PAGE absorbs the
#: masked writes of inactive slots
ZERO_PAGE = 0
TRASH_PAGE = 1
RESERVED_PAGES = 2


def _tp_active(cfg) -> bool:
    return (
        cfg.tp_axis is not None and cfg.tp_size > 1
        and _axis_bound(cfg.tp_axis)
    )


class Attention(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x, slots=None):
        cfg = self.cfg
        assert cfg.n_heads % cfg.tp_size == 0, (cfg.n_heads, cfg.tp_size)
        h, d = cfg.n_heads // cfg.tp_size, cfg.head_dim  # local heads
        if _tp_active(cfg):
            from ..parallel.tensor_parallel import tp_gather_grad

            x = tp_gather_grad(x, cfg.tp_axis)
        dense = lambda name: nn.DenseGeneral(
            (h, d), axis=-1, name=name, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, use_bias=False,
        )
        q, k, v = dense("q")(x), dense("k")(x), dense("v")(x)
        if cfg.decode and cfg.page_size > 0:
            o = self._paged_decode_attend(q, k, v, slots)
        elif cfg.decode:
            o = self._decode_attend(q, k, v)
        else:
            fn = self.attn_fn or causal_attention
            o = fn(q, k, v, cfg.dtype)
        out = nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), name="o", dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, use_bias=False,
        )(o)
        if _tp_active(cfg):
            from ..parallel.tensor_parallel import tp_reduce

            out = tp_reduce(out, cfg.tp_axis)  # row-parallel partial sums
        return out

    def _decode_attend(self, q, k, v):
        """Single-token attention against a KV cache ("cache" collection;
        flax's canonical decode pattern).  ``q/k/v`` are ``[b, 1, h, d]``;
        new K/V land at ``cache_index`` and q attends to positions
        ``<= cache_index``."""
        cfg = self.cfg
        b, qlen, h, d = q.shape
        assert qlen == 1, f"decode consumes one token per call, got {qlen}"
        # flax's canonical guard: the init pass also runs this code, and
        # must NOT advance the cache it is creating
        is_initialized = self.has_variable("cache", "cached_key")
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (b, cfg.max_seq_len, h, d), cfg.dtype,
        )
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (b, cfg.max_seq_len, h, d), cfg.dtype,
        )
        cache_index = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if not is_initialized:
            return v  # init trace: single token attends only to itself
        idx = cache_index.value
        cached_k.value = jax.lax.dynamic_update_slice(
            cached_k.value, k.astype(cfg.dtype), (0, idx, 0, 0)
        )
        cached_v.value = jax.lax.dynamic_update_slice(
            cached_v.value, v.astype(cfg.dtype), (0, idx, 0, 0)
        )
        cache_index.value = idx + 1
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, cached_k.value,
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(d).astype(jnp.float32)
        mask = jnp.arange(cfg.max_seq_len) <= idx  # [k]
        scores = jnp.where(mask[None, None, None, :], scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, cached_v.value)

    def _paged_decode_attend(self, q, k, v, slots):
        """Attention against this layer's **page pool** (the serving
        plane's paged KV-cache).  ``q/k/v`` are ``[b, s, h, d]`` where b is
        the engine's slot count and s is 1 (a decode tick) or the static
        prefill chunk; ``slots`` carries the shared per-slot state the
        scheduler maintains host-side:

        * ``block_table`` int32 ``[b, max_seq_len // page_size]`` — page id
          of each logical page of each slot (unallocated entries point at
          the reserved ZERO page),
        * ``lengths`` int32 ``[b]`` — tokens already cached per slot (the
          positions this call writes are ``lengths .. lengths + s - 1``),
        * ``active`` bool ``[b]`` — inactive slots' writes are routed to
          the reserved TRASH page (their outputs are garbage the engine
          ignores).

        The gather reconstructs, per slot, exactly the dense
        ``[b, max_seq_len, h, d]`` cache `_decode_attend` would hold
        (pages in position order, unallocated rows zero), and the
        score/mask/softmax/value math is the same expression — so greedy
        decode through the pool is bit-identical to the dense path
        (pinned in ``tests/test_serve.py``)."""
        cfg = self.cfg
        b, s, h, d = q.shape
        assert cfg.page_size > 0 and cfg.max_seq_len % cfg.page_size == 0, (
            cfg.page_size, cfg.max_seq_len)
        assert cfg.num_pages > RESERVED_PAGES, cfg.num_pages
        pages_per_slot = cfg.max_seq_len // cfg.page_size
        is_initialized = self.has_variable("cache", "pool_key")
        pool_k = self.variable(
            "cache", "pool_key", jnp.zeros,
            (cfg.num_pages, cfg.page_size, h, d), cfg.dtype,
        )
        pool_v = self.variable(
            "cache", "pool_value", jnp.zeros,
            (cfg.num_pages, cfg.page_size, h, d), cfg.dtype,
        )
        if not is_initialized:
            return v  # init trace: single token attends only to itself
        if slots is None:
            raise ValueError(
                "paged decode (page_size > 0) needs the `slots` call "
                "argument (block_table / lengths / active)"
            )
        lengths = slots["lengths"]          # [b]
        block_table = slots["block_table"]  # [b, pages_per_slot]
        active = slots["active"]            # [b]
        # destination (page, offset) of each written position; inactive
        # slots write to the trash page so the pool stays clean
        positions = lengths[:, None] + jnp.arange(s)[None, :]   # [b, s]
        dest_page = jnp.take_along_axis(
            block_table, positions // cfg.page_size, axis=1
        )                                                       # [b, s]
        dest_page = jnp.where(active[:, None], dest_page, TRASH_PAGE)
        offsets = positions % cfg.page_size
        pool_k.value = pool_k.value.at[dest_page, offsets].set(
            k.astype(cfg.dtype))
        pool_v.value = pool_v.value.at[dest_page, offsets].set(
            v.astype(cfg.dtype))
        # gather each slot's pages back into position order: elementwise
        # equal to the dense cache (zero page rows = untouched zeros)
        def view(pool):  # [b, max_seq_len, h, d]
            return pool[block_table].reshape(b, cfg.max_seq_len, h, d)

        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, view(pool_k.value),
            preferred_element_type=jnp.float32,
        ) / jnp.sqrt(d).astype(jnp.float32)
        # causal per slot: position lengths+i attends to keys <= lengths+i
        mask = jnp.arange(cfg.max_seq_len)[None, None, :] <= positions[:, :, None]
        scores = jnp.where(mask[:, None, :, :], scores, -1e30)
        weights = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, view(pool_v.value))


class MLPBlock(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        assert cfg.d_ff % cfg.tp_size == 0, (cfg.d_ff, cfg.tp_size)
        d_ff = cfg.d_ff // cfg.tp_size                   # local width
        if _tp_active(cfg):
            from ..parallel.tensor_parallel import tp_gather_grad

            x = tp_gather_grad(x, cfg.tp_axis)
        gate = nn.Dense(d_ff, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="wi_gate")(x)
        up = nn.Dense(d_ff, use_bias=False, dtype=cfg.dtype,
                      param_dtype=cfg.param_dtype, name="wi_up")(x)
        y = nn.silu(gate) * up
        out = nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype, name="wo")(y)
        if _tp_active(cfg):
            from ..parallel.tensor_parallel import tp_reduce

            out = tp_reduce(out, cfg.tp_axis)
        return out


class Block(nn.Module):
    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None
    mlp: Optional[Callable[[], nn.Module]] = None  # MoE drops in here

    @nn.compact
    def __call__(self, x, slots=None):
        cfg = self.cfg
        y = RMSNorm(cfg.dtype, cfg.param_dtype, name="attn_norm")(x)
        attn = Attention(cfg, self.attn_fn, name="attn")
        # dense/training call sites keep their exact one-arg form (the
        # goldens pin those programs); only paged decode threads slots
        x = x + (attn(y) if slots is None else attn(y, slots))
        y = RMSNorm(cfg.dtype, cfg.param_dtype, name="mlp_norm")(x)
        mlp = self.mlp() if self.mlp is not None else MLPBlock(cfg, name="mlp")
        x = x + mlp(y)
        return x


class TransformerLM(nn.Module):
    """Causal LM: token ids [batch, seq] -> logits [batch, seq, vocab]."""

    cfg: TransformerConfig
    attn_fn: Optional[Callable] = None
    mlp_factory: Optional[Callable[[int], Optional[Callable]]] = None
    head: bool = True  # False: return final hidden states (encoder trunk)

    @nn.compact
    def __call__(self, tokens, slots=None):
        cfg = self.cfg
        if slots is not None and not (cfg.decode and cfg.page_size > 0):
            raise ValueError(
                "`slots` is only meaningful for paged decode configs "
                "(decode=True, page_size > 0)"
            )
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, name="embed",
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
        )(tokens)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (cfg.max_seq_len, cfg.d_model), cfg.param_dtype,
        )
        s = tokens.shape[1]
        if cfg.decode and cfg.page_size > 0:
            # paged decode: every slot sits at its OWN position (continuous
            # batching admits requests mid-flight), so the position comes
            # from the scheduler's per-slot lengths, not a shared counter.
            # During init (no slots yet) position 0 stands in.
            if slots is None:
                pos_ids = jnp.zeros((tokens.shape[0], s), jnp.int32)
            else:
                pos_ids = (slots["lengths"][:, None]
                           + jnp.arange(s, dtype=jnp.int32)[None, :])
            # pos[idx] equals the dense path's dynamic_slice row for the
            # same position — elementwise identical, per slot
            pos_slice = jnp.take(pos, pos_ids, axis=0)  # [b, s, d_model]
            x = x + pos_slice.astype(cfg.dtype)
        else:
            start = 0
            if cfg.sp_axis is not None and _axis_bound(cfg.sp_axis):
                start = jax.lax.axis_index(cfg.sp_axis) * s
            if cfg.decode:
                # autoregressive position counter (mirrors the attention
                # cache; same init-pass guard — see Attention._decode_attend)
                advance = self.has_variable("cache", "pos_index")
                pos_index = self.variable(
                    "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
                )
                if advance:
                    start = pos_index.value
                    pos_index.value = start + s
            pos_slice = jax.lax.dynamic_slice_in_dim(pos, start, s, axis=0)
            x = x + pos_slice[None].astype(cfg.dtype)
        if cfg.remat:
            from ..utils import remat_wrap

            block_cls = remat_wrap(Block, cfg.remat_policy)
        else:
            block_cls = Block
        for i in range(cfg.n_layers):
            mlp = self.mlp_factory(i) if self.mlp_factory is not None else None
            blk = block_cls(cfg, self.attn_fn, mlp, name=f"block_{i}")
            x = blk(x) if slots is None else blk(x, slots)
        x = RMSNorm(cfg.dtype, cfg.param_dtype, name="final_norm")(x)
        if not self.head:
            return x.astype(jnp.float32)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="lm_head",
        )(x)
        return logits.astype(jnp.float32)


#: dotted-name suffix -> (sharded dim of the GLOBAL kernel, contracting
#: dims) for the trainer's tp leaf sharding and the global-init redraw
#: (column-parallel kernels shard an output feature dim; row-parallel
#: kernels shard a contracting dim)
_TP_DIMS = {
    # q/k/v: [d_model, heads, head_dim] — shard heads, contract d_model
    "attn.q.kernel": (1, (0,)),
    "attn.k.kernel": (1, (0,)),
    "attn.v.kernel": (1, (0,)),
    # o: [heads, head_dim, d_model] — shard heads, contract heads*head_dim
    "attn.o.kernel": (0, (0, 1)),
    # wi: [d_model, d_ff] — shard d_ff, contract d_model
    "mlp.wi_gate.kernel": (1, (0,)),
    "mlp.wi_up.kernel": (1, (0,)),
    # wo: [d_ff, d_model] — shard d_ff, contract d_ff
    "mlp.wo.kernel": (0, (0,)),
}


def tp_param_dim(name: str):
    """Sharded dim for a TP param of :class:`TransformerLM` (None: dense)."""
    for suffix, (dim, _) in _TP_DIMS.items():
        if name.endswith(suffix):
            return dim
    return None


def tp_param_fan_in_dims(name: str):
    """Contracting dims of a TP kernel's GLOBAL shape (for init redraw)."""
    for suffix, (_, fan_in) in _TP_DIMS.items():
        if name.endswith(suffix):
            return fan_in
    return None


def lm_loss_fn(model: TransformerLM):
    """Next-token cross-entropy; batch = dict(tokens=[b, s+1])."""

    def loss_fn(params, batch):
        import optax

        tokens = batch["tokens"]
        logits = model.apply({"params": params}, tokens[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tokens[:, 1:]
        ).mean()

    return loss_fn


def sp_lm_loss_fn(model: TransformerLM, sp_size: int, sp_axis: str = "sp"):
    """Sequence-parallel next-token loss.

    ``batch['tokens']`` is the FULL [batch, seq_global+1] array, replicated
    over the sp axis; each shard slices its contiguous chunk, runs the model
    on local positions, and computes the loss for its targets.  The trainer's
    loss allreduce (over dp × sp) averages the shard means, which equals the
    global mean because chunks are equal-sized.
    """

    def loss_fn(params, batch):
        import optax

        tokens = batch["tokens"]
        seq_global = tokens.shape[1] - 1
        assert seq_global % sp_size == 0, (seq_global, sp_size)
        s_local = seq_global // sp_size
        start = jax.lax.axis_index(sp_axis) * s_local
        inputs = jax.lax.dynamic_slice_in_dim(tokens, start, s_local, axis=1)
        targets = jax.lax.dynamic_slice_in_dim(tokens, start + 1, s_local, axis=1)
        logits = model.apply({"params": params}, inputs)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    return loss_fn
