"""VGG family — the reference's headline end-to-end benchmark model.

Bagua's flagship published number is VGG16 synthetic-ImageNet throughput
(/root/reference/rust/bagua-net/README.md:65-81: 126.5 img/s per V100 with
bagua-net, 85.8 baseline; README.md:21-26 is the 128-GPU VGG16 scaling
chart; the autotune sysperf probe also trains VGG16,
/root/reference/bagua/service/autotune_system.py).  TPU-first rendering:
bfloat16 convs on the MXU, NHWC layout, f32 params, static shapes; the
classifier head keeps the original two 4096-wide dense layers — on TPU
those are the cheap part (dense matmuls), the conv stack is the work.
Classifier dropout is intentionally omitted: the trainer's loss contract is
rng-free and the synthetic throughput workload (the reference's benchmark
use of VGG16) measures step time, not generalization.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# filters per conv, "M" = 2x2 max-pool (the standard configuration tables)
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")
_VGG19_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    cfg: Sequence = _VGG16_CFG
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    hidden: int = 4096

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                       dtype=self.dtype, param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        i = 0
        for c in self.cfg:
            if c == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.relu(conv(c, name=f"conv{i}")(x))
                i += 1
        x = x.reshape(x.shape[0], -1)
        dense = partial(nn.Dense, dtype=self.dtype, param_dtype=jnp.float32)
        x = nn.relu(dense(self.hidden, name="fc1")(x))
        x = nn.relu(dense(self.hidden, name="fc2")(x))
        return dense(self.num_classes, dtype=jnp.float32, name="head")(x)


VGG16 = partial(VGG, cfg=_VGG16_CFG)
VGG19 = partial(VGG, cfg=_VGG19_CFG)


def vgg_loss_fn(model):
    """Softmax cross-entropy over integer labels (no batch-norm state)."""
    import optax

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["images"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()

    return loss_fn
