"""ResNet family for the synthetic throughput benchmark.

The reference's CI benchmark trains ResNet50 on synthetic ImageNet-shaped
batches and gates on img/s per device
(/root/reference/.buildkite/scripts/benchmark_master.sh:83-98,
examples/benchmark/synthetic_benchmark.py).  This is the TPU-first
equivalent: bfloat16 convs (MXU), f32 params and batch-norm statistics,
NHWC layout (TPU-native), static shapes.

Batch-norm *applies* in bfloat16 by default (``norm_dtype``): the training
step is HBM-bandwidth-bound on TPU, and an f32 norm forces every activation
tensor through an f32 round-trip between bf16 convs — measured 25% of
ResNet50 step time on v5e.  Flax's ``BatchNorm`` still computes the batch
statistics in f32 internally (``_compute_stats`` promotes), and running
stats live in ``param_dtype`` f32, so only the normalize/scale/shift
arithmetic drops to bf16.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="proj_conv"
            )(residual)
            residual = self.norm(name="proj_norm")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    norm_dtype: Any = jnp.bfloat16  # f32 restores the conservative pre-norm cast
    norm_cls: Any = None  # override with SyncBatchNorm for cross-chip stats
    #: rematerialize each bottleneck block in the backward pass.  Measured
    #: on v5e (BENCH_RESNET_SWEEP.json r5): a LOSS for ResNet50 throughput
    #: — conv recompute re-reads activations/weights, ADDING HBM traffic
    #: (28.1 -> 33.0 GB/step at batch 128) for -18% img/s — so it stays
    #: off by default; use it only when activation memory, not speed, is
    #: the binding constraint (it admits batch 512 on one chip).
    remat: bool = False
    #: ``None`` recomputes everything inside a block; ``"dots"`` keeps
    #: dot/conv results (jax.checkpoint_policies.dots_saveable does not
    #: cover conv_general, so on this conv trunk it approximates full
    #: recompute — kept for API symmetry with TransformerConfig).
    remat_policy: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm_base = self.norm_cls or nn.BatchNorm
        norm = partial(
            norm_base, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.norm_dtype, param_dtype=jnp.float32,
        )
        block_cls = BottleneckBlock
        if self.remat:
            from ..utils import remat_wrap

            block_cls = remat_wrap(BottleneckBlock, self.remat_policy)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="stem_conv")(x)
        x = norm(name="stem_norm")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    self.num_filters * 2 ** i, strides, conv, norm,
                    name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3))
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3))


def classification_loss_fn(model, batch_stats=None):
    """Softmax cross-entropy over integer labels.

    Only the ``params`` collection is trainable/communicated; batch-norm
    running statistics are closed over as a frozen constant (train-mode BN
    normalizes with per-batch statistics, so they never affect the loss —
    matching the reference's synthetic benchmark, which never evals).  Carrying
    live running stats across steps is the SyncBatchNorm contrib path.
    """
    import optax

    def loss_fn(params, batch):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
            logits, _ = model.apply(
                variables, batch["images"], train=True, mutable=["batch_stats"]
            )
        else:
            logits = model.apply(variables, batch["images"], train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["labels"]
        ).mean()

    return loss_fn
