from .mlp import MLP  # noqa: F401
from .vgg import VGG, VGG16, VGG19, vgg_loss_fn  # noqa: F401
