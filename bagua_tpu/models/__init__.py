from .mlp import MLP  # noqa: F401
