"""Autoregressive generation with a KV cache (inference path).

Additive — the reference is a training accelerator with no serving story;
a complete LM framework needs one.  TPU-idiomatic formulation: the whole
generation loop is ONE jitted program — a ``lax.scan`` over decode steps,
each consuming one token against the flax ``"cache"`` collection that
:class:`~bagua_tpu.models.transformer.TransformerLM` maintains in decode
mode (``TransformerConfig(decode=True)``).  Static shapes throughout: the
cache is pre-allocated at ``max_seq_len`` and the scan length is
``prompt_len + max_new_tokens - 1``, so one compile serves a fixed
(batch, prompt_len, max_new) signature.

Sampling: greedy at ``temperature=0`` (exact continuation of the argmax
chain), else temperature-scaled categorical.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["decode_model", "generate", "generate_tp",
           "clear_generate_cache", "clear_tp_generate_cache"]


def decode_model(model):
    """The decode-mode twin of a ``TransformerLM`` (same params, KV-cached
    single-token attention; ``attn_fn`` is unused in decode)."""
    cfg = dataclasses.replace(model.cfg, decode=True)
    return type(model)(cfg, attn_fn=None, mlp_factory=model.mlp_factory,
                       head=model.head)


def _generate_core(model, params, prompt, max_new_tokens, rng, temperature):
    b, prompt_len = prompt.shape
    cache = model.init(
        jax.random.PRNGKey(0), jnp.zeros((b, 1), jnp.int32)
    )["cache"]

    def step(carry, inputs):
        cache, feed = carry  # feed: [b] token consumed this step
        key, forced, forced_tok = inputs
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            feed[:, None], mutable=["cache"],
        )
        logits = logits[:, 0]  # [b, vocab]
        sampled = jnp.where(
            temperature > 0.0,
            jax.random.categorical(key, logits / jnp.maximum(temperature, 1e-6)),
            jnp.argmax(logits, axis=-1),
        ).astype(prompt.dtype)
        # while still inside the prompt, the "next token" is forced
        nxt = jnp.where(forced, forced_tok, sampled)
        return (mutated["cache"], nxt), nxt

    n_steps = prompt_len + max_new_tokens - 1
    keys = jax.random.split(rng, n_steps)
    # step i feeds token i; for i < prompt_len - 1 the output is forced to
    # prompt[i + 1] (teacher forcing through the prompt)
    forced = jnp.arange(n_steps) < (prompt_len - 1)
    forced_tok = jnp.concatenate(
        [prompt[:, 1:], jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1
    ).T  # [n_steps, b]
    (_, _), toks = jax.lax.scan(
        step, (cache, prompt[:, 0]), (keys, forced, forced_tok),
    )
    # toks[i] = token fed at step i+1; the generated continuation is the
    # last max_new_tokens of them
    return toks[prompt_len - 1:].T  # [b, max_new_tokens]


# Bounded LRU of compiled decode programs, keyed by the generate signature
# (model config, batch, prompt_len, max_new_tokens) — the same discipline
# as the tp cache below: long-lived serving processes that vary batch
# shapes or budgets must not accumulate executables forever, and a bare
# `jax.jit` module global could never free them.  Evictions just recompile.
_GEN_CACHE_MAX = 8
_GEN_CACHE: "dict" = {}  # insertion-ordered; move-to-end on hit

# Same policy for the tensor-parallel decode programs (these additionally
# pin their mesh/device objects).  8 distinct (model, mesh, budget,
# sharding) signatures cover realistic serving; evictions just recompile.
_TP_GEN_CACHE_MAX = 8
_TP_GEN_CACHE: "dict" = {}  # insertion-ordered; move-to-end on hit


def clear_generate_cache() -> None:
    """Drop every compiled single-host decode program (frees the
    executables); the next :func:`generate` call recompiles."""
    _GEN_CACHE.clear()


def clear_tp_generate_cache() -> None:
    """Drop every compiled tensor-parallel decode program (frees the
    executables and releases their mesh references)."""
    _TP_GEN_CACHE.clear()


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
        model: a ``TransformerLM`` in decode mode (``decode_model(m)``), or
            a training-mode model (converted automatically).
        params: the trained params (training and decode modes share them).
        prompt: int32 ``[batch, prompt_len]``, ``prompt_len >= 1``;
            ``prompt_len + max_new_tokens`` must fit ``cfg.max_seq_len``.
        temperature: 0 = greedy, else categorical at the given temperature.
        rng: PRNG key (required only for temperature > 0).

    Returns:
        int32 ``[batch, max_new_tokens]``.
    """
    if not model.cfg.decode:
        model = decode_model(model)
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # memoized per (model, batch, prompt_len, max_new) signature, exactly
    # like generate_tp: repeated calls reuse the compiled scan instead of
    # re-dispatching through a fresh trace, and the LRU bounds the
    # executables a long-lived serving process can accumulate
    from ..utils import lru_get_or_build

    n = int(max_new_tokens)

    def build():
        def run(params, prompt, rng, temperature, _model=model, _n=n):
            return _generate_core(_model, params, prompt, _n, rng,
                                  temperature)

        return jax.jit(run)

    fn = lru_get_or_build(_GEN_CACHE, _GEN_CACHE_MAX,
                          (model, b, prompt_len, n), build)
    return fn(params, prompt, rng, jnp.float32(temperature))


def generate_tp(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    mesh,
    tp_axis: str = "tp",
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
    tp_param_dim=None,
):
    """Tensor-parallel generation: the decode loop runs under ``shard_map``
    over ``tp_axis``, with attention heads / FFN width sharded exactly as in
    training (the model's conjugate collectives reduce the per-shard
    partials, so logits — and therefore samples — are identical on every
    shard).  ``params`` are the GLOBAL arrays (as held by a
    ``BaguaTrainer(tp_axis=...)`` state); ``tp_param_dim`` maps param name →
    sharded dim (default: the transformer family's table).

    ``mesh`` may carry extra (replication) axes besides ``tp_axis`` — on
    the CPU-simulation platform prefer a mesh spanning ALL devices (e.g.
    ``build_mesh({"rep": 4, "tp": 2})``): XLA's in-process communicator can
    wedge on collectives over a device subset after full-device work ran
    in the same process.
    """
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ..tensor import _name_of_path

    if not model.cfg.decode:
        model = decode_model(model)
    if model.cfg.tp_axis != tp_axis or model.cfg.tp_size <= 1:
        raise ValueError(
            f"model config must carry tp_axis={tp_axis!r} with tp_size > 1 "
            f"(got tp_axis={model.cfg.tp_axis!r}, tp_size={model.cfg.tp_size})"
        )
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if mesh.shape[tp_axis] != model.cfg.tp_size:
        raise ValueError(
            f"mesh axis {tp_axis!r} has size {mesh.shape[tp_axis]} but the "
            f"model config says tp_size={model.cfg.tp_size}"
        )
    if tp_param_dim is None:
        from .transformer import tp_param_dim as _default_dim

        tp_param_dim = _default_dim

    def leaf_spec(path, leaf):
        d = tp_param_dim(_name_of_path(path))
        return P() if d is None else P(*([None] * d + [tp_axis]))

    pspecs = jax.tree_util.tree_map_with_path(leaf_spec, params)
    # params may live on a different (e.g. training dp) mesh — lay them out
    # on THIS mesh with their tp shardings before entering the jit
    from jax.sharding import NamedSharding

    params = jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params, pspecs,
    )
    replicated = NamedSharding(mesh, P())
    prompt = jax.device_put(prompt, replicated)
    rng = jax.device_put(rng, replicated)
    n = int(max_new_tokens)

    # one compiled fn per (model, mesh, axis, budget, param structure) —
    # rebuilding jit(shard_map(...)) per call would re-trace the whole
    # decode scan every request (the _EAGER_CACHE lesson, communication.py)
    # key includes the spec VALUES, not just the tree structure — a custom
    # tp_param_dim mapping the same params to different dims must recompile
    from ..utils import lru_get_or_build

    flat_specs, spec_tree = jax.tree_util.tree_flatten(pspecs)

    def build():
        def per_shard(p, toks, key, temp):
            return _generate_core(model, p, toks, n, key, temp)

        return jax.jit(shard_map(
            per_shard, mesh=mesh, in_specs=(pspecs, P(), P(), P()),
            out_specs=P(), check_vma=False,
        ))

    fn = lru_get_or_build(
        _TP_GEN_CACHE, _TP_GEN_CACHE_MAX,
        (model, mesh, tp_axis, n, spec_tree, tuple(flat_specs)), build,
    )
    return fn(params, prompt, rng, jnp.float32(temperature))
