"""Autoregressive generation with a KV cache (inference path).

Additive — the reference is a training accelerator with no serving story;
a complete LM framework needs one.  TPU-idiomatic formulation: the whole
generation loop is ONE jitted program — a ``lax.scan`` over decode steps,
each consuming one token against the flax ``"cache"`` collection that
:class:`~bagua_tpu.models.transformer.TransformerLM` maintains in decode
mode (``TransformerConfig(decode=True)``).  Static shapes throughout: the
cache is pre-allocated at ``max_seq_len`` and the scan length is
``prompt_len + max_new_tokens - 1``, so one compile serves a fixed
(batch, prompt_len, max_new) signature.

Sampling: greedy at ``temperature=0`` (exact continuation of the argmax
chain), else temperature-scaled categorical.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["decode_model", "generate"]


def decode_model(model):
    """The decode-mode twin of a ``TransformerLM`` (same params, KV-cached
    single-token attention; ``attn_fn`` is unused in decode)."""
    cfg = dataclasses.replace(model.cfg, decode=True)
    return type(model)(cfg, attn_fn=None, mlp_factory=model.mlp_factory,
                       head=model.head)


@partial(jax.jit, static_argnums=(0, 3))
def _generate_jit(model, params, prompt, max_new_tokens, rng, temperature):
    b, prompt_len = prompt.shape
    cache = model.init(
        jax.random.PRNGKey(0), jnp.zeros((b, 1), jnp.int32)
    )["cache"]

    def step(carry, inputs):
        cache, feed = carry  # feed: [b] token consumed this step
        key, forced, forced_tok = inputs
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            feed[:, None], mutable=["cache"],
        )
        logits = logits[:, 0]  # [b, vocab]
        sampled = jnp.where(
            temperature > 0.0,
            jax.random.categorical(key, logits / jnp.maximum(temperature, 1e-6)),
            jnp.argmax(logits, axis=-1),
        ).astype(prompt.dtype)
        # while still inside the prompt, the "next token" is forced
        nxt = jnp.where(forced, forced_tok, sampled)
        return (mutated["cache"], nxt), nxt

    n_steps = prompt_len + max_new_tokens - 1
    keys = jax.random.split(rng, n_steps)
    # step i feeds token i; for i < prompt_len - 1 the output is forced to
    # prompt[i + 1] (teacher forcing through the prompt)
    forced = jnp.arange(n_steps) < (prompt_len - 1)
    forced_tok = jnp.concatenate(
        [prompt[:, 1:], jnp.zeros((b, max_new_tokens), prompt.dtype)], axis=1
    ).T  # [n_steps, b]
    (_, _), toks = jax.lax.scan(
        step, (cache, prompt[:, 0]), (keys, forced, forced_tok),
    )
    # toks[i] = token fed at step i+1; the generated continuation is the
    # last max_new_tokens of them
    return toks[prompt_len - 1:].T  # [b, max_new_tokens]


def generate(
    model,
    params,
    prompt: jax.Array,
    max_new_tokens: int,
    temperature: float = 0.0,
    rng: Optional[jax.Array] = None,
):
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
        model: a ``TransformerLM`` in decode mode (``decode_model(m)``), or
            a training-mode model (converted automatically).
        params: the trained params (training and decode modes share them).
        prompt: int32 ``[batch, prompt_len]``, ``prompt_len >= 1``;
            ``prompt_len + max_new_tokens`` must fit ``cfg.max_seq_len``.
        temperature: 0 = greedy, else categorical at the given temperature.
        rng: PRNG key (required only for temperature > 0).

    Returns:
        int32 ``[batch, max_new_tokens]``.
    """
    if not model.cfg.decode:
        model = decode_model(model)
    b, prompt_len = prompt.shape
    total = prompt_len + max_new_tokens
    if total > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} + max_new_tokens {max_new_tokens} "
            f"exceeds max_seq_len {model.cfg.max_seq_len}"
        )
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_jit(model, params, prompt, int(max_new_tokens), rng,
                         jnp.float32(temperature))
