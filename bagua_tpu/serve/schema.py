"""BENCH_SERVE.json schema (``bagua-bench-serve-v1``).

The serving bench's committed artifact is a list of records (the
``BENCH_*`` house style): a schema header, TTFT/TPOT percentile records
from a Poisson-paced trace, the continuous-vs-static throughput A/B on the
``benchmarks/_ab.py`` honesty protocol (per-trial ratio spread +
``noise_bound`` flag), and the serving goodput-ledger breakdown proving
the serving classes were *fed*.  :func:`validate_serve_bench` is shared by
the producer (``benchmarks/serve_bench.py`` refuses to write an invalid
record), the CI smoke stage, and the ``tests/test_bench_sanity.py`` gate.

Import-light (no jax): the CI stage validates artifacts without paying a
device bring-up.
"""

from __future__ import annotations

from typing import Dict, List

__all__ = ["SERVE_BENCH_SCHEMA", "SERVE_SPEEDUP_GATE",
           "validate_serve_bench"]

SERVE_BENCH_SCHEMA = "bagua-bench-serve-v1"

#: the acceptance ratio: continuous batching must hold at least this many
#: times static batching's token throughput on the mixed-length trace
#: (or the record must honestly flag the comparison noise-bound)
SERVE_SPEEDUP_GATE = 1.3

_PCTS = ("p50", "p90", "p99")


def _by_metric(records) -> Dict[str, dict]:
    return {r.get("metric"): r for r in records if isinstance(r, dict)}


def validate_serve_bench(records) -> List[str]:
    """Schema problems with a BENCH_SERVE.json record list ([] = valid)."""
    problems: List[str] = []
    if not isinstance(records, list) or not records:
        return ["not a non-empty JSON list"]
    by = _by_metric(records)

    header = by.get("serve_bench_schema")
    if not isinstance(header, dict):
        return ["missing serve_bench_schema header record"]
    if header.get("schema") != SERVE_BENCH_SCHEMA:
        problems.append(f"schema != {SERVE_BENCH_SCHEMA}")
    for key in ("time_unix", "platform", "n_devices", "config", "trace"):
        if key not in header:
            problems.append(f"header missing {key}")
    cfg = header.get("config") or {}
    for key in ("max_slots", "page_size", "num_pages", "prefill_chunk"):
        if not isinstance(cfg.get(key), int):
            problems.append(f"header.config missing/mistyped {key}")

    lat = by.get("serve_latency")
    if not isinstance(lat, dict):
        problems.append("missing serve_latency record")
    else:
        for field in ("ttft_s", "tpot_s"):
            pct = lat.get(field)
            if not isinstance(pct, dict):
                problems.append(f"serve_latency.{field} missing")
                continue
            for p in _PCTS:
                v = pct.get(p)
                if not isinstance(v, (int, float)) or v < 0:
                    problems.append(f"serve_latency.{field}.{p} "
                                    "missing/negative")
        if not isinstance(lat.get("n_requests"), int) \
                or lat.get("n_requests", 0) < 1:
            problems.append("serve_latency.n_requests missing")

    for side in ("serve_continuous_tokens_per_sec",
                 "serve_static_tokens_per_sec"):
        rec = by.get(side)
        if not isinstance(rec, dict):
            problems.append(f"missing {side} record")
            continue
        if not isinstance(rec.get("value"), (int, float)) \
                or rec["value"] <= 0:
            problems.append(f"{side}.value missing/nonpositive")
        if "interleaved_ab" not in str(rec.get("timing", "")):
            problems.append(f"{side} not measured under the interleaved "
                            "A/B protocol")

    sp = by.get("serve_continuous_over_static_throughput")
    if not isinstance(sp, dict):
        problems.append("missing serve_continuous_over_static_throughput")
    else:
        ratios = sp.get("per_trial_ratios")
        if not isinstance(ratios, list) or len(ratios) < 3:
            problems.append("speedup per_trial_ratios missing/too few")
        if not isinstance(sp.get("noise_bound"), bool):
            problems.append("speedup noise_bound missing")
        if not isinstance(sp.get("value"), (int, float)) \
                or sp.get("value", 0) <= 0:
            problems.append("speedup value missing/nonpositive")
        if sp.get("gate") != SERVE_SPEEDUP_GATE:
            problems.append(f"speedup gate != {SERVE_SPEEDUP_GATE}")
        if not sp.get("provenance"):
            problems.append("speedup missing provenance (cpu-sim honesty "
                            "note)")
        # the acceptance criterion itself, noise-bound-honest: a value
        # below the gate is only admissible when the trial spread says the
        # host could not resolve the comparison.  COMMITTED (full-trace)
        # records only — the CI smoke trace (fewer requests, 3 trials on
        # a loaded host) is a shape check, not an acceptance measurement;
        # the committed artifact's gate lives in test_bench_sanity.py
        if not header.get("smoke") \
                and isinstance(sp.get("value"), (int, float)) \
                and sp["value"] < SERVE_SPEEDUP_GATE \
                and not sp.get("noise_bound"):
            problems.append(
                f"continuous/static throughput {sp['value']} below the "
                f"{SERVE_SPEEDUP_GATE}x gate without a noise_bound flag"
            )

    led = by.get("serve_ledger_classes")
    if not isinstance(led, dict):
        problems.append("missing serve_ledger_classes record")
    else:
        classes = led.get("classes") or {}
        for cls in ("prefill", "decode", "weight_load"):
            v = classes.get(cls)
            if not isinstance(v, (int, float)) or v <= 0:
                problems.append(f"serving ledger class `{cls}` not fed")
        gf = led.get("goodput_fraction")
        if not isinstance(gf, (int, float)) or not (0.0 < gf <= 1.0):
            problems.append("serve_ledger_classes.goodput_fraction "
                            "missing/out of range")
    return problems
