"""Continuous-batching inference engine (the serving plane's scheduler).

The training side of this framework ends at checkpoints; this engine is
what makes the trained artifact *serve* — the Orca (OSDI '22) iteration-
level scheduling idea expressed TPU-first:

* **One jitted program, static shapes.**  Every scheduler tick runs the
  same compiled decode step over ``max_slots`` batch slots: an int32 feed
  token, a block table, a lengths vector, and an active mask.  Requests
  **join mid-batch** (a free slot + a block-table row) and **evict on
  finish** (mask off, pages reclaimed) without a recompile — the
  continuous-batching unlock, since a static-batched engine would hold
  every slot hostage to the batch's longest request.
* **Paged KV-cache.**  KV state lives in per-layer page pools
  (:mod:`bagua_tpu.serve.cache`); slots map positions onto pool pages
  through their block-table rows, so requests of different lengths share
  one pre-allocated flat pool — the bucket-flat residency idea applied to
  serving memory.  Pool exhaustion backpressures (queue, then preempt the
  youngest slot for recompute) — it never crashes.
* **Prefill that does not stall decode.**  Prompts stream through the
  same tick at one token per slot per tick (exactly ``generate()``'s
  teacher forcing), so a long prompt never blocks running decodes; with
  ``prefill_chunk > 1`` a second compiled program additionally consumes
  whole prompt chunks for one slot between ticks — at most one chunk call
  per tick, bounding the latency it can add to in-flight decodes.
* **Bit-identical decode.**  Greedy output for any request — including
  requests that joined mid-batch or were preempted and recomputed — is
  bit-identical to ``models.generate.generate()`` on the same prompt
  (pinned in ``tests/test_serve.py``): the paged attend reconstructs the
  dense cache's math exactly, page pool or not.
* **Serving observability.**  Request-level spans
  (``serve/admit|prefill|decode|detokenize``), ``serve/*`` counters in
  the metric registry, and the goodput ledger's serving classes
  (``prefill``/``decode`` are serving goodput; ``batch_formation_idle``
  and ``weight_load`` are badput with a name), so ``goodput_fraction``
  means something for a serving replica.

Greedy decoding only (temperature sampling would make per-request
reproducibility depend on slot placement; the training-side ``generate``
keeps the sampling path).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from .. import env as _env
from ..obs.spans import trace_span
from ..telemetry import counters
from .cache import PagePool, SlotTable

__all__ = ["ServeConfig", "Request", "ServeQueueFull", "ServeEngine",
           "clear_serve_program_cache"]


class ServeQueueFull(RuntimeError):
    """The admission queue is at ``queue_depth`` — the caller should shed
    or retry; admission backpressure is explicit, never an OOM."""


# Bounded LRU of compiled (tick, chunk) program pairs keyed by the engine
# signature — the models/generate.py discipline: engines come and go
# (replica restarts, A/B baselines, tests) but the decode program depends
# only on (model config, max_slots, prefill_chunk), so rebuilding an
# engine must not re-pay the trace+compile.
_PROGRAM_CACHE_MAX = 4
_PROGRAM_CACHE: dict = {}  # insertion-ordered; move-to-end on hit


def clear_serve_program_cache() -> None:
    """Drop every compiled serving program (frees the executables)."""
    _PROGRAM_CACHE.clear()


@dataclasses.dataclass
class Request:
    """One generation request and (after completion) its result."""

    rid: int
    prompt: np.ndarray          # int32 [prompt_len]
    max_new_tokens: int
    #: filled by the engine
    output: List[int] = dataclasses.field(default_factory=list)
    t_submit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_done: Optional[float] = None
    preemptions: int = 0

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def tpot_s(self) -> Optional[float]:
        """Time per output token after the first (None for 1-token
        outputs)."""
        if self.t_first_token is None or self.t_done is None:
            return None
        n = len(self.output)
        if n <= 1:
            return None
        return (self.t_done - self.t_first_token) / (n - 1)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Engine knobs; defaults come from the ``BAGUA_SERVE_*`` registry
    rows (docs/env_vars.md)."""

    max_slots: int
    page_size: int
    num_pages: int          # total pool pages incl. the 2 reserved
    queue_depth: int
    prefill_chunk: int      # 1 disables the chunked-prefill program
    tick_idle_s: float      # idle poll granularity while awaiting arrivals

    @staticmethod
    def from_env(max_seq_len: int, **overrides) -> "ServeConfig":
        from ..models.transformer import RESERVED_PAGES

        kw = dict(
            max_slots=_env.get_serve_max_slots(),
            page_size=_env.get_serve_page_size(),
            num_pages=_env.get_serve_num_pages(),
            queue_depth=_env.get_serve_queue_depth(),
            prefill_chunk=_env.get_serve_prefill_chunk(),
            tick_idle_s=_env.get_serve_tick_idle_s(),
        )
        kw.update(overrides)
        if kw["num_pages"] <= 0:
            # auto: enough for every slot to reach max_seq_len — no
            # preemption pressure; size it down explicitly to oversubscribe
            kw["num_pages"] = (RESERVED_PAGES + kw["max_slots"]
                               * (max_seq_len // kw["page_size"]))
        return ServeConfig(**kw)


class ServeEngine:
    """Continuous-batching engine over a ``TransformerLM`` + trained params.

    ``model`` may be a training-mode or decode-mode model; the engine
    derives its own paged decode twin.  ``continuous=False`` switches to
    the static-batching baseline (admission only into an EMPTY batch,
    which then runs to full completion) — the A/B the serving bench
    measures against.
    """

    def __init__(self, model, params, config: Optional[ServeConfig] = None,
                 continuous: bool = True):
        import jax
        import jax.numpy as jnp

        from ..models.transformer import RESERVED_PAGES

        cfg = model.cfg
        self.config = config or ServeConfig.from_env(cfg.max_seq_len)
        c = self.config
        if cfg.max_seq_len % c.page_size:
            raise ValueError(
                f"page_size {c.page_size} must divide max_seq_len "
                f"{cfg.max_seq_len}"
            )
        pages_per_slot = cfg.max_seq_len // c.page_size
        if c.num_pages - RESERVED_PAGES < pages_per_slot:
            raise ValueError(
                f"num_pages {c.num_pages} cannot hold one full-length "
                f"request ({pages_per_slot} pages + {RESERVED_PAGES} "
                "reserved) — the engine could never complete it"
            )
        serve_cfg = dataclasses.replace(
            cfg, decode=True, page_size=int(c.page_size),
            num_pages=int(c.num_pages),
        )
        self.model = type(model)(
            serve_cfg, attn_fn=None,
            mlp_factory=getattr(model, "mlp_factory", None),
            head=getattr(model, "head", True),
        )
        self.params = params
        self.continuous = bool(continuous)
        self.max_seq_len = int(cfg.max_seq_len)
        self.pool = PagePool(c.num_pages)
        self.slots = SlotTable(c.max_slots, cfg.max_seq_len, c.page_size)
        self._slot_req: List[Optional[Request]] = [None] * c.max_slots
        self._slot_pos: List[int] = [0] * c.max_slots   # prompt cursor
        self._slot_order: List[int] = []                 # admission order
        self._queue: "deque[Request]" = deque()
        self.completed: List[Request] = []
        self._next_rid = 0
        self._ticks = 0

        # the serving ledger classes ride the span tracer exactly like the
        # training classes do — install the sink once per process
        from ..obs import ledger as obs_ledger
        from ..obs import spans as obs_spans

        if obs_spans.enabled():
            obs_ledger.install()

        # compiled programs (static shapes: max_slots x 1 tick, 1 x chunk
        # prefill), shared across engines with the same signature through
        # the bounded module LRU.  Pool buffers are donated where the
        # backend honors donation (TPU); on cpu-sim donation would only
        # warn.
        donate = (1,) if jax.default_backend() != "cpu" else ()
        model = self.model  # closures must not capture self (cache sharing)
        dummy = {
            "block_table": np.zeros(
                (c.max_slots, pages_per_slot), np.int32),
            "lengths": np.zeros((c.max_slots,), np.int32),
            "active": np.zeros((c.max_slots,), bool),
        }

        def build_programs():
            def tick_fn(p, cache, feed, block_table, lengths, active):
                slots = {"block_table": block_table, "lengths": lengths,
                         "active": active}
                logits, mutated = model.apply(
                    {"params": p, "cache": cache}, feed[:, None], slots,
                    mutable=["cache"],
                )
                # exactly generate()'s greedy rule
                sampled = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
                return mutated["cache"], sampled

            chunk_fn = None
            if c.prefill_chunk > 1:
                def chunk_fn(p, cache, tokens, block_table, lengths,
                             active):
                    slots = {"block_table": block_table, "lengths": lengths,
                             "active": active}
                    logits, mutated = model.apply(
                        {"params": p, "cache": cache}, tokens, slots,
                        mutable=["cache"],
                    )
                    last = jnp.argmax(
                        logits[:, -1], axis=-1).astype(jnp.int32)
                    return mutated["cache"], last

                chunk_fn = jax.jit(chunk_fn, donate_argnums=donate)
            # abstract cache template (per-layer page pools): eval_shape
            # costs a trace, never a forward — every pool leaf is zeros
            # by construction, so engines rebuild their cache from the
            # shapes alone instead of re-running model.init
            cache_shapes = jax.eval_shape(
                lambda: model.init(
                    jax.random.PRNGKey(0),
                    jnp.zeros((c.max_slots, 1), jnp.int32), dummy,
                )["cache"]
            )
            return (jax.jit(tick_fn, donate_argnums=donate), chunk_fn,
                    cache_shapes)

        from ..utils import lru_get_or_build

        try:
            programs = lru_get_or_build(
                _PROGRAM_CACHE, _PROGRAM_CACHE_MAX,
                (model, c.max_slots, c.prefill_chunk, donate),
                build_programs,
            )
        except TypeError:  # unhashable model pieces (exotic mlp_factory)
            programs = build_programs()
        self._tick_fn, self._chunk_fn, cache_shapes = programs

        # this engine's page pools (flax "cache" collection): fresh zero
        # buffers from the cached shapes — never shared with another
        # engine (donation on TPU invalidates consumed buffers)
        self.cache = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    # -- admission ---------------------------------------------------------

    def submit(self, prompt, max_new_tokens: int,
               rid: Optional[int] = None) -> Request:
        """Queue one request; raises :class:`ServeQueueFull` at the depth
        cap (explicit backpressure, the caller sheds or retries)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if int(max_new_tokens) < 1:
            # generate(prompt, 0) returns an empty continuation; the
            # engine's finish check would emit one unrequested token
            # instead — reject rather than silently diverge
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}"
            )
        if prompt.size + int(max_new_tokens) > self.max_seq_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{max_new_tokens} exceeds max_seq_len {self.max_seq_len}"
            )
        if len(self._queue) >= self.config.queue_depth:
            counters.incr("serve/requests_rejected")
            raise ServeQueueFull(
                f"admission queue is at queue_depth="
                f"{self.config.queue_depth}"
            )
        if rid is None:
            rid = self._next_rid
        self._next_rid = max(self._next_rid, rid) + 1
        req = Request(rid=int(rid), prompt=prompt,
                      max_new_tokens=int(max_new_tokens),
                      t_submit=time.monotonic())
        self._queue.append(req)
        counters.set_gauge("serve/queue_depth", len(self._queue))
        return req

    @property
    def active_slots(self) -> int:
        return int(self.slots.active.sum())

    @property
    def idle(self) -> bool:
        return not self._queue and self.active_slots == 0

    def _admit(self) -> None:
        if not self.continuous and self.active_slots > 0:
            return  # static batching: the formed batch runs to completion
        for slot in range(self.config.max_slots):
            if not self._queue:
                break
            if self._slot_req[slot] is not None:
                continue
            if self.pool.free_pages < 1 and self.active_slots > 0:
                # no page for even the first prompt token: leave the
                # request queued rather than admit-then-thrash
                counters.incr("serve/pool_exhausted")
                break
            req = self._queue.popleft()
            self._slot_req[slot] = req
            self._slot_pos[slot] = 0
            self.slots.active[slot] = True
            self.slots.lengths[slot] = 0
            self._slot_order.append(slot)
            counters.incr("serve/requests_admitted")

    # -- paging ------------------------------------------------------------

    def _preempt_youngest(self, spare: Optional[int] = None) -> bool:
        """Free the youngest admitted slot's pages (recompute-on-resume,
        the PagedAttention recovery policy); its request rejoins the HEAD
        of the queue.  ``spare`` protects the slot currently asking for a
        page when older slots exist.  Returns False when nothing can be
        preempted."""
        order = [s for s in self._slot_order if self._slot_req[s] is not None]
        victims = [s for s in order if s != spare] or order
        if not victims:
            return False
        victim = victims[-1]
        req = self._slot_req[victim]
        self.pool.free(self.slots.release(victim))
        self._slot_req[victim] = None
        self._slot_order.remove(victim)
        req.output = []
        req.t_first_token = None
        req.preemptions += 1
        self._queue.appendleft(req)
        counters.incr("serve/requests_preempted")
        return True

    def _ensure_pages(self, slot: int, n_tokens: int) -> bool:
        """Allocate the pages ``slot`` needs for its next ``n_tokens``
        positions, preempting younger slots on exhaustion.  False when the
        slot itself was preempted to make room."""
        while self.slots.needs_page(slot, n_tokens):
            page = self.pool.alloc()
            if page is None:
                counters.incr("serve/pool_exhausted")
                self._preempt_youngest(spare=slot)
                if self._slot_req[slot] is None:
                    return False  # the slot itself was the youngest
                continue
            self.slots.map_page(slot, page)
        return True

    # -- the scheduler tick -------------------------------------------------

    def step(self) -> int:
        """One scheduler tick: admit → (chunked prefill) → decode tick →
        detokenize/evict.  Returns the number of requests completed by
        this tick."""
        with trace_span("serve/admit", queue=len(self._queue)):
            self._admit()
        done = self._maybe_chunk_prefill()
        if self.active_slots:
            sampled = self._decode_tick()
            done += self._detokenize(sampled)
        self._ticks += 1
        counters.incr("serve/ticks")
        counters.set_gauge("serve/queue_depth", len(self._queue))
        counters.set_gauge("serve/active_slots", self.active_slots)
        counters.set_gauge("serve/pages_in_use", self.pool.pages_in_use)
        return done

    def _maybe_chunk_prefill(self) -> int:
        """At most ONE chunked-prefill call per tick (a long prompt must
        not stall running decodes): pick the oldest slot with at least a
        full chunk of prompt left and consume it in one jitted call.
        Returns requests completed on this path (a chunk that consumes
        the whole prompt of a 1-token-budget request finishes it)."""
        if self._chunk_fn is None:
            return 0
        c = self.config.prefill_chunk
        for slot in list(self._slot_order):
            req = self._slot_req[slot]
            if req is None or req.prompt.size - self._slot_pos[slot] < c:
                continue
            if not self._ensure_pages(slot, c):
                continue  # preempted away; its request re-queued
            with trace_span("serve/prefill", slot=slot, chunk=c,
                            rid=req.rid):
                bt = self.slots.block_table[slot:slot + 1].copy()
                lengths = self.slots.lengths[slot:slot + 1].copy()
                active = np.ones((1,), bool)
                tokens = req.prompt[None,
                                    self._slot_pos[slot]:
                                    self._slot_pos[slot] + c]
                self.cache, last = self._chunk_fn(
                    self.params, self.cache, np.ascontiguousarray(tokens),
                    bt, lengths, active,
                )
                # block INSIDE the span: dispatch is async, so without
                # the readback here the chunk's compute wall would leak
                # into idle_other instead of the ledger's prefill class
                last = np.asarray(last)
            self._slot_pos[slot] += c
            self.slots.lengths[slot] += c
            counters.incr("serve/prefill_tokens", c)
            counters.incr("serve/prefill_chunks")
            if self._slot_pos[slot] == req.prompt.size:
                # the chunk consumed the prompt's last token: its argmax
                # is the request's first output token
                req.output.append(int(last[0]))
                counters.incr("serve/decode_tokens")
                req.t_first_token = time.monotonic()
                counters.set_gauge("serve/ttft_last_s", req.ttft_s)
                if len(req.output) >= req.max_new_tokens:
                    self._finish(slot)
                    return 1
            return 0
        return 0

    def _decode_tick(self):
        """The batched one-token tick: every active slot consumes one
        token (forced prompt token while prefilling — generate()'s teacher
        forcing — else its own last output)."""
        feed = np.zeros((self.config.max_slots,), np.int32)
        for slot in list(self._slot_order):
            req = self._slot_req[slot]
            if req is None:
                continue
            if not self._ensure_pages(slot, 1):
                continue
            if self._slot_pos[slot] < req.prompt.size:
                feed[slot] = req.prompt[self._slot_pos[slot]]
            else:
                feed[slot] = req.output[-1]
        with trace_span("serve/decode", active=self.active_slots,
                        tick=self._ticks):
            dev = self.slots.device_slots()
            self.cache, sampled = self._tick_fn(
                self.params, self.cache, feed, dev["block_table"],
                dev["lengths"], dev["active"],
            )
            # block INSIDE the span: dispatch is async, so the tick's
            # compute wall must land in the ledger's decode class here,
            # not leak into idle_other at the detokenize readback
            return np.asarray(sampled)

    def _detokenize(self, toks: np.ndarray) -> int:
        """Advance host state from the tick's (already read back) samples:
        prompt cursors, outputs, TTFT stamps, finish/evict."""
        done = 0
        with trace_span("serve/detokenize", active=self.active_slots):
            for slot in list(self._slot_order):
                req = self._slot_req[slot]
                if req is None or not self.slots.active[slot]:
                    continue
                self.slots.lengths[slot] += 1
                if self._slot_pos[slot] < req.prompt.size:
                    self._slot_pos[slot] += 1
                    counters.incr("serve/prefill_tokens")
                    if self._slot_pos[slot] < req.prompt.size:
                        continue  # still teacher-forcing the prompt
                req.output.append(int(toks[slot]))
                # every appended output token is a sampled token,
                # including a request's first (produced by the tick that
                # consumed its final prompt token)
                counters.incr("serve/decode_tokens")
                if req.t_first_token is None:
                    req.t_first_token = time.monotonic()
                    counters.set_gauge("serve/ttft_last_s", req.ttft_s)
                if len(req.output) >= req.max_new_tokens:
                    self._finish(slot)
                    done += 1
        return done

    def _finish(self, slot: int) -> None:
        req = self._slot_req[slot]
        req.t_done = time.monotonic()
        self.pool.free(self.slots.release(slot))
        self._slot_req[slot] = None
        self._slot_order.remove(slot)
        self.completed.append(req)
        counters.incr("serve/requests_completed")
        if req.tpot_s is not None:
            counters.set_gauge("serve/tpot_last_s", req.tpot_s)

    # -- driving -----------------------------------------------------------

    def run(self, timed_requests: Optional[Sequence[Tuple[float, Any, int]]]
            = None, max_ticks: Optional[int] = None) -> List[Request]:
        """Drive the engine until every queued/submitted request completes.

        ``timed_requests``: optional ``(arrival_s, prompt, max_new)``
        trace replayed in real time — the bench's Poisson arrivals.  Wall
        spent waiting for the next arrival with an empty engine is fed to
        the ledger as ``batch_formation_idle``.
        """
        from ..obs import ledger as obs_ledger

        pending = deque(sorted(timed_requests or [], key=lambda r: r[0]))
        t0 = time.monotonic()
        start_completed = len(self.completed)
        ticks = 0
        while pending or not self.idle:
            now = time.monotonic() - t0
            while pending and pending[0][0] <= now:
                if len(self._queue) >= self.config.queue_depth:
                    # queue at depth: DEFER the arrival (backpressure per
                    # the engine contract) — raising ServeQueueFull out of
                    # the replay loop would abandon the trace mid-flight
                    break
                _, prompt, max_new = pending.popleft()
                self.submit(prompt, max_new)
            if self.idle and pending:
                wait = min(pending[0][0] - now, self.config.tick_idle_s)
                if wait > 0:
                    time.sleep(wait)
                    obs_ledger.ledger.note_class_window(
                        "batch_formation_idle", wait)
                continue
            self.step()
            ticks += 1
            if max_ticks is not None and ticks >= max_ticks:
                break
        return self.completed[start_completed:]
