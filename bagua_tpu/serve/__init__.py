"""Serving plane: continuous-batching inference over trained artifacts
(docs/serving.md).

The framework's end-state story — train, observe, heal, and now *serve*:

* :mod:`~bagua_tpu.serve.cache` — the paged KV-cache's host bookkeeping:
  the page-pool allocator and the per-slot block tables over the
  per-layer page pools the transformer's paged decode mode keeps
  (``TransformerConfig(decode=True, page_size=…, num_pages=…)``).
* :mod:`~bagua_tpu.serve.engine` — the continuous-batching scheduler:
  one static-shape jitted tick, join-mid-batch / evict-on-finish without
  recompiling, chunked prefill that never stalls running decodes,
  queue-then-preempt backpressure on pool exhaustion, and greedy decode
  bit-identical to ``models.generate.generate()``.
* :mod:`~bagua_tpu.serve.loader` — integrity-verified weight loads
  through the checkpoint digest chain, with layout-sidecar-aware
  flat→serving-layout conversion.
* :mod:`~bagua_tpu.serve.schema` — the ``BENCH_SERVE.json`` schema the
  serving bench, CI smoke stage, and artifact gate share.

Observability rides the existing planes: ``serve/*`` spans and counters,
and the goodput ledger's serving classes (``prefill``/``decode`` count as
serving goodput; ``batch_formation_idle``/``weight_load`` are badput with
a name).
"""

from .cache import PagePool, SlotTable  # noqa: F401
from .engine import (  # noqa: F401
    Request,
    ServeConfig,
    ServeEngine,
    ServeQueueFull,
    clear_serve_program_cache,
)
from .loader import load_serving_params, save_serving_artifact  # noqa: F401
from .schema import (  # noqa: F401
    SERVE_BENCH_SCHEMA,
    SERVE_SPEEDUP_GATE,
    validate_serve_bench,
)
