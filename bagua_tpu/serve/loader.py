"""Integrity-verified model loads for the serving plane.

A serving replica must never decode with silently corrupted weights, so
every load rides the PR 5 checkpoint integrity chain
(:class:`bagua_tpu.checkpoint.BaguaCheckpointManager`): the content digest
recorded at save time is verified on restore, a torn sidecar or digest
mismatch disqualifies that step with a loud warning, and (when no explicit
step was requested) the load falls back newest-first to the last step that
verifies — the exact policy training resumes use.

Layout awareness: training may have checkpointed the params as
**bucket-flat buffers** (the flat-resident layout, PR 4).  The layout
sidecar records the full bucket descriptor, so the loader rebuilds the
:class:`~bagua_tpu.bucket.BucketPlan` from the sidecar alone, restores the
flat buffers with their shapes derived from the descriptor (no trainer
required in the serving process), digest-verifies them, and unflattens to
the leaf params the decode program consumes — the flat→serving-layout
conversion.  Leaf-layout checkpoints restore directly.

:func:`save_serving_artifact` is the publishing half: flatten trained leaf
params under a plan, record the descriptor + digest, and ship a directory
any replica can :func:`load_serving_params` from.
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Tuple

from ..bucket import BucketPlan
from ..checkpoint import BaguaCheckpointManager
from ..obs.spans import trace_span
from ..telemetry import counters

logger = logging.getLogger(__name__)

__all__ = ["save_serving_artifact", "load_serving_params"]


def save_serving_artifact(
    directory: str,
    params: Any,
    step: int = 0,
    bucket_bytes: Optional[int] = None,
) -> None:
    """Publish ``params`` as a serving artifact: bucket-flat buffers + the
    layout sidecar (bucket descriptor, content digest) under
    ``directory``.  The flat layout is deliberate — one contiguous buffer
    per bucket restores with large sequential reads, and the descriptor
    makes the artifact self-describing (a replica needs no trainer, no
    bucket plan, only the target model's param structure)."""
    from .. import env as _env
    from ..tensor import build_params

    named = build_params(params)
    plan = BucketPlan.build(
        named, bucket_bytes or _env.get_default_bucket_size(), alignment=1
    )
    flats = plan.flatten_tree(params)
    mgr = BaguaCheckpointManager(directory, async_save=False)
    try:
        meta = {
            "layout": "flat",
            "plan_dependent": True,
            "serving_artifact": True,
            "flat_layout": plan.layout_descriptor(),
        }
        mgr.save(int(step), {"flats": tuple(flats)}, metadata=meta)
    finally:
        mgr.close()


def _restore_with_layout(mgr: BaguaCheckpointManager, step: int,
                         params_like: Any) -> Tuple[int, Any]:
    """Restore one step into the serving (leaf) layout, converting via the
    sidecar when the on-disk layout is bucket-flat.  Raises
    ``CheckpointIntegrityError`` for corruption (the newest-first walk
    then falls back) and ``ValueError`` for genuine mismatches (a model
    whose params the artifact does not cover)."""
    import jax
    import numpy as np

    from ..tensor import leaves_by_name, tree_from_named

    sidecar = mgr.read_layout(step)  # torn sidecar -> integrity error
    if sidecar and "flat_layout" in sidecar:
        plan = BucketPlan.from_layout_descriptor(sidecar["flat_layout"])
        flats_like = {
            "flats": tuple(
                jax.ShapeDtypeStruct((b.padded_numel,), np.dtype(b.dtype))
                for b in plan.buckets
            ),
        }
        # the expectation IS the sidecar's own constraint set (the flat
        # shapes come from its descriptor), so the plan-dependent-layout
        # warning path stays quiet — a genuine mismatch still raises
        expect = {k: v for k, v in sidecar.items()
                  if k not in ("flat_layout", "integrity")}
        got_step, restored = mgr.restore(flats_like, step=step,
                                         expect_metadata=expect)
        named = plan.unflatten_to_named(restored["flats"])
        want = leaves_by_name(params_like)
        missing = sorted(set(want) - set(named))
        if missing:
            raise ValueError(
                "serving artifact does not cover the model's params "
                f"(missing {missing[:3]}{'…' if len(missing) > 3 else ''}) "
                "— wrong checkpoint for this model config?"
            )
        mismatched = sorted(
            n for n in want
            if tuple(np.shape(want[n])) != tuple(np.shape(named[n]))
        )
        if mismatched:
            raise ValueError(
                "serving artifact param shapes do not match the model "
                f"({mismatched[:3]}{'…' if len(mismatched) > 3 else ''})"
            )
        return got_step, tree_from_named(params_like, named)
    return mgr.restore(params_like, step=step)


def load_serving_params(
    directory: str,
    params_like: Any,
    step: Optional[int] = None,
) -> Tuple[int, Any]:
    """Load serving params from ``directory`` with digest verification and
    newest-first integrity fallback.

    ``params_like`` provides the target leaf structure/shapes — pass the
    model's initialized params (or ``jax.eval_shape`` of the init).  The
    load is spanned as ``serve/weight_load``, which the goodput ledger
    books under the serving ``weight_load`` class.
    """
    from ..obs import ledger as obs_ledger
    from ..obs import spans as obs_spans

    if obs_spans.enabled():
        # the load may be the process's FIRST serving act — hook the
        # ledger sink up before the span opens so weight_load is booked
        obs_ledger.install()
    with trace_span("serve/weight_load", directory=str(directory)):
        mgr = BaguaCheckpointManager(directory, async_save=False)
        try:
            if step is not None:
                result = _restore_with_layout(mgr, int(step), params_like)
            else:
                result = mgr._restore_newest_verified(
                    lambda s: _restore_with_layout(mgr, s, params_like)
                )
        finally:
            mgr.close()
    counters.incr("serve/weight_loads")
    logger.info("serving params loaded from %s at step %d", directory,
                result[0])
    return result
