"""Paged KV-cache bookkeeping: the host half of the serving plane's memory
system (docs/serving.md).

The device half lives in :mod:`bagua_tpu.models.transformer`: in paged
decode mode (``TransformerConfig(decode=True, page_size=P, num_pages=N)``)
each layer's flax ``"cache"`` collection holds a **page pool**
``[num_pages, page_size, heads, head_dim]`` instead of a dense
``[b, max_seq_len, ...]`` cache — the bucket-flat idea (one pre-allocated
flat buffer, logical tensors as offsets into it) applied to KV state, with
fixed-size pages as the allocation unit (vLLM / PagedAttention,
arXiv 2309.06180).  Requests of different lengths share the pool through
per-slot **block tables**; the compiled decode program never changes shape.

This module owns the host-side state the jitted programs consume:

* :class:`PagePool` — the free-page allocator over ``num_pages`` (pages 0
  and 1 are reserved: the permanent ZERO page unallocated block-table
  entries gather from, and the TRASH page that absorbs masked writes of
  inactive slots).  Allocation is O(1) (free list); exhaustion returns
  ``None`` — the scheduler's cue to queue or preempt, never to crash.
* :class:`SlotTable` — the per-slot block tables / lengths / active mask,
  kept as numpy on the host (the scheduler mutates them between ticks) and
  snapshotted into the device ``slots`` argument of each tick.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..models.transformer import RESERVED_PAGES, TRASH_PAGE, ZERO_PAGE

__all__ = ["PagePool", "SlotTable", "ZERO_PAGE", "TRASH_PAGE",
           "RESERVED_PAGES"]


class PagePool:
    """Free-list allocator over the paged KV-cache's page ids.

    Pure host bookkeeping — the pages' storage is the per-layer pool
    arrays inside the engine's flax cache; one allocation here stands for
    the same page id in EVERY layer's pool (the block table is shared
    across layers, so a single id allocates ``2 * n_layers`` physical
    pages' worth of KV).
    """

    def __init__(self, num_pages: int):
        if num_pages <= RESERVED_PAGES:
            raise ValueError(
                f"num_pages must exceed the {RESERVED_PAGES} reserved "
                f"pages, got {num_pages}"
            )
        self.num_pages = int(num_pages)
        # LIFO free list: recently freed pages are re-used first (their
        # pool rows are hot, and reuse exercises the stale-page masking
        # the bit-identity tests pin)
        self._free: List[int] = list(
            range(self.num_pages - 1, RESERVED_PAGES - 1, -1)
        )

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return (self.num_pages - RESERVED_PAGES) - len(self._free)

    def alloc(self) -> Optional[int]:
        """One free page id, or None when the pool is exhausted (the
        scheduler then queues the request or preempts a slot)."""
        if not self._free:
            return None
        return self._free.pop()

    def free(self, pages) -> None:
        for p in pages:
            p = int(p)
            assert RESERVED_PAGES <= p < self.num_pages, p
            assert p not in self._free, f"double free of page {p}"
            self._free.append(p)


class SlotTable:
    """Per-slot block tables / lengths / active flags (host numpy).

    ``block_table[slot]`` maps the slot's logical pages (position //
    page_size) to pool page ids; unallocated entries stay at the ZERO page
    so the device gather reads zeros there — exactly the dense cache's
    untouched rows, which is what keeps paged decode bit-identical.
    """

    def __init__(self, max_slots: int, max_seq_len: int, page_size: int):
        assert max_seq_len % page_size == 0, (max_seq_len, page_size)
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.pages_per_slot = max_seq_len // page_size
        self.block_table = np.full(
            (self.max_slots, self.pages_per_slot), ZERO_PAGE, np.int32
        )
        self.lengths = np.zeros((self.max_slots,), np.int32)
        self.active = np.zeros((self.max_slots,), bool)
        #: page ids held per slot, in allocation (position) order
        self.pages: Dict[int, List[int]] = {i: [] for i in range(max_slots)}

    def needs_page(self, slot: int, n_tokens: int = 1) -> int:
        """Pages the slot must still allocate before caching ``n_tokens``
        more tokens at its current length."""
        have = len(self.pages[slot])
        need = -(-(int(self.lengths[slot]) + n_tokens) // self.page_size)
        return max(0, need - have)

    def map_page(self, slot: int, page: int) -> None:
        idx = len(self.pages[slot])
        assert idx < self.pages_per_slot, (slot, idx)
        self.pages[slot].append(int(page))
        self.block_table[slot, idx] = int(page)

    def release(self, slot: int) -> List[int]:
        """Clear a slot (eviction / preemption); returns its pages for the
        pool to reclaim."""
        pages, self.pages[slot] = self.pages[slot], []
        self.block_table[slot, :] = ZERO_PAGE
        self.lengths[slot] = 0
        self.active[slot] = False
        return pages

    def device_slots(self) -> Dict[str, np.ndarray]:
        """The ``slots`` argument of one tick — snapshot copies, so the
        jitted call never aliases arrays the scheduler mutates next."""
        return {
            "block_table": self.block_table.copy(),
            "lengths": self.lengths.copy(),
            "active": self.active.copy(),
        }
