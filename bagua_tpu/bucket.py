"""Bucketing: partition named tensors into flat, aligned communication buffers.

Counterpart of the reference's ``BaguaBucket``
(/root/reference/bagua/torch_api/bucket.py:15-123: in-place flattening into a
contiguous buffer + padding tensor for alignment) and the autotuner's
``split_bucket_by_bucket_size`` (service/autotune_task_manager.py:86-119).

TPU-first rationale: the reference flattens so the Rust scheduler can issue one
NCCL call per bucket.  Under XLA we flatten for the same reason — one large
``psum``/``all_to_all`` per bucket beats many small ones on ICI — but the
flattening is *traced* (concat inside the jitted step, fused by XLA) instead of
aliasing storage.  Alignment padding to a multiple of the world size is what
lets the compressed scatter-gather ops split a bucket into equal per-rank
chunks (reference bytegrad.py:38-43).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .define import TensorDeclaration, TensorDtype, DTYPE_BYTES
from .tensor import NamedParam, leaves_by_name
from .utils import from_bagua_datatype


def split_bucket_by_bucket_size(
    tensor_list: List[TensorDeclaration],
    bucket_size: int,
    param_group_info: Optional[Dict[str, int]] = None,
) -> List[List[TensorDeclaration]]:
    """Greedy dtype-grouped split, mirroring the reference autotuner
    (autotune_task_manager.py:86-119): iterate dtypes in sorted order, fill a
    bucket until it reaches ``bucket_size`` bytes, then start a new one."""
    param_group_info = param_group_info or {}
    dtypes = sorted({TensorDtype(t.dtype).value for t in tensor_list})
    buckets: List[List[TensorDeclaration]] = []
    for dtype in dtypes:
        # flush at dtype boundaries: a bucket is one flat buffer of one dtype
        # (the reference's buckets are homogeneous in practice; carrying a
        # partial bucket across dtypes would silently cast gradients)
        tmp: List[TensorDeclaration] = []
        tmp_bytes = 0
        for td in [t for t in tensor_list if TensorDtype(t.dtype).value == dtype]:
            tmp_bytes += td.nbytes
            tmp.append(td)
            if tmp_bytes >= bucket_size:
                buckets.append(tmp)
                tmp, tmp_bytes = [], 0
        if tmp:
            buckets.append(tmp)
    for i in range(len(buckets)):
        buckets[i] = sorted(buckets[i], key=lambda p: param_group_info.get(p.name, -1))
    return buckets


@dataclass(frozen=True)
class BucketSpec:
    """One bucket: ordered named tensors + alignment padding (reference
    bucket.py:15-55)."""

    name: str
    tensors: Tuple[NamedParam, ...]
    alignment: int = 1

    @property
    def numel(self) -> int:
        return sum(t.numel for t in self.tensors)

    @property
    def padded_numel(self) -> int:
        n = self.numel
        if self.alignment > 1 and n % self.alignment:
            n += self.alignment - n % self.alignment
        return n

    @property
    def padding(self) -> int:
        return self.padded_numel - self.numel

    @property
    def dtype(self):
        return self.tensors[0].dtype

    def offsets(self) -> List[int]:
        offs, off = [], 0
        for t in self.tensors:
            offs.append(off)
            off += t.numel
        return offs

    def signature(self) -> Tuple:
        return (
            self.name,
            self.alignment,
            tuple((t.name, t.shape, str(t.dtype)) for t in self.tensors),
        )


@dataclass(frozen=True)
class BucketPlan:
    """A full partition of the registered tensors into buckets."""

    buckets: Tuple[BucketSpec, ...]

    def signature(self) -> Tuple:
        return tuple(b.signature() for b in self.buckets)

    @property
    def tensor_names(self) -> List[str]:
        return [t.name for b in self.buckets for t in b.tensors]

    @staticmethod
    def from_declaration_buckets(
        decl_buckets: Sequence[Sequence[TensorDeclaration]],
        named_params: Sequence[NamedParam],
        alignment: int = 1,
    ) -> "BucketPlan":
        by_name = {p.name: p for p in named_params}
        specs = []
        for i, db in enumerate(decl_buckets):
            tensors = tuple(by_name[d.name] for d in db)
            specs.append(BucketSpec(name=str(i), tensors=tensors, alignment=alignment))
        plan = BucketPlan(buckets=tuple(specs))
        missing = set(by_name) - set(plan.tensor_names)
        if missing:
            raise ValueError(f"bucket plan misses tensors: {sorted(missing)}")
        return plan

    @staticmethod
    def build(
        named_params: Sequence[NamedParam],
        bucket_bytes: int,
        alignment: int = 1,
        param_group_info: Optional[Dict[str, int]] = None,
    ) -> "BucketPlan":
        decls = [p.declaration() for p in named_params]
        decl_buckets = split_bucket_by_bucket_size(decls, bucket_bytes, param_group_info)
        return BucketPlan.from_declaration_buckets(decl_buckets, named_params, alignment)

    # ---- traced flatten/unflatten ------------------------------------

    def flatten_tree(self, tree) -> List[jax.Array]:
        """tree -> list of flat padded bucket buffers (traced; XLA fuses the
        concatenation).  Equivalent of bucket.py:95-123 ``_flatten_``."""
        named = leaves_by_name(tree)
        flats = []
        for b in self.buckets:
            parts = [jnp.ravel(named[t.name]).astype(b.dtype) for t in b.tensors]
            if b.padding:
                parts.append(jnp.zeros((b.padding,), dtype=b.dtype))
            flats.append(jnp.concatenate(parts) if len(parts) > 1 else parts[0])
        return flats

    def unflatten_to_named(self, flats: Sequence[jax.Array]) -> Dict[str, jax.Array]:
        named = {}
        for b, flat in zip(self.buckets, flats):
            for t, off in zip(b.tensors, b.offsets()):
                seg = jax.lax.slice_in_dim(flat, off, off + t.numel)
                named[t.name] = seg.reshape(t.shape).astype(t.dtype)
        return named

    def unflatten_tree(self, flats: Sequence[jax.Array], tree_like):
        from .tensor import tree_from_named

        return tree_from_named(tree_like, self.unflatten_to_named(flats))

    # ---- layout portability ------------------------------------------

    def layout_descriptor(self) -> List[dict]:
        """JSON-serializable description of the flat layout — enough to
        rebuild an equivalent plan (:meth:`from_layout_descriptor`) on a
        process that never saw the original params.  Stored in checkpoint
        layout sidecars so a flat-resident checkpoint saved under one plan
        can be re-laid-out under another on restore."""
        return [
            {
                "alignment": int(b.alignment),
                "tensors": [
                    {
                        "name": t.name,
                        "shape": [int(d) for d in t.shape],
                        "dtype": np.dtype(t.dtype).name,
                    }
                    for t in b.tensors
                ],
            }
            for b in self.buckets
        ]

    @staticmethod
    def from_layout_descriptor(desc: Sequence[dict]) -> "BucketPlan":
        """Rebuild a plan from :meth:`layout_descriptor` output.  The
        reconstructed :class:`NamedParam` entries carry empty tree paths —
        sufficient for every flat-layout operation (flatten / unflatten /
        relayout key on names, shapes, and dtypes only)."""
        specs = []
        for i, b in enumerate(desc):
            tensors = tuple(
                NamedParam(
                    name=t["name"],
                    path=(),
                    shape=tuple(int(d) for d in t["shape"]),
                    dtype=np.dtype(t["dtype"]),
                )
                for t in b["tensors"]
            )
            specs.append(
                BucketSpec(name=str(i), tensors=tensors,
                           alignment=int(b["alignment"]))
            )
        return BucketPlan(buckets=tuple(specs))


def relayout_flats(
    old_plan: BucketPlan, new_plan: BucketPlan, flats: Sequence[jax.Array]
) -> List[jax.Array]:
    """Migrate flat bucket buffers from ``old_plan``'s layout to
    ``new_plan``'s WITHOUT materializing leaf shapes: per-tensor 1-D
    segments are sliced out of the old flats and concatenated straight into
    the new ones (old padding dropped, new padding zero-filled).  This is
    the flat->flat path autotune re-bucketing and cross-plan checkpoint
    restores use to move flat-RESIDENT training state, so the per-step
    round-trip the resident layout removed never sneaks back in at
    migration points.

    Segments slice along the LAST axis, so stacked per-rank state (gossip
    families carry flats with a leading rank axis) migrates with the same
    code path.  Both plans must cover the same tensor names."""
    segments: Dict[str, jax.Array] = {}
    seg_numel: Dict[str, int] = {}
    for b, flat in zip(old_plan.buckets, flats):
        for t, off in zip(b.tensors, b.offsets()):
            segments[t.name] = jax.lax.slice_in_dim(
                flat, off, off + t.numel, axis=-1
            )
            seg_numel[t.name] = t.numel
    missing = [
        t.name for b in new_plan.buckets for t in b.tensors
        if t.name not in segments
    ]
    if missing:
        raise ValueError(
            f"relayout_flats: old plan misses tensors {sorted(missing)}"
        )
    resized = {
        t.name: (seg_numel[t.name], t.numel)
        for b in new_plan.buckets for t in b.tensors
        if seg_numel[t.name] != t.numel
    }
    if resized:
        # a silently-shifted offset would corrupt every later tensor in
        # the bucket (worst case: equal total lengths, no error at all)
        raise ValueError(
            "relayout_flats: tensor sizes differ between plans — the "
            "flat buffers cannot be re-laid-out (model edit between "
            "save and restore?): "
            + ", ".join(f"{n}: {a} -> {b} elems"
                        for n, (a, b) in sorted(resized.items()))
        )
    out: List[jax.Array] = []
    for b in new_plan.buckets:
        parts = [segments[t.name].astype(b.dtype) for t in b.tensors]
        if b.padding:
            pad_shape = parts[0].shape[:-1] + (b.padding,)
            parts.append(jnp.zeros(pad_shape, dtype=b.dtype))
        out.append(
            jnp.concatenate(parts, axis=-1) if len(parts) > 1 else parts[0]
        )
    return out
