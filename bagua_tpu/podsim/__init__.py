"""Pod-scale proof harness: many real processes, one simulated pod.

Every DCN/throughput claim in the hierarchical-collective, codec, and
autopilot PRs was validated on an 8-device single-process cpu-sim or via
jaxpr byte accounting.  The coordinator-side machinery those claims lean
on — rendezvous, lease tracking, historian ingest, autopilot decisions,
the ``/fleet`` HTTP plane — is only credible if it holds up at pod-scale
world sizes.  This package converts "should work on a pod" into a
measurable contract at 32-256 *real OS processes* on one host:

* :mod:`~bagua_tpu.podsim.util` — ``reserve_port()``, the one ephemeral
  port allocator every multi-process test and drill shares, plus the
  store-backed barrier.
* :mod:`~bagua_tpu.podsim.shaping` — per-link traffic shaping (latency /
  bandwidth / deterministic jitter per ICI- vs DCN-classed link) with
  drop/partition faults composed through
  :mod:`bagua_tpu.faults.inject` (point ``podsim.link``).
* :mod:`~bagua_tpu.podsim.transport` — loopback-TCP ring transport with
  the shaper applied on every hop; addresses rendezvous through the
  restart TCPStore.
* :mod:`~bagua_tpu.podsim.collectives` — the two-level hierarchical
  ring allreduce (intra reduce-scatter, inter ring over the 1/intra
  shard with the uint8 min-max wire codec on the DCN tier, intra
  allgather) executed byte-for-byte over the shaped transport.
* :mod:`~bagua_tpu.podsim.worker` — one simulated node: joins the REAL
  elastic-membership rendezvous, heartbeats a REAL lease, runs the
  shaped data plane, follows stop/resize/halt fences.
* :mod:`~bagua_tpu.podsim.coordinator` — the coordinator stack as a
  *killable OS process*: hosts one replica of the restart store, holds
  (or stands by for) the leadership lease, and on takeover resumes
  historian/autopilot state from the surviving replica —
  ``scripts/failover_drill.py`` SIGKILLs it mid-training to prove
  coordinator failover.
* :mod:`~bagua_tpu.podsim.orchestrator` — plays every node's launcher at
  once: hosts the restart TCPStore, runs the real
  :class:`~bagua_tpu.elastic.coordinator.ElasticCoordinator` /
  :class:`~bagua_tpu.elastic.membership.LeaseTracker` /
  :class:`~bagua_tpu.obs.historian.Historian` /
  :class:`~bagua_tpu.autopilot.engine.AutopilotEngine` /
  :class:`~bagua_tpu.obs.http.ObsHTTPServer` stack over N worker
  processes.

Import-light (no jax) by construction: a 128-rank drill cannot afford a
jax import per simulated rank, so workers install a namespace-package
shim for ``bagua_tpu`` and import only the elastic/store/obs modules that
are themselves jax-free.  ``scripts/scale_drill.py`` drives the drill
matrix and writes ``BENCH_SCALE.json``; see ``docs/podsim.md``.
"""

from .shaping import (  # noqa: F401
    LINK_DCN,
    LINK_ICI,
    LinkDropped,
    LinkSevered,
    LinkShaper,
    LinkSpec,
    ShapeSpec,
    SHAPE_PRESETS,
    classify_link,
    resolve_shape,
    transfer_time_s,
)
from .util import reserve_port, reserve_ports, store_barrier  # noqa: F401

__all__ = [
    "LINK_DCN",
    "LINK_ICI",
    "LinkDropped",
    "LinkSevered",
    "LinkShaper",
    "LinkSpec",
    "SHAPE_PRESETS",
    "ShapeSpec",
    "classify_link",
    "resolve_shape",
    "reserve_port",
    "reserve_ports",
    "store_barrier",
    "transfer_time_s",
]
