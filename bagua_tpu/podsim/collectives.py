"""Hierarchical + compressed ring collectives over a byte transport.

The production communicator builds its two-level allreduce out of XLA
``ppermute`` ring hops (intra-slice reduce-scatter, inter-slice ring on
the ``1/intra`` shard, intra-slice allgather — ``docs/hierarchical.md``),
with the wire codec fused into the DCN hops.  The pod simulator executes
the SAME construction as explicit numpy arithmetic over real sockets: one
``hop(payload) -> payload`` callback per ring, every frame carrying its
chunk index, reduction in f32 regardless of wire precision.  The DCN tier
rides the ``minmax_uint8`` wire model (u8 payload + f32 lo/hi sidecar per
chunk — the same 4x byte reduction the fused codec path ships), the ICI
tier stays f32.

This is deliberately *not* a re-implementation of the jax path — it is
the byte- and topology-accurate stand-in that lets 32-256 real processes
drive the coordinator stack without 32-256 jax runtimes.  Numerics are
still asserted: the caller compares against the exact mean with a
tolerance derived from the u8 quantization step.
"""

from __future__ import annotations

import struct
from typing import Callable, List, Tuple

import numpy as np

__all__ = [
    "encode_chunk", "decode_chunk", "wire_bytes",
    "ring_reduce_scatter", "ring_allgather", "ring_allreduce",
    "hierarchical_allreduce", "quantization_atol",
]

#: chunk frame: u32 chunk index, u8 codec id, then the codec payload
_HDR = struct.Struct("<IB")
_CODEC_F32 = 0
_CODEC_MINMAX_U8 = 1
_CODEC_ONEBIT = 2
_CODEC_TOPK = 3
_CODEC_IDS = {"f32": _CODEC_F32, "minmax_uint8": _CODEC_MINMAX_U8,
              "onebit_ef": _CODEC_ONEBIT, "topk": _CODEC_TOPK}
_SIDECAR = struct.Struct("<ff")  # lo, hi
#: onebit sidecar: u32 element count (packbits pads to a byte multiple,
#: so the frame must carry the true length), f32 mean-abs scale
_ONEBIT_SIDECAR = struct.Struct("<If")
#: topk header: u32 element count, u32 selected count
_TOPK_HDR = struct.Struct("<II")


def _topk_ratio() -> float:
    """The production knob (``BAGUA_TOPK_RATIO``) read directly from the
    process environment — the worker bootstrap deliberately avoids
    importing :mod:`bagua_tpu.env` (it pulls the jax runtime)."""
    import os

    # bagua: lint-ignore[raw-env-read] -- the jax-free worker shim cannot
    # import bagua_tpu.env (the package __init__ pulls the jax runtime);
    # default mirrors the ENV_REGISTRY declaration
    return float(os.environ.get("BAGUA_TOPK_RATIO", "0.01"))


def encode_chunk(idx: int, x: "np.ndarray", codec: str) -> bytes:
    """One wire frame: header + payload.  ``minmax_uint8`` quantizes to
    u8 against a per-chunk [lo, hi] f32 sidecar — the fused DCN codec's
    wire model."""
    x = np.asarray(x, dtype=np.float32)
    cid = _CODEC_IDS[codec]
    if cid == _CODEC_F32:
        return _HDR.pack(int(idx), cid) + x.astype("<f4").tobytes()
    if cid == _CODEC_ONEBIT:
        # sign wire model: 1 bit/element + a mean-abs scale — the 1-bit
        # ring's ~32x byte reduction (a non-finite input poisons the
        # scale, so the decoded chunk is all-NaN: the grad-guard
        # propagation contract holds on the wire mirror too)
        scale = float(np.mean(np.abs(x))) if x.size else 0.0
        bits = np.packbits(x >= 0.0)
        return (_HDR.pack(int(idx), cid)
                + _ONEBIT_SIDECAR.pack(x.size, scale) + bits.tobytes())
    if cid == _CODEC_TOPK:
        n = int(x.size)
        kk = max(1, min(n, int(np.ceil(n * _topk_ratio())))) if n else 0
        mag = np.where(np.isfinite(x), np.abs(x), np.inf)
        sel = np.argpartition(mag, n - kk)[n - kk:] if n else \
            np.zeros(0, np.int64)
        return (_HDR.pack(int(idx), cid) + _TOPK_HDR.pack(n, kk)
                + sel.astype("<i4").tobytes()
                + x[sel].astype("<f4").tobytes())
    lo = float(x.min()) if x.size else 0.0
    hi = float(x.max()) if x.size else 0.0
    scale = (hi - lo) / 255.0 if hi > lo else 1.0
    q = np.clip(np.rint((x - lo) / scale), 0, 255).astype(np.uint8)
    return _HDR.pack(int(idx), cid) + _SIDECAR.pack(lo, hi) + q.tobytes()


def decode_chunk(frame: bytes) -> Tuple[int, "np.ndarray"]:
    idx, cid = _HDR.unpack_from(frame)
    body = frame[_HDR.size:]
    if cid == _CODEC_F32:
        return idx, np.frombuffer(body, dtype="<f4").astype(np.float32)
    if cid == _CODEC_ONEBIT:
        n, scale = _ONEBIT_SIDECAR.unpack_from(body)
        bits = np.frombuffer(body[_ONEBIT_SIDECAR.size:], dtype=np.uint8)
        signs = np.unpackbits(bits)[:n].astype(np.float32) * 2.0 - 1.0
        return idx, signs * np.float32(scale)
    if cid == _CODEC_TOPK:
        n, kk = _TOPK_HDR.unpack_from(body)
        off = _TOPK_HDR.size
        sel = np.frombuffer(body[off:off + 4 * kk], dtype="<i4")
        vals = np.frombuffer(body[off + 4 * kk:off + 8 * kk], dtype="<f4")
        out = np.zeros(n, dtype=np.float32)
        out[sel] = vals
        return idx, out
    lo, hi = _SIDECAR.unpack_from(body)
    q = np.frombuffer(body[_SIDECAR.size:], dtype=np.uint8)
    scale = (hi - lo) / 255.0 if hi > lo else 1.0
    return idx, (q.astype(np.float32) * scale + lo)


def wire_bytes(nelems: int, codec: str) -> int:
    """Frame size for ``nelems`` f32 elements under ``codec`` — the
    shaper charges these bytes, so the DCN tier's byte reduction (4x u8,
    ~32x onebit, ~50x topk at the default 1% ratio) shows up in injected
    serialization time exactly like the fused path."""
    cid = _CODEC_IDS[codec]
    n = int(nelems)
    if cid == _CODEC_F32:
        return _HDR.size + 4 * n
    if cid == _CODEC_ONEBIT:
        return _HDR.size + _ONEBIT_SIDECAR.size + -(-n // 8)
    if cid == _CODEC_TOPK:
        kk = max(1, min(n, int(np.ceil(n * _topk_ratio())))) if n else 0
        return _HDR.size + _TOPK_HDR.size + 8 * kk
    return _HDR.size + _SIDECAR.size + n


def quantization_atol(x_span: float, reduce_hops: int,
                      codec: str = "minmax_uint8") -> float:
    """Worst-case absolute error of a mean computed through ``reduce_hops``
    codec-quantized additions of values spanning ``x_span``.  u8: half a
    quantization step per encode, accumulated.  onebit/topk are LOSSY by
    construction (the production path pairs them with an error-feedback
    residual the stateless mirror does not carry), so their bound is
    span-scale: it proves transport integrity — frames reassemble, the
    reduction stays finite and magnitude-bounded — not fidelity."""
    if _CODEC_IDS.get(codec) in (_CODEC_ONEBIT, _CODEC_TOPK):
        return x_span * float(max(1, reduce_hops)) + 1e-5
    return (x_span / 255.0) * 0.5 * max(1, reduce_hops) + 1e-5


Hop = Callable[[bytes, int], bytes]  # (payload, hop_index) -> payload


def _split(x: "np.ndarray", n: int) -> List["np.ndarray"]:
    """n near-equal chunks (padded to equal length so frames are uniform —
    mirrors the communicator's padded ring chunking)."""
    per = -(-x.size // n)
    padded = np.zeros(per * n, dtype=np.float32)
    padded[: x.size] = x
    return [padded[i * per: (i + 1) * per].copy() for i in range(n)]


def ring_reduce_scatter(x: "np.ndarray", pos: int, size: int, hop: Hop,
                        codec: str = "f32",
                        hop_base: int = 0) -> Tuple["np.ndarray", int, int]:
    """Standard ring reduce-scatter: ``size - 1`` hops, each sending the
    running partial of one chunk to the next ring position.  Returns
    (owned fully-reduced chunk, its chunk index, hops consumed)."""
    if size == 1:
        return np.asarray(x, dtype=np.float32).copy(), 0, 0
    chunks = _split(np.asarray(x, dtype=np.float32), size)
    for step in range(size - 1):
        send_idx = (pos - step) % size
        frame = hop(encode_chunk(send_idx, chunks[send_idx], codec),
                    hop_base + step)
        idx, partial = decode_chunk(frame)
        chunks[idx] = chunks[idx] + partial
    own = (pos + 1) % size
    return chunks[own], own, size - 1


def ring_allgather(own: "np.ndarray", own_idx: int, size: int, hop: Hop,
                   codec: str = "f32",
                   hop_base: int = 0) -> Tuple[List["np.ndarray"], int]:
    """Standard ring allgather: circulate each fully-reduced chunk
    ``size - 1`` hops; frames carry their chunk index, so the assembly
    is self-describing.  Returns (all chunks in index order, hops)."""
    chunks: List = [None] * size
    chunks[own_idx] = np.asarray(own, dtype=np.float32)
    cur_idx, cur = own_idx, chunks[own_idx]
    for step in range(size - 1):
        frame = hop(encode_chunk(cur_idx, cur, codec), hop_base + step)
        cur_idx, cur = decode_chunk(frame)
        chunks[cur_idx] = cur
    return chunks, size - 1


def ring_allreduce(x: "np.ndarray", pos: int, size: int, hop: Hop,
                   codec: str = "f32") -> Tuple["np.ndarray", int]:
    """reduce-scatter + allgather; returns (summed vector, hops)."""
    x = np.asarray(x, dtype=np.float32)
    own, own_idx, h1 = ring_reduce_scatter(x, pos, size, hop, codec)
    chunks, h2 = ring_allgather(own, own_idx, size, hop, codec, hop_base=h1)
    return np.concatenate(chunks)[: x.size], h1 + h2


def hierarchical_allreduce(
    x: "np.ndarray",
    intra_hop: Hop, intra_pos: int, intra_size: int,
    inter_hop: Hop, inter_pos: int, inter_size: int,
    dcn_codec: str = "minmax_uint8",
) -> Tuple["np.ndarray", dict]:
    """The two-level construction over two rings: intra reduce-scatter
    (f32, ICI), inter ring allreduce on the owned ``1/intra`` shard
    (``dcn_codec`` wire, DCN), intra allgather (f32, ICI).  Returns the
    *mean* over all ``intra_size * inter_size`` ranks plus hop
    accounting."""
    x = np.asarray(x, dtype=np.float32)
    world = intra_size * inter_size
    own, own_idx, intra_hops = ring_reduce_scatter(
        x, intra_pos, intra_size, intra_hop, codec="f32")
    inter_hops = 0
    if inter_size > 1:
        own, inter_hops = ring_allreduce(
            own, inter_pos, inter_size, inter_hop, codec=dcn_codec)
    chunks, ag_hops = ring_allgather(
        own, own_idx, intra_size, intra_hop, codec="f32",
        hop_base=intra_hops)
    out = np.concatenate(chunks)[: x.size] / float(world)
    return out, {
        "intra_hops": intra_hops + ag_hops,
        "inter_hops": inter_hops,
        "world": world,
    }
