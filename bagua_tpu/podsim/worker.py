"""One simulated node of the pod: real membership, real lease, shaped
data plane.  Executed as a *file* (``python .../podsim/worker.py``), not
``-m``: the bootstrap below installs a namespace-package shim for
``bagua_tpu`` so the worker imports only the jax-free elastic/store/
podsim modules — ``bagua_tpu/__init__`` pulls the whole jax runtime, and
a 128-rank drill cannot afford 128 jax imports (measured ~0.9 s and
~125 MB each on the CI host vs ~0.2 s / ~20 MB shimmed).

Per epoch the worker walks the production member path end to end:
``join_round`` → :class:`LeaseHeartbeat` (own store connection, health
payload from the node's *profile*) → the shaped hierarchical+compressed
data plane (:mod:`~bagua_tpu.podsim.collectives` over
:class:`~bagua_tpu.podsim.transport.RingTransport`) → stop/halt fence
watching.  Profiles are switched live through the store key
``podsim/profile/<node>`` so the orchestrator can turn a healthy node
into a chronic straggler mid-run and watch the autopilot fence it:

========== ==========================================================
profile    heartbeat health payload
========== ==========================================================
healthy    goodput ~0.92, no suspects
straggler  dispatch-dominant ``straggler_suspect`` (ratio 6) — the
           autopilot's ``chronic_straggler`` rule fences the node
slow       goodput 0.3 — drags the fleet SLO minimum
========== ==========================================================

Exit codes mirror the launcher: 0 done/halted, 4 fenced, 3 error.
"""

import sys

if __package__ in (None, ""):  # pragma: no cover - subprocess entry
    import importlib.util
    import os

    _repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, _repo)
    _spec = importlib.util.spec_from_loader(
        "bagua_tpu", loader=None, is_package=True)
    _pkg = importlib.util.module_from_spec(_spec)
    _pkg.__path__ = [os.path.join(_repo, "bagua_tpu")]
    sys.modules["bagua_tpu"] = _pkg

import argparse  # noqa: E402
import json  # noqa: E402
import logging  # noqa: E402
import time  # noqa: E402

from bagua_tpu.contrib.utils.tcp_store import TCPStore  # noqa: E402
from bagua_tpu.elastic.coordinator import (  # noqa: E402
    ExcludedFromRound,
    Halted,
    join_round,
    wait_for_next_epoch,
)
from bagua_tpu.elastic.membership import (  # noqa: E402
    LeaseHeartbeat,
    MembershipClient,
    WorldSpec,
)
from bagua_tpu.podsim.shaping import LinkShaper, resolve_shape  # noqa: E402
from bagua_tpu.podsim.transport import RingTransport  # noqa: E402

logger = logging.getLogger("podsim.worker")

PROFILE_KEY = "podsim/profile/{node}"


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--store-addr", default="127.0.0.1")
    ap.add_argument("--store-port", type=int, required=True)
    ap.add_argument("--store-endpoints", default="",
                    help="comma-separated host:port replica group; when "
                         "set, every store client this worker opens is a "
                         "FailoverStore over the group — ops survive the "
                         "primary store dying mid-epoch (the coordinator-"
                         "failover drill)")
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--max-nnodes", type=int, required=True)
    ap.add_argument("--steps", type=int, default=0,
                    help="shaped collective steps per epoch (0 = none)")
    ap.add_argument("--vec-elems", type=int, default=16384)
    ap.add_argument("--shape", default="pod",
                    help="link shape preset name or JSON object")
    ap.add_argument("--slice-size", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--dcn-codec", default="minmax_uint8",
                    choices=("minmax_uint8", "f32", "onebit_ef", "topk"))
    ap.add_argument("--hb-interval", type=float, default=0.5)
    ap.add_argument("--timeout", type=float, default=120.0)
    return ap.parse_args(argv)


def _connect_store(args, timeout_s: float = 30.0):
    if args.store_endpoints:
        from bagua_tpu.elastic.failover import FailoverStore

        return FailoverStore(
            [e.strip() for e in args.store_endpoints.split(",")
             if e.strip()],
            connect_timeout_s=timeout_s,
        )
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            return TCPStore(args.store_addr, args.store_port, timeout_s=60.0)
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.1)


def _health(args, state: dict):
    """The heartbeat's health payload for the node's current profile.
    Single-rank obs form — ``build_fleet_record`` normalizes it."""
    profile = state.get("profile", "healthy")
    obs = {
        "rank": args.node_id,
        "step": int(state.get("steps_done", 0)),
        "goodput_fraction": 0.92,
        "worst_badput_class": "collective_wait",
    }
    if profile == "straggler":
        obs["straggler_suspect"] = {
            "rank": args.node_id,
            "ratio": 6.0,
            "detected_at_unix": time.time(),
            "dominant_phase": "dispatch",
        }
    elif profile == "slow":
        obs["goodput_fraction"] = 0.3
    return {"obs": obs}


def _poll_profile(store, args, state: dict) -> None:
    raw = store.get(PROFILE_KEY.format(node=args.node_id))
    if raw is not None:
        state["profile"] = raw.decode()


def _hier_geometry(world: int, slice_size: int):
    """(intra, inter): hierarchical when the slice width divides the
    world evenly, flat single ring otherwise (a post-shrink ragged world
    still runs shaped collectives, just unhierarchically)."""
    if slice_size > 1 and world % slice_size == 0 and world > slice_size:
        return slice_size, world // slice_size
    return world, 1


def _data_plane(args, store, spec: WorldSpec, state: dict) -> dict:
    from bagua_tpu.podsim import collectives as C

    import numpy as np

    rank = spec.rank_of(args.node_id)
    world = spec.nnodes
    intra, inter = _hier_geometry(world, args.slice_size)
    shape = resolve_shape(args.shape, slice_size=args.slice_size,
                          seed=args.seed)
    shaper = LinkShaper(shape, world)
    slice_idx, pos_in_slice = rank // intra, rank % intra
    ns = f"podsim/{spec.epoch}/ring"
    intra_ring = RingTransport(
        store, f"{ns}/intra{slice_idx}",
        [slice_idx * intra + j for j in range(intra)], pos_in_slice,
        shaper=shaper, timeout_s=args.timeout,
    )
    inter_ring = RingTransport(
        store, f"{ns}/inter{pos_in_slice}",
        [pos_in_slice + s * intra for s in range(inter)], slice_idx,
        shaper=shaper, timeout_s=args.timeout,
    ) if inter > 1 else None

    # every rank regenerates every rank's vector -> exact expected mean
    n = args.vec_elems
    vecs = [
        np.random.default_rng([args.seed, spec.epoch, r]).uniform(
            -1.0, 1.0, n).astype(np.float32)
        for r in range(world)
    ]
    expected = np.mean(vecs, axis=0)
    atol = (C.quantization_atol(2.0 * intra, 2 * max(1, inter - 1),
                                args.dcn_codec)
            if args.dcn_codec != "f32" and inter > 1 else 1e-4)

    max_err, t0 = 0.0, time.monotonic()
    try:
        for step in range(args.steps):
            out, hops = C.hierarchical_allreduce(
                vecs[rank],
                intra_ring.hop, pos_in_slice, intra,
                (inter_ring.hop if inter_ring is not None
                 else intra_ring.hop), slice_idx, inter,
                dcn_codec=args.dcn_codec,
            )
            err = float(np.max(np.abs(out - expected)))
            max_err = max(max_err, err)
            if err > atol:
                raise AssertionError(
                    f"step {step}: allreduce error {err:.5f} > atol "
                    f"{atol:.5f} (world {world}, {intra}x{inter})"
                )
            state["steps_done"] = step + 1
            _poll_profile(store, args, state)
    finally:
        intra_ring.close()
        if inter_ring is not None:
            inter_ring.close()
    return {
        "rank": rank, "world": world, "intra": intra, "inter": inter,
        "steps": args.steps, "max_err": max_err, "atol": atol,
        "wall_s": round(time.monotonic() - t0, 3),
        "shaping": shaper.stats,
    }


def _run_epoch(args, store, client: MembershipClient,
               spec: WorldSpec, state: dict) -> str:
    hb = LeaseHeartbeat(
        # failover mode: the heartbeat's own connection must also walk the
        # endpoint list, or a dead primary silently kills every lease
        (lambda: _connect_store(args))
        if args.store_endpoints else
        (lambda: TCPStore(args.store_addr, args.store_port, timeout_s=30.0)),
        args.node_id, spec.epoch, interval_s=args.hb_interval,
        max_nnodes=args.max_nnodes,
        health_source=lambda: _health(args, state),
    ).start()
    try:
        if args.steps > 0 and spec.nnodes > 1:
            verdict = _data_plane(args, store, spec, state)
        else:
            verdict = {"rank": spec.rank_of(args.node_id),
                       "world": spec.nnodes, "skipped": True}
        store.set(f"podsim/{spec.epoch}/ok/{args.node_id}",
                  json.dumps(verdict))
        print(f"node {args.node_id}: epoch {spec.epoch} ok "
              f"(world {spec.nnodes}, rank {spec.rank_of(args.node_id)})",
              flush=True)
        while True:
            if client.read_halt() is not None:
                return "halt"
            stop = client.read_stop(spec.epoch)
            if stop is not None:
                if not stop.get("rejoin", True) and \
                        args.node_id in (stop.get("nodes") or []):
                    print(f"node {args.node_id}: fenced "
                          f"({stop.get('kind')})", flush=True)
                    return "fenced"
                return "stop"
            cur = client.current_epoch()
            if cur is not None and cur > spec.epoch:
                return "stop"
            _poll_profile(store, args, state)
            time.sleep(0.2)
    finally:
        hb.stop()


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = parse_args(argv)
    store = _connect_store(args)
    client = MembershipClient(store, args.node_id, args.max_nnodes)
    state = {"profile": "healthy", "steps_done": 0}
    _poll_profile(store, args, state)

    deadline = time.monotonic() + args.timeout
    epoch = None
    while epoch is None:
        epoch = client.current_epoch()
        if epoch is None:
            if time.monotonic() > deadline:
                print(f"node {args.node_id}: no epoch opened", flush=True)
                return 3
            time.sleep(0.1)

    # scale the rendezvous poll with fleet size: 128 members polling every
    # 0.2 s is 1.3k store round-trips/s of pure waiting, which starves the
    # very joins being waited on (single-core CI, threaded Python store)
    poll_s = min(1.0, max(0.2, args.max_nnodes / 128.0))
    while True:
        try:
            spec = join_round(client, epoch, timeout_s=args.timeout,
                              poll_s=poll_s)
        except ExcludedFromRound as e:
            print(f"node {args.node_id}: excluded from epoch {e.spec.epoch};"
                  " standing by", flush=True)
            try:
                epoch = wait_for_next_epoch(client, e.spec.epoch,
                                            timeout_s=args.timeout,
                                            poll_s=poll_s)
            except Halted:
                return 0
            continue
        except Halted:
            return 0
        print(f"node {args.node_id}: joined epoch {spec.epoch} "
              f"world {spec.nnodes}", flush=True)
        rc = _run_epoch(args, store, client, spec, state)
        if rc == "halt":
            return 0
        if rc == "fenced":
            return 4
        try:
            epoch = wait_for_next_epoch(client, spec.epoch,
                                        timeout_s=args.timeout,
                                        poll_s=poll_s)
        except Halted:
            return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 - drill log must carry the cause
        import traceback

        traceback.print_exc()
        sys.exit(3)
