"""The pod simulator's coordinator side: spawn N real worker processes,
run the REAL control plane against them, measure everything.

:class:`PodSim` is the launcher a scale drill scripts against.  It hosts
the restart TCPStore, runs :class:`~bagua_tpu.elastic.coordinator.
ElasticCoordinator` rendezvous rounds, polls leases with
:class:`~bagua_tpu.elastic.membership.LeaseTracker`, merges heartbeat
health into ``bagua-obs-fleet-v1`` records
(:func:`~bagua_tpu.obs.export.build_fleet_record`), feeds the telemetry
historian and the autopilot engine, serves the coordinator ``/fleet``
HTTP plane, and actuates fence/resize decisions through
``publish_stop`` — i.e. the exact object graph ``distributed/run.py``
assembles on node 0, minus jax.  The workers are real OS processes
(:mod:`~bagua_tpu.podsim.worker`) joined over loopback TCP, so connect
storms, listen backlogs, GIL-bound monitor loops and fan-in serialization
are all REAL costs here, measured per tick in :attr:`PodSim.metrics`.

Scenario primitives: ``kill``/``relaunch`` a node (lease-expiry shrink,
standby regrow), ``set_profile`` (flip a node's heartbeat health to
``straggler``/``slow`` mid-run and let the autopilot escalate), ``halt``
(orderly teardown).  The drill script composes these; the chaos plane
(``BAGUA_FAULT_PLAN`` in the workers' env) composes link faults on top.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from ..autopilot.engine import AutopilotEngine
from ..autopilot.policy import Action, PolicyConfig
from ..contrib.utils.tcp_store import TCPStore, TCPStoreServer
from ..elastic.coordinator import ElasticCoordinator
from ..elastic.membership import LeaseTracker, MembershipClient, WorldSpec
from ..obs.export import build_fleet_record, validate_fleet_snapshot
from ..obs.historian import Historian
from ..obs.http import ObsHTTPServer

logger = logging.getLogger("podsim.orchestrator")

__all__ = ["PodSim", "worker_argv", "WORKER_PATH", "COORDINATOR_PATH"]

WORKER_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "worker.py")
_WORKER = WORKER_PATH
#: the killable coordinator process (failover drills); PodSim itself runs
#: the coordinator in-process — see :mod:`bagua_tpu.podsim.coordinator`
COORDINATOR_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "coordinator.py")


def worker_argv(store_addr: str, store_port: int, node_id: int,
                max_nnodes: int, *, steps: int = 0, vec_elems: int = 16384,
                shape: str = "pod", slice_size: int = 8, seed: int = 0,
                dcn_codec: str = "minmax_uint8", hb_interval_s: float = 0.5,
                timeout_s: float = 120.0,
                store_endpoints: str = "") -> List[str]:
    """The ``worker.py`` command line — ONE builder for the in-process
    :class:`PodSim` launcher and the cross-process failover drill, so a
    drill worker is configured exactly like a scale-drill worker."""
    argv = [
        sys.executable, WORKER_PATH,
        "--store-addr", store_addr, "--store-port", str(store_port),
        "--node-id", str(node_id), "--max-nnodes", str(max_nnodes),
        "--steps", str(steps), "--vec-elems", str(vec_elems),
        "--shape", shape, "--slice-size", str(slice_size),
        "--seed", str(seed), "--dcn-codec", dcn_codec,
        "--hb-interval", str(hb_interval_s),
        "--timeout", str(timeout_s),
    ]
    if store_endpoints:
        argv += ["--store-endpoints", store_endpoints]
    return argv


class PodSim:
    """One simulated pod.  Context-manage it — ``__exit__`` tears down
    processes, HTTP plane, and the store server unconditionally."""

    def __init__(self, world: int, workdir: str,
                 min_nnodes: int = 1,
                 steps: int = 0, vec_elems: int = 16384,
                 shape: str = "pod", slice_size: int = 8, seed: int = 0,
                 dcn_codec: str = "minmax_uint8",
                 hb_interval_s: float = 0.5, lease_ttl_s: float = 4.0,
                 join_window_s: float = 30.0, timeout_s: float = 120.0,
                 policy: Optional[PolicyConfig] = None,
                 http: bool = True,
                 worker_env: Optional[Dict[str, str]] = None):
        self.world = int(world)
        self.workdir = str(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.min_nnodes = int(min_nnodes)
        self.steps = int(steps)
        self.vec_elems = int(vec_elems)
        self.shape = str(shape)
        self.slice_size = int(slice_size)
        self.seed = int(seed)
        self.dcn_codec = str(dcn_codec)
        self.hb_interval_s = float(hb_interval_s)
        self.lease_ttl_s = float(lease_ttl_s)
        self.timeout_s = float(timeout_s)
        self.worker_env = dict(worker_env or {})

        # the coordinator stack run.py builds on node 0, minus jax
        self.server = TCPStoreServer("127.0.0.1", 0, backend="python")
        self.addr, self.port = self.server.address
        self.store = TCPStore(self.addr, self.port, timeout_s=60.0)
        self.client = MembershipClient(self.store, 0, self.world)
        self.coord = ElasticCoordinator(
            self.client, self.min_nnodes, self.world,
            master_addr=self.addr, master_port=self.port,
            join_window_s=float(join_window_s), timeout_s=self.timeout_s,
        )
        self.historian = Historian(capacity=4096, window_s=120.0)
        self.engine = AutopilotEngine(
            config=policy or PolicyConfig(
                mode="act", sustain=2, cooldown_s=0.0, budget=8,
                staleness_s=60.0, suspect_ttl_s=30.0,
            ),
            store=self.store,
        )
        self._fleet_record: Optional[dict] = None
        self.http: Optional[ObsHTTPServer] = None
        if http:
            self.http = ObsHTTPServer(
                port=0, addr="127.0.0.1",
                fleet_provider=lambda: self._fleet_record,
                historian=self.historian,
            ).start()

        self.procs: Dict[int, subprocess.Popen] = {}
        self.spec: Optional[WorldSpec] = None
        self.tracker: Optional[LeaseTracker] = None
        #: drill measurements: per-phase wall times and per-tick control
        #: loop latencies (seconds)
        self.metrics: Dict[str, List[float]] = {
            "rendezvous_s": [], "decide_s": [], "ingest_s": [],
            "tick_s": [],
        }

    # ---- process control -------------------------------------------------

    def log_path(self, node_id: int) -> str:
        return os.path.join(self.workdir, f"node{node_id}.log")

    def spawn(self, node_id: int) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self.worker_env)
        argv = worker_argv(
            self.addr, self.port, node_id, self.world,
            steps=self.steps, vec_elems=self.vec_elems, shape=self.shape,
            slice_size=self.slice_size, seed=self.seed,
            dcn_codec=self.dcn_codec, hb_interval_s=self.hb_interval_s,
            timeout_s=self.timeout_s,
        )
        log = open(self.log_path(node_id), "ab")
        try:
            proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                start_new_session=True, env=env,
            )
        finally:
            log.close()
        self.procs[node_id] = proc
        return proc

    def spawn_all(self) -> None:
        for nid in range(self.world):
            self.spawn(nid)

    def kill(self, node_id: int) -> None:
        """Hard-kill one node's process — the silent-death case lease
        expiry exists for."""
        proc = self.procs.get(node_id)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)

    def alive(self) -> List[int]:
        return sorted(n for n, p in self.procs.items() if p.poll() is None)

    # ---- control plane ---------------------------------------------------

    def rendezvous(self, epoch: int,
                   expect: Optional[List[int]] = None) -> WorldSpec:
        """One coordinator round; wall time lands in
        ``metrics['rendezvous_s']``."""
        t0 = time.monotonic()
        spec = self.coord.run_round(epoch, expect=expect)
        self.metrics["rendezvous_s"].append(time.monotonic() - t0)
        self.spec = spec
        self.tracker = LeaseTracker(
            self.client, spec.epoch, sorted(spec.ranks),
            ttl_s=self.lease_ttl_s,
        )
        return spec

    def set_profile(self, node_id: int, profile: str) -> None:
        self.store.set(f"podsim/profile/{node_id}", profile)

    def ok_ids(self, spec: WorldSpec) -> List[int]:
        members = sorted(spec.ranks)
        vals = self.store.mget(
            [f"podsim/{spec.epoch}/ok/{n}" for n in members])
        return [n for n, v in zip(members, vals) if v is not None]

    def ok_verdicts(self, spec: WorldSpec) -> Dict[int, dict]:
        members = sorted(spec.ranks)
        vals = self.store.mget(
            [f"podsim/{spec.epoch}/ok/{n}" for n in members])
        return {n: json.loads(v) for n, v in zip(members, vals)
                if v is not None}

    def _observe_tick(self, spec: WorldSpec) -> List[Action]:
        """One monitor-loop body: poll leases, merge health, historian,
        autopilot — each stage timed."""
        t0 = time.monotonic()
        expired = self.tracker.poll()
        members = {n: self.tracker.health_of(n) for n in sorted(spec.ranks)}
        record = build_fleet_record(spec.epoch, members)
        problems = validate_fleet_snapshot(record)
        if problems:
            raise AssertionError(f"fleet record invalid: {problems}")
        t1 = time.monotonic()
        self.historian.ingest(record)
        t2 = time.monotonic()
        actions = self.engine.observe_snapshot(record)
        t3 = time.monotonic()
        self._fleet_record = record
        self.metrics["ingest_s"].append(t2 - t1)
        self.metrics["decide_s"].append(t3 - t2)
        self.metrics["tick_s"].append(t3 - t0)
        if expired:
            self.client.publish_stop(
                spec.epoch, "lease_expired", expired[0],
                f"lease(s) expired after {self.lease_ttl_s:.1f}s: {expired}",
                rejoin=False, nodes=expired,
            )
        return actions

    def monitor(self, spec: WorldSpec, until: str = "all_ok",
                max_s: float = 60.0,
                tick_s: float = 0.25) -> Tuple[str, List[int]]:
        """Run the coordinator monitor loop until a verdict:

        * ``("all_ok", members)`` — every member wrote its epoch verdict
          (``until="all_ok"``)
        * ``("fenced", nodes)`` — the autopilot decided fence/resize; the
          stop is published (``rejoin=False``) before returning
        * ``("expired", nodes)`` — a lease ran out; stop published
        * ``("timeout", [])`` — ``max_s`` elapsed without a verdict
        """
        deadline = time.monotonic() + max_s
        while time.monotonic() < deadline:
            actions = self._observe_tick(spec)
            stop = self.client.read_stop(spec.epoch)
            if stop is not None and stop.get("kind") == "lease_expired":
                return "expired", list(stop.get("nodes") or [])
            for action in actions:
                if action.kind not in ("fence", "resize"):
                    continue
                targets = [int(t) for t in (
                    action.target if isinstance(action.target, (list, tuple))
                    else [action.target])]
                self.client.publish_stop(
                    spec.epoch, f"autopilot_{action.kind}", targets[0],
                    action.reason, rejoin=False, nodes=targets,
                )
                self.engine.note_actuated(action)
                return "fenced", targets
            if until == "all_ok" and \
                    len(self.ok_ids(spec)) == spec.nnodes:
                return "all_ok", sorted(spec.ranks)
            time.sleep(tick_s)
        return "timeout", []

    def standby_ids(self) -> List[int]:
        return self.coord.standby_ids(self.spec) if self.spec else []

    # ---- teardown --------------------------------------------------------

    def halt(self, reason: str = "drill complete") -> None:
        self.client.publish_halt(0, reason)

    def wait_all(self, timeout_s: float = 30.0) -> Dict[int, Optional[int]]:
        """Reap every worker; returns node -> exit code (None = had to be
        killed)."""
        codes: Dict[int, Optional[int]] = {}
        deadline = time.monotonic() + timeout_s
        for nid, proc in sorted(self.procs.items()):
            try:
                codes[nid] = proc.wait(
                    timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
                codes[nid] = None
        return codes

    def shutdown(self) -> None:
        try:
            self.halt("shutdown")
        except Exception:  # noqa: BLE001 - teardown must not raise
            pass
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.kill()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                pass
        if self.http is not None:
            self.http.stop()
            self.http = None
        try:
            self.store._sock.close()  # TCPStore has no close(); be tidy
        except Exception:  # noqa: BLE001
            pass
        self.server.stop()

    def __enter__(self) -> "PodSim":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
