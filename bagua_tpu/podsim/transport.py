"""Loopback-TCP ring transport with the link shaper on every hop.

Each ring member owns one listening socket; addresses rendezvous through
the restart TCPStore under an epoch-fenced namespace (the same epoch
discipline the membership layer uses — a ring from attempt N cannot
cross-talk with attempt N+1's).  Ring position ``p`` sends to ``p+1`` and
receives from ``p-1``; frames are length-prefixed.  The shaper charges
the hop's wire bytes against the (src, dst) *global* rank pair, so an
intra-slice ring pays ICI physics and a cross-slice ring pays DCN physics
— and armed ``podsim.link`` faults surface here as ``ConnectionError``s,
exactly the failure class a real peer reset produces.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import List, Optional

from .shaping import LinkShaper
from .util import wait_store_keys

__all__ = ["RingTransport"]

_LEN = struct.Struct("<I")
_MAX_FRAME = 64 * 1024 * 1024


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError(
                f"ring peer closed mid-frame ({len(buf)}/{n} bytes)"
            )
        buf += part
    return bytes(buf)


class RingTransport:
    """One ring over loopback TCP.

    ``rank_map`` lists the *global* rank at each ring position (the
    shaper classifies links by global rank); ``pos`` is this member's
    position.  ``namespace`` must be unique per (epoch, ring) — e.g.
    ``podsim/<epoch>/ring/intra3``."""

    def __init__(self, store, namespace: str, rank_map: List[int], pos: int,
                 shaper: Optional[LinkShaper] = None,
                 host: str = "127.0.0.1", timeout_s: float = 60.0):
        self.size = len(rank_map)
        self.pos = int(pos)
        self.rank = int(rank_map[self.pos])
        self.next_rank = int(rank_map[(self.pos + 1) % self.size])
        self.shaper = shaper
        self._send: Optional[socket.socket] = None
        self._recv: Optional[socket.socket] = None
        self._listener: Optional[socket.socket] = None
        if self.size == 1:
            return
        # listen (kernel-assigned port — the bind itself holds it), then
        # publish, then connect to next, then accept prev.  Everyone
        # connects "rightward" concurrently, so accept cannot deadlock.
        lst = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        lst.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        lst.bind((host, 0))
        lst.listen(2)
        lst.settimeout(timeout_s)
        self._listener = lst
        store.set(f"{namespace}/addr/{self.pos}",
                  f"{host}:{lst.getsockname()[1]}")
        (next_addr,) = wait_store_keys(
            store, [f"{namespace}/addr/{(self.pos + 1) % self.size}"],
            timeout_s=timeout_s,
        )
        next_host, next_port = next_addr.decode().rsplit(":", 1)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                self._send = socket.create_connection(
                    (next_host, int(next_port)),
                    timeout=max(1.0, deadline - time.monotonic()),
                )
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        self._send.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._recv, _ = lst.accept()
        self._recv.settimeout(timeout_s)

    def hop(self, payload: bytes, hop_index: int = 0,
            step: Optional[int] = None) -> bytes:
        """One ppermute-shaped exchange: shaped send to next, receive from
        prev.  Identity at ring size 1."""
        if self.size == 1:
            return payload
        if len(payload) > _MAX_FRAME:
            raise ValueError(f"frame {len(payload)} exceeds {_MAX_FRAME}")
        if self.shaper is not None:
            self.shaper.traverse(self.rank, self.next_rank, len(payload),
                                 hop=hop_index, step=step)
        self._send.sendall(_LEN.pack(len(payload)) + payload)
        n = _LEN.unpack(_recv_exact(self._recv, _LEN.size))[0]
        return _recv_exact(self._recv, n)

    def close(self) -> None:
        for s in (self._send, self._recv, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._send = self._recv = self._listener = None

    def __enter__(self) -> "RingTransport":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
