"""Per-link traffic shaping for the pod simulator (no jax).

The hierarchical collective work classifies every edge of the device
graph as ``ici`` (fast intra-slice fabric) or ``dcn`` (slow cross-slice
data-center network) and spends its complexity budget on the DCN tier —
see ``bagua_tpu.communication`` (``LINK_ICI``/``LINK_DCN``) and
``docs/hierarchical.md``.  The simulator reproduces that asymmetry for
*real processes over loopback TCP*: every ring hop pays a deterministic
traversal time

    ``latency_s  +  nbytes / bandwidth_Bps  +  u * jitter_s``

where ``u`` is a hash of ``(seed, src, dst, hop)`` — identical across
reruns, so a drill's wall-clock numbers are comparable run to run (the
historian/replay layers already insist on wall-clock-free determinism;
the shaper extends it to injected network time).

Fault composition rides the existing chaos plane instead of inventing a
second one: the fault point ``podsim.link`` (``bagua_tpu.faults.inject``)
supports kind ``drop`` — the next shaped hop raises :class:`LinkDropped`,
a ``ConnectionError`` the transport surfaces like a real peer reset — and
kind ``partition`` — the slice named by the spec's ``rank`` field loses
every DCN-crossing link for ``duration_s`` seconds
(:class:`LinkSevered`), while its intra-slice fabric keeps working, which
is what an actual inter-slice network cut looks like.  Arming happens
through the normal ``FaultPlan`` / ``BAGUA_FAULT_PLAN`` machinery, so
drills compose link faults with store flakes, heartbeat drops, and
straggler dilation from one plan.
"""

from __future__ import annotations

import hashlib
import json
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..faults import inject as _inject

__all__ = [
    "LINK_ICI", "LINK_DCN", "LinkSpec", "ShapeSpec", "SHAPE_PRESETS",
    "LinkDropped", "LinkSevered", "LinkShaper", "classify_link",
    "resolve_shape", "transfer_time_s", "deterministic_jitter",
]

#: link classes — mirror ``bagua_tpu.communication.LINK_ICI``/``LINK_DCN``
#: (kept literal here so the simulator stays jax-free; equality is pinned
#: in tests/test_podsim.py)
LINK_ICI = "ici"
LINK_DCN = "dcn"

#: fault point the shaper queries (registered in bagua_tpu.faults.inject)
FAULT_POINT = "podsim.link"


class LinkDropped(_inject.InjectedFault, ConnectionError):
    """An armed ``podsim.link``/``drop`` fault ate this hop's payload."""


class LinkSevered(_inject.InjectedFault, ConnectionError):
    """A ``podsim.link``/``partition`` fault has this slice cut off from
    the DCN; every cross-slice hop touching it fails until the cut
    expires."""


@dataclass(frozen=True)
class LinkSpec:
    """One link class's physics: propagation latency, usable bandwidth in
    bytes/second (0 = infinite), and the jitter ceiling."""

    latency_s: float = 0.0
    bandwidth_Bps: float = 0.0
    jitter_s: float = 0.0

    def to_json(self) -> dict:
        return {"latency_s": self.latency_s,
                "bandwidth_Bps": self.bandwidth_Bps,
                "jitter_s": self.jitter_s}


@dataclass(frozen=True)
class ShapeSpec:
    """A whole pod's link model: slice width for ICI/DCN classification
    plus the two link classes' physics and the jitter seed."""

    name: str = "off"
    slice_size: int = 8
    ici: LinkSpec = field(default_factory=LinkSpec)
    dcn: LinkSpec = field(default_factory=LinkSpec)
    seed: int = 0

    def to_json(self) -> dict:
        return {"name": self.name, "slice_size": self.slice_size,
                "ici": self.ici.to_json(), "dcn": self.dcn.to_json(),
                "seed": self.seed}


#: named presets for ``BAGUA_SCALE_SHAPE`` / ``--shape``.  Numbers are
#: scaled-down stand-ins (a cpu-sim drill cannot afford real WAN waits);
#: what matters for the harness is the ICI:DCN asymmetry, not absolute
#: magnitudes.
SHAPE_PRESETS: Dict[str, ShapeSpec] = {
    # no injected time at all — pure-software ceiling
    "off": ShapeSpec(name="off"),
    # one pod: microsecond-class ICI, ~200us DCN RTT-half, mild jitter
    "pod": ShapeSpec(
        name="pod", slice_size=8,
        ici=LinkSpec(latency_s=2e-6, bandwidth_Bps=40e9, jitter_s=1e-6),
        dcn=LinkSpec(latency_s=200e-6, bandwidth_Bps=2.5e9, jitter_s=50e-6),
    ),
    # cross-region flavor: the DCN tier dominates everything
    "wan": ShapeSpec(
        name="wan", slice_size=8,
        ici=LinkSpec(latency_s=2e-6, bandwidth_Bps=40e9, jitter_s=1e-6),
        dcn=LinkSpec(latency_s=5e-3, bandwidth_Bps=100e6, jitter_s=1e-3),
    ),
}


def resolve_shape(raw, slice_size: Optional[int] = None,
                  seed: Optional[int] = None) -> ShapeSpec:
    """A :class:`ShapeSpec` from a preset name, a JSON object string, an
    already-parsed dict, or an existing spec; ``slice_size``/``seed``
    override whatever the source carried."""
    if isinstance(raw, ShapeSpec):
        spec = raw
    elif raw is None or raw == "":
        spec = SHAPE_PRESETS["off"]
    elif isinstance(raw, dict):
        spec = _shape_from_dict(raw)
    elif isinstance(raw, str) and raw.lstrip().startswith("{"):
        spec = _shape_from_dict(json.loads(raw))
    elif isinstance(raw, str) and raw in SHAPE_PRESETS:
        spec = SHAPE_PRESETS[raw]
    else:
        raise ValueError(
            f"unknown link shape {raw!r}; presets: "
            f"{sorted(SHAPE_PRESETS)} (or a JSON object)"
        )
    if slice_size is not None or seed is not None:
        spec = ShapeSpec(
            name=spec.name, ici=spec.ici, dcn=spec.dcn,
            slice_size=spec.slice_size if slice_size is None
            else int(slice_size),
            seed=spec.seed if seed is None else int(seed),
        )
    return spec


def _shape_from_dict(d: dict) -> ShapeSpec:
    def link(sub) -> LinkSpec:
        sub = sub or {}
        return LinkSpec(
            latency_s=float(sub.get("latency_s", 0.0)),
            bandwidth_Bps=float(sub.get("bandwidth_Bps", 0.0)),
            jitter_s=float(sub.get("jitter_s", 0.0)),
        )

    return ShapeSpec(
        name=str(d.get("name", "custom")),
        slice_size=int(d.get("slice_size", 8)),
        ici=link(d.get("ici")), dcn=link(d.get("dcn")),
        seed=int(d.get("seed", 0)),
    )


def classify_link(src: int, dst: int, slice_size: int) -> str:
    """``ici`` when both ranks sit in the same slice of ``slice_size``
    consecutive ranks, ``dcn`` otherwise — the same contiguous-slice
    convention the hierarchical communicator's mesh factory uses."""
    if slice_size <= 0:
        return LINK_ICI
    return (
        LINK_ICI if int(src) // int(slice_size) == int(dst) // int(slice_size)
        else LINK_DCN
    )


def deterministic_jitter(seed: int, src: int, dst: int, hop: int) -> float:
    """Uniform in ``[0, 1)`` as a pure function of the identifiers — the
    jitter term must replay identically, so no RNG state anywhere."""
    digest = hashlib.blake2b(
        struct.pack("<qqqq", int(seed), int(src), int(dst), int(hop)),
        digest_size=8,
    ).digest()
    return struct.unpack("<Q", digest)[0] / 2.0 ** 64


def transfer_time_s(nbytes: int, link: LinkSpec, u: float = 0.0) -> float:
    """Traversal time of one payload over one link: latency + serialization
    (``nbytes / bandwidth``) + ``u`` of the jitter ceiling."""
    t = float(link.latency_s)
    if link.bandwidth_Bps > 0:
        t += float(nbytes) / float(link.bandwidth_Bps)
    if link.jitter_s > 0:
        t += float(u) * float(link.jitter_s)
    return t


class LinkShaper:
    """Applies a :class:`ShapeSpec` to every hop of a world: classify the
    (src, dst) edge, compute the deterministic traversal time, consult the
    fault plan, sleep.  Thread-safe (a worker's intra and inter rings may
    hop concurrently); per-class byte/hop/sleep accounting for the drill
    verdicts."""

    def __init__(self, shape: ShapeSpec, world_size: int,
                 sleep=time.sleep, clock=time.monotonic):
        self.shape = shape
        self.world_size = int(world_size)
        self._sleep = sleep
        self._clock = clock
        self._lock = threading.Lock()
        #: slice index -> cut expiry (monotonic) for live partitions
        self._cuts: Dict[int, float] = {}
        self.stats: Dict[str, Dict[str, float]] = {
            LINK_ICI: {"hops": 0, "bytes": 0, "slept_s": 0.0},
            LINK_DCN: {"hops": 0, "bytes": 0, "slept_s": 0.0},
        }

    # ---- pure maths -----------------------------------------------------

    def classify(self, src: int, dst: int) -> str:
        return classify_link(src, dst, self.shape.slice_size)

    def link(self, src: int, dst: int) -> LinkSpec:
        return (self.shape.ici if self.classify(src, dst) == LINK_ICI
                else self.shape.dcn)

    def delay_s(self, src: int, dst: int, nbytes: int, hop: int = 0) -> float:
        """Deterministic traversal time for this hop (no side effects)."""
        u = deterministic_jitter(self.shape.seed, src, dst, hop)
        return transfer_time_s(nbytes, self.link(src, dst), u)

    # ---- fault composition ---------------------------------------------

    def _slice_of(self, rank: int) -> int:
        size = max(1, self.shape.slice_size)
        return int(rank) // size

    def check_faults(self, src: int, dst: int,
                     step: Optional[int] = None) -> None:
        """Raise if an armed ``podsim.link`` fault condemns this hop: a
        fresh ``drop`` fire eats it outright; a ``partition`` fire opens
        (or an earlier fire sustains) a timed cut of ``spec.rank``'s
        slice's DCN links."""
        plan = _inject.get_plan()
        now = self._clock()
        if plan is not None:
            spec = plan.should_fire(FAULT_POINT, step)
            if spec is not None:
                if spec.kind == "partition":
                    with self._lock:
                        self._cuts[int(spec.rank)] = max(
                            self._cuts.get(int(spec.rank), 0.0),
                            now + float(spec.duration_s),
                        )
                else:
                    raise LinkDropped(
                        f"podsim.link drop: hop {src}->{dst} payload lost "
                        f"(injected)"
                    )
        with self._lock:
            self._cuts = {s: e for s, e in self._cuts.items() if e > now}
            cuts = set(self._cuts)
        if cuts and self.classify(src, dst) == LINK_DCN and (
                self._slice_of(src) in cuts or self._slice_of(dst) in cuts):
            raise LinkSevered(
                f"podsim.link partition: DCN hop {src}->{dst} crosses a "
                f"severed slice ({sorted(cuts)})"
            )

    # ---- the hop --------------------------------------------------------

    def traverse(self, src: int, dst: int, nbytes: int, hop: int = 0,
                 step: Optional[int] = None) -> float:
        """One shaped hop: fault check, deterministic delay, accounting.
        Returns the injected delay in seconds."""
        self.check_faults(src, dst, step=step)
        d = self.delay_s(src, dst, nbytes, hop)
        if d > 0:
            self._sleep(d)
        cls = self.classify(src, dst)
        with self._lock:
            st = self.stats[cls]
            st["hops"] += 1
            st["bytes"] += int(nbytes)
            st["slept_s"] += d
        return d
