"""Killable coordinator process for the pod simulator.

:class:`~bagua_tpu.podsim.orchestrator.PodSim` runs the coordinator stack
*in-process*, which is perfect for measuring the control plane but makes
the coordinator unkillable — the failover drill needs to SIGKILL the
coordinator mid-training and watch a standby take over, so this module is
the same stack as a real OS process.  Executed as a *file* (``python
.../podsim/coordinator.py``) with the same jax-free namespace-package
shim as :mod:`~bagua_tpu.podsim.worker`.

Roles (``--coord-id`` indexes ``--store-endpoints``):

* coord-id 0 — boots as the store **primary** and the acting coordinator:
  hosts its :class:`TCPStoreServer` endpoint (recovering replicated state
  from peers on relaunch, and starting demoted if a takeover already
  moved the primary role), runs rendezvous rounds, polls member leases,
  ingests fleet records into the historian, feeds the autopilot engine,
  and renews the ``coord/lease`` leadership lease.
* coord-id >= 1 — boots as a **standby**: hosts a replication-follower
  store server and a :class:`StandbyCoordinatorWatch`; when the lease
  goes stale it promotes its store (generation fence) and then runs the
  SAME coordinator loop — the historian rings and autopilot policy state
  load from the replicated store, so trend windows and cooldowns RESUME.

Drill observability rides the store itself:

* ``coord/lease`` — who is coordinator NOW (node, seq, generation);
* ``podsim/coord/status`` — JSON heartbeat of the ACTING coordinator:
  role, epoch, tick count, store generation, historian series (total and
  loaded-at-construction), autopilot rung / actions_taken / resumed flag.
  The generation fence keeps a demoted ex-primary's status writes from
  ever reaching the group.

Exit codes: 0 halt, 5 demoted (an ex-primary observed the generation
fence after a partition — the double-primary row of the failure matrix),
3 error.
"""

import sys

if __package__ in (None, ""):  # pragma: no cover - subprocess entry
    import importlib.util
    import os

    _repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sys.path.insert(0, _repo)
    _spec = importlib.util.spec_from_loader(
        "bagua_tpu", loader=None, is_package=True)
    _pkg = importlib.util.module_from_spec(_spec)
    _pkg.__path__ = [os.path.join(_repo, "bagua_tpu")]
    sys.modules["bagua_tpu"] = _pkg

import argparse  # noqa: E402
import json  # noqa: E402
import logging  # noqa: E402
import time  # noqa: E402

from bagua_tpu.autopilot.engine import (  # noqa: E402
    STATE_STORE_KEY,
    AutopilotEngine,
)
from bagua_tpu.autopilot.policy import PolicyConfig  # noqa: E402
from bagua_tpu.contrib.utils.tcp_store import TCPStoreServer  # noqa: E402
from bagua_tpu.elastic import membership as mb  # noqa: E402
from bagua_tpu.elastic.coordinator import ElasticCoordinator  # noqa: E402
from bagua_tpu.elastic.failover import (  # noqa: E402
    CoordinatorLeaseKeeper,
    FailoverStore,
    StandbyCoordinatorWatch,
    parse_endpoints,
)
from bagua_tpu.obs.export import build_fleet_record  # noqa: E402
from bagua_tpu.obs.historian import Historian  # noqa: E402

logger = logging.getLogger("podsim.coordinator")

STATUS_KEY = "podsim/coord/status"

#: exit code when an ex-primary observes the generation fence
EXIT_DEMOTED = 5


def parse_args(argv=None) -> argparse.Namespace:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--store-endpoints", required=True,
                    help="comma-separated host:port replica group "
                         "(priority order; index 0 is the boot primary)")
    ap.add_argument("--coord-id", type=int, required=True,
                    help="this process's index into --store-endpoints")
    ap.add_argument("--world", type=int, required=True,
                    help="max worker nodes (worker ids 0..world-1)")
    ap.add_argument("--min-nnodes", type=int, default=1)
    ap.add_argument("--join-window", type=float, default=30.0)
    ap.add_argument("--timeout", type=float, default=120.0)
    ap.add_argument("--lease-ttl", type=float, default=4.0,
                    help="member lease TTL (the worker heartbeats)")
    ap.add_argument("--coord-lease-ttl", type=float, default=2.0,
                    help="coordinator leadership lease TTL")
    ap.add_argument("--takeover-grace", type=float, default=0.0,
                    help="member-lease grace after takeover "
                         "(0 = 2x --lease-ttl)")
    ap.add_argument("--tick", type=float, default=0.25)
    return ap.parse_args(argv)


def _endpoints(args):
    return parse_endpoints(
        [e.strip() for e in args.store_endpoints.split(",") if e.strip()])


def _write_status(store, payload: dict) -> None:
    try:
        store.set(STATUS_KEY, json.dumps(payload))
    except ConnectionError as e:
        # a fenced/unreachable status write is itself a signal the monitor
        # loop will act on (server demotion check) — never die over it
        logger.debug("status not written: %s", e)


def run_coordinator(args, server, store, *, takeover: bool) -> int:
    """The acting-coordinator loop: rendezvous rounds + lease tracking +
    historian/autopilot ingestion, until halt (0) or demotion (5).  On a
    ``takeover`` the current epoch's published world is ADOPTED (the
    fleet keeps training; nobody restarts) and the member leases are
    re-armed with the takeover grace window."""
    client = mb.MembershipClient(store, 0, args.world)
    endpoints = _endpoints(args)
    coord = ElasticCoordinator(
        client, args.min_nnodes, args.world,
        master_addr=endpoints[0][0], master_port=endpoints[0][1],
        join_window_s=args.join_window, timeout_s=args.timeout,
    )
    # state-resume proof: capture what the replicated store carried BEFORE
    # this process's own engine/historian start writing
    autopilot_resumed = store.get(STATE_STORE_KEY) is not None
    engine = AutopilotEngine(
        config=PolicyConfig(mode="observe", sustain=2, cooldown_s=0.0,
                            budget=8, staleness_s=60.0, suspect_ttl_s=30.0),
        store=store,
    )
    historian = Historian(capacity=2048, window_s=120.0, store=store)
    loaded_series = len(historian.metrics())
    grace = args.takeover_grace or 2.0 * args.lease_ttl
    role = "promoted" if takeover else "primary"
    logger.info("acting coordinator (%s): autopilot_resumed=%s, "
                "historian loaded %d series", role, autopilot_resumed,
                loaded_series)

    epoch = 0
    expect = None
    spec = None
    ticks = 0
    if takeover:
        # mid-epoch takeover: adopt the published world instead of forcing
        # a rendezvous — the whole point is that healthy workers never
        # notice the coordinator changed
        cur = client.current_epoch()
        if cur is not None:
            epoch = cur
            spec = client.read_world(cur)
    while True:
        if spec is None:
            spec = coord.run_round(epoch, expect=expect)
        tracker = mb.LeaseTracker(
            client, spec.epoch, sorted(spec.ranks), ttl_s=args.lease_ttl)
        if takeover:
            tracker.rearm(grace)
            takeover = False
        logger.info("monitoring epoch %d (%d nodes)", spec.epoch,
                    spec.nnodes)
        while True:
            if not server.is_primary:
                # generation fence observed: a standby promoted while we
                # were partitioned/paused — the replicated group already
                # rejected our late writes; stand down
                logger.warning(
                    "this coordinator was demoted (store generation moved "
                    "on); exiting as the fenced ex-primary")
                return EXIT_DEMOTED
            expired = tracker.poll()
            record = build_fleet_record(
                spec.epoch,
                {n: tracker.health_of(n) for n in sorted(spec.ranks)},
            )
            historian.ingest(record)
            engine.observe_snapshot(record)
            ticks += 1
            if ticks % 4 == 0:
                # keep the replicated policy/trend state fresh even when
                # no action fires — what a takeover must be able to resume
                engine._persist_state()
            _write_status(store, {
                "node": args.coord_id, "role": role,
                "generation": server.generation,
                "epoch": spec.epoch, "ticks": ticks,
                "historian_series": len(historian.metrics()),
                "historian_loaded_series": loaded_series,
                "autopilot_resumed": autopilot_resumed,
                "autopilot_rung": engine.state.rung,
                "autopilot_actions_taken": engine.state.actions_taken,
                "time_unix": time.time(),
            })
            if expired:
                reason = (f"no heartbeat for {args.lease_ttl:.1f}s "
                          f"(node(s) {expired})")
                client.publish_stop(
                    spec.epoch, mb.STOP_LEASE_EXPIRED, expired[0],
                    reason, rejoin=False, nodes=expired,
                )
                expect = set(spec.ranks) - set(expired)
                epoch = spec.epoch + 1
                spec = None
                logger.warning("%s; regrouping as epoch %d", reason, epoch)
                break
            if client.read_halt() is not None:
                logger.info("halt verdict read; coordinator exiting")
                return 0
            time.sleep(args.tick)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
    )
    args = parse_args(argv)
    endpoints = _endpoints(args)
    if not 0 <= args.coord_id < len(endpoints):
        print(f"--coord-id {args.coord_id} outside endpoint list",
              flush=True)
        return 2
    host, port = endpoints[args.coord_id]
    server = TCPStoreServer(
        host, port,
        peers=[e for i, e in enumerate(endpoints) if i != args.coord_id],
        role="primary" if args.coord_id == 0 else "standby",
    )
    store = FailoverStore(endpoints, connect_timeout_s=args.timeout)
    keeper = None
    watch = None
    try:
        # boot leadership: index 0 acts unless a takeover already moved
        # the primary role (peer recovery starts a relaunched 0 demoted)
        if args.coord_id == 0 and server.is_primary:
            keeper = CoordinatorLeaseKeeper(
                lambda: FailoverStore(endpoints, connect_timeout_s=10.0),
                args.coord_id, args.coord_lease_ttl,
                generation=server.generation,
            ).start()
            return run_coordinator(args, server, store, takeover=False)
        watch = StandbyCoordinatorWatch(
            FailoverStore(endpoints, connect_timeout_s=args.timeout),
            args.coord_id, args.coord_id, args.coord_lease_ttl,
        ).start()
        client = mb.MembershipClient(store, 0, args.world)
        logger.info("standby coordinator %d watching the leadership lease",
                    args.coord_id)
        while True:
            if watch.promoted:
                keeper = CoordinatorLeaseKeeper(
                    lambda: FailoverStore(endpoints, connect_timeout_s=10.0),
                    args.coord_id, args.coord_lease_ttl,
                    generation=watch.store.generation,
                ).start()
                return run_coordinator(args, server, store, takeover=True)
            try:
                if client.read_halt() is not None:
                    return 0
            except ConnectionError:
                pass  # group unreachable: the watch holds its clock too
            time.sleep(0.25)
    finally:
        if keeper is not None:
            keeper.stop()
        if watch is not None:
            watch.stop()
        store.close()
        server.stop()


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception:  # noqa: BLE001 - drill log must carry the cause
        import traceback

        traceback.print_exc()
        sys.exit(3)
