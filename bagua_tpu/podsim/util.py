"""Loopback plumbing shared by every multi-process harness (no jax).

``reserve_port`` exists because the repo grew four private copies of
"bind port 0, read the port, close the socket" (``tests/test_elastic.py``,
the obs HTTP tests, ``scripts/elastic_drill.py``, ``bagua_tpu.utils``),
and the copies collide: two fixtures that each bind-and-release can be
handed the SAME ephemeral port by the kernel before either rebinds it,
which is exactly the flake mode parallel process launch provokes.  The
central allocator keeps a process-wide ledger of every port it has handed
out, so within one orchestrating process no two callers ever receive the
same number — the kernel guarantees the port was free at reservation
time, the ledger guarantees we never double-book it ourselves.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional

__all__ = ["reserve_port", "reserve_ports", "store_barrier"]

#: every port this process has handed out (never reissued, even after the
#: consumer closed it — ephemeral ports are plentiful and a stale entry is
#: cheaper than a collision)
_HANDED_OUT: set = set()
_LOCK = threading.Lock()


def reserve_port(host: str = "127.0.0.1") -> int:
    """One free ephemeral port, never previously returned by this process.

    The port is *probed* (bound with ``SO_REUSEADDR``, then released), not
    held: the caller is expected to bind it promptly.  Cross-process races
    remain possible in principle — that is why servers built on this
    helper keep their ephemeral-fallback paths — but the common flake
    (one orchestrator handing the same port to two of its own children)
    is structurally gone."""
    for _ in range(128):
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((host, 0))
            port = s.getsockname()[1]
        with _LOCK:
            if port not in _HANDED_OUT:
                _HANDED_OUT.add(port)
                return port
    raise OSError(
        f"reserve_port: could not find an unissued ephemeral port on "
        f"{host} after 128 probes ({len(_HANDED_OUT)} already handed out)"
    )


def reserve_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    """``n`` distinct ports in one call (one per simulated node)."""
    return [reserve_port(host) for _ in range(int(n))]


def store_barrier(store, name: str, rank: int, world: int,
                  timeout_s: float = 60.0, poll_s: float = 0.05) -> None:
    """KV-store barrier for the pod simulator's data plane: every rank
    sets ``<name>/<rank>`` then polls until all ``world`` slots exist.
    Same single-mget-scan shape the elastic membership layer uses; the
    barrier key must be unique per (epoch, purpose) — the store has no
    deletes, so reuse would satisfy the barrier instantly."""
    store.set(f"{name}/{int(rank)}", b"1")
    keys = [f"{name}/{i}" for i in range(int(world))]
    deadline = time.monotonic() + float(timeout_s)
    while True:
        if all(v is not None for v in store.mget(keys)):
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"store_barrier {name!r}: rank {rank} waited "
                f"{timeout_s:.0f}s for {world} arrivals"
            )
        time.sleep(poll_s)


def wait_store_keys(store, keys: List[str], timeout_s: float = 60.0,
                    poll_s: float = 0.05) -> List[bytes]:
    """Poll one mget until every key exists; returns the values.  The
    address-exchange primitive ring transports rendezvous through."""
    deadline = time.monotonic() + float(timeout_s)
    while True:
        vals = store.mget(list(keys))
        if all(v is not None for v in vals):
            return vals
        if time.monotonic() > deadline:
            missing = [k for k, v in zip(keys, vals) if v is None]
            raise TimeoutError(
                f"wait_store_keys: {len(missing)} of {len(keys)} keys "
                f"missing after {timeout_s:.0f}s (first: {missing[:3]})"
            )
        time.sleep(poll_s)


def free_port_compat(low: int = 0, high: int = 0,
                     host: str = "127.0.0.1") -> Optional[int]:
    """Drop-in for the legacy ``utils.find_free_port`` signature (the
    range arguments were already ignored there); returns a reserved
    port."""
    del low, high
    return reserve_port(host)
