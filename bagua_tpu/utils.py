"""Small utilities: pytree flatten helpers, dtype mapping, rate tracking.

Counterpart of reference ``bagua/torch_api/utils.py`` (flatten/unflatten :10-54,
to_bagua_datatype :205, StatisticalAverage :251-368).  Flattening here operates
on JAX pytrees instead of torch tensor lists; the fused-param-storage helpers
(`flatten_module_params`) have no TPU analog because XLA owns layout — the
bucket layer (bagua_tpu/bucket.py) is the equivalent mechanism.
"""

from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .define import TensorDtype


def device_fence(tree):
    """Force every array in ``tree`` to completion and return the tree.

    ``jax.block_until_ready`` is not a reliable fence on tunneled/remote
    device transports (it can return while work is still queued on the far
    side), so benchmarks and sync points that must observe REAL completion
    read one element of each leaf back to the host — a readback cannot
    complete before the producing computation has."""
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "ravel"):
            np.asarray(jax.device_get(leaf.ravel()[:1]))
    return tree


def to_bagua_datatype(dtype) -> TensorDtype:
    """jnp/np dtype -> wire datatype name (reference utils.py:205-216)."""
    d = jnp.dtype(dtype)
    if d == jnp.float32:
        return TensorDtype.F32
    if d == jnp.float16:
        return TensorDtype.F16
    if d == jnp.bfloat16:
        return TensorDtype.BF16
    if d == jnp.uint8:
        return TensorDtype.U8
    if d == jnp.int32:
        return TensorDtype.I32
    if d == jnp.int64:
        return TensorDtype.I64
    raise ValueError(f"unsupported data type {dtype}.")


def from_bagua_datatype(dtype: TensorDtype):
    return {
        TensorDtype.F32: jnp.float32,
        TensorDtype.F16: jnp.float16,
        TensorDtype.BF16: jnp.bfloat16,
        TensorDtype.U8: jnp.uint8,
        TensorDtype.I32: jnp.int32,
        TensorDtype.I64: jnp.int64,
    }[TensorDtype(dtype)]


def flatten(arrays: List[jax.Array]) -> jax.Array:
    """Concatenate arrays into one flat 1-D buffer (reference utils.py:10-25)."""
    if len(arrays) == 0:
        return jnp.zeros((0,), dtype=jnp.float32)
    return jnp.concatenate([jnp.ravel(a) for a in arrays])


def unflatten(flat: jax.Array, like: List[jax.Array]) -> List[jax.Array]:
    """Split a flat buffer back into arrays shaped like ``like``
    (reference utils.py:28-43)."""
    outs = []
    offset = 0
    for a in like:
        n = a.size
        outs.append(jax.lax.dynamic_slice_in_dim(flat, offset, n).reshape(a.shape))
        offset += n
    return outs


def check_contiguous(sizes: List[int], offsets: List[int]) -> bool:
    off = 0
    for s, o in zip(sizes, offsets):
        if o != off:
            return False
        off += s
    return True


def apply_flattened_call(tree, call):
    leaves, treedef = jax.tree.flatten(tree)
    flat = flatten(leaves)
    flat = call(flat)
    return jax.tree.unflatten(treedef, unflatten(flat, leaves))


def average_by_removing_extreme_values(raw_score_list):
    """Robust mean: drop values > 3 sigma from the median-ish mean, like the
    reference's speed averaging (utils.py:219-248)."""
    score_list = np.asarray(raw_score_list, dtype=np.float64)
    while len(score_list) > 2:
        mean = score_list.mean()
        std = score_list.std()
        keep = np.abs(score_list - mean) <= 3 * std
        if keep.all():
            break
        score_list = score_list[keep]
    return float(score_list.mean()), float(score_list.std()), score_list.tolist()


class StatisticalAverage:
    """Exponentially time-bucketed rate tracker (reference utils.py:251-368).

    Records a cumulative value (e.g. samples processed) at wall-clock times and
    answers "average rate over the last T seconds" with power-of-two bucketing.
    """

    def __init__(self, last_update_time: float = None, records: List[float] = None,
                 record_tail: Tuple[float, float] = (0.0, 0.0)):
        self.last_update_time = time.time() if last_update_time is None else last_update_time
        self.records: List[float] = list(records) if records else []
        self.record_tail = record_tail

    def record_seconds(self) -> float:
        # buckets of 1, 2, 4, ... 2^(L-1) seconds cover 2^L - 1 seconds.
        # Claiming 2^L here would self-inflate: record()'s regrow loop runs
        # while 2^i <= total + elapsed, so an overcount of exactly one
        # second makes EVERY call grow the list by one bucket regardless of
        # elapsed time — unbounded, and 2.0 ** i overflows after ~1000
        # steps of training
        return 2.0 ** len(self.records) - 1.0 if self.records else 0.0

    def total_recording_time(self) -> float:
        tail_sec, _ = self.record_tail
        return self.record_seconds() + tail_sec

    def get_records_mean(self, last_n_seconds: float) -> float:
        if last_n_seconds <= 0:
            return 0.0
        records_seconds = self.record_seconds()
        tail_seconds, tail_mean = self.record_tail
        if len(self.records) == 0:
            return tail_mean
        if last_n_seconds < 1.0:
            return self.records[0]
        if last_n_seconds <= records_seconds:
            mean = 0.0
            cnt = int(math.floor(math.log2(last_n_seconds)))
            for i in range(cnt):
                mean += (2.0 ** i / last_n_seconds) * self.records[i]
            last_sec = last_n_seconds - 2.0 ** cnt + (2.0 ** cnt - sum(2.0 ** i for i in range(cnt)))
            mean += max(last_sec, 0.0) / last_n_seconds * self.records[min(cnt, len(self.records) - 1)]
            return mean
        mean = (records_seconds / max(last_n_seconds, 1e-9)) * (
            sum(2.0 ** i * r for i, r in enumerate(self.records)) / max(records_seconds, 1e-9)
        )
        remain = min(last_n_seconds - records_seconds, tail_seconds)
        mean += (remain / max(last_n_seconds, 1e-9)) * tail_mean
        return mean

    def record(self, val: float):
        if not math.isfinite(val):
            return  # a zero-dt window's inf rate would poison every mean
        now = time.time()
        elapsed = now - self.last_update_time
        new_records: List[float] = []
        total = self.total_recording_time()
        i = 0
        while 2.0 ** i <= total + elapsed:
            seconds = 2.0 ** i
            if seconds <= elapsed:
                new_records.append(val)
            else:
                mean = (elapsed / seconds) * val + ((seconds - elapsed) / seconds) * self.get_records_mean(seconds - elapsed)
                new_records.append(mean)
            i += 1
        tail_total = min(total + elapsed, 2.0 ** 10)
        tail_sec = max(tail_total - (2.0 ** (len(new_records)) - 1 if new_records else 0), 0.0)
        tail_mean = self.get_records_mean(tail_total) if tail_sec > 0 else 0.0
        self.records = new_records
        self.record_tail = (tail_sec, tail_mean)
        self.last_update_time = now

    def get(self, last_n_seconds: float) -> float:
        elapsed = time.time() - self.last_update_time
        if elapsed >= last_n_seconds:
            return 0.0
        return self.get_records_mean(last_n_seconds - elapsed) * (
            (last_n_seconds - elapsed) / last_n_seconds
        )

    def total(self) -> float:
        total_sec = self.total_recording_time()
        return self.get_records_mean(total_sec) * total_sec


def lru_get_or_build(cache: dict, max_entries: int, key, build):
    """The bounded insertion-ordered LRU idiom shared by the compiled-
    program caches (``models.generate``'s signature caches,
    ``serve.engine``'s program cache): pop-on-hit + re-insert moves the
    entry to most-recent, ``build()`` fills a miss, and eviction drops the
    oldest entries beyond ``max_entries`` (an evicted program just
    recompiles on its next use)."""
    value = cache.pop(key, None)
    if value is None:
        value = build()
    cache[key] = value
    while len(cache) > max_entries:
        cache.pop(next(iter(cache)))
    return value


def find_free_port(low: int = 20000, high: int = 65000) -> int:
    import socket

    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


logger = logging.getLogger("bagua_tpu")


def remat_wrap(block_cls, remat_policy=None):
    """Wrap a flax module class in ``nn.checkpoint`` with a NAMED policy —
    the single source of the policy-name map shared by the transformer and
    ResNet ``remat``/``remat_policy`` knobs (None = recompute everything;
    "dots" keeps dot_general results; "dots_no_batch" its no-batch-dims
    variant)."""
    import flax.linen as nn
    import jax

    policy = {
        None: None,
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_no_batch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }[remat_policy]
    return nn.checkpoint(block_cls, policy=policy)
