"""Telemetry producer: per-tensor gradient-readiness spans for the autotuner.

Counterpart of the reference's OpenTelemetry span pipeline: the Rust backend
opens a ``tensor_ready`` span per gradient as the backward pass marks it
(bagua-core-internal/src/lib.rs:305-308), a custom exporter POSTs the batch to
the autotune sidecar (bagua-opentelemetry/src/exporter/mod.rs:15-59), and the
service re-orders buckets by the observed readiness order
(service/autotune_service.py:274-294, autotune_task_manager.py:167-172).

Under XLA the backward pass is one fused program — there is no per-tensor
runtime event to hook.  What *is* observable, and is exactly the quantity the
consumer needs, is each tensor's position in the backward schedule: the cost
of backpropagating from the loss to that tensor alone.  Differentiating the
loss w.r.t. a single leaf compiles a program containing the full forward plus
the backward chain only as deep as that leaf, so its static cost (XLA's FLOP
count) grows monotonically with backward depth — tensors near the loss (ready
first) cost least.  We use that cost as the span timestamp: deterministic, no
timing noise, no instrumentation in the hot path.  Wall-clock execution time
is the fallback when the cost model is unavailable.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Union

logger = logging.getLogger(__name__)

# NOTE: jax is imported lazily inside the span functions — the launcher
# process consumes the counters below and must not pay (or depend on) a
# jax import just to count membership transitions.


class CounterSnapshot(dict):
    """A counters snapshot: a plain ``name -> value`` dict (so every
    existing consumer — JSON dumps, delta arithmetic — keeps working)
    carrying a monotonic ``collected_at`` stamp, so the metrics exporter
    and flight recorder can order/age snapshots without a second clock
    read racing the lock."""

    def __init__(self, values: Dict[str, Union[int, float]],
                 collected_at: float):
        super().__init__(values)
        self.collected_at = collected_at


class TelemetryCounters:
    """Process-wide named counters/gauges (thread-safe).

    The reference exports OTel metrics next to its spans; here the
    consumers are in-process (the elastic launcher's membership/resize
    accounting, the obs exporter, tests, the drill scripts' JSON
    artifacts), so a dict under a lock is the whole implementation.
    ``incr`` is for monotonic event counts (``elastic/resizes``),
    ``set_gauge`` for last-value readings (``elastic/world_nnodes``);
    every name is declared in
    :data:`bagua_tpu.obs.export.METRIC_REGISTRY` (bagua-lint's
    ``unregistered-counter`` rule enforces it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._values: Dict[str, Union[int, float]] = {}

    def incr(self, name: str, n: int = 1) -> Union[int, float]:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + n
            return self._values[name]

    def incr_many(self, updates: Dict[str, Union[int, float]]) -> None:
        """Batch increment under ONE lock acquisition — for writer loops
        (fault-plan arming, exporter self-accounting) that would otherwise
        take the lock once per metric."""
        with self._lock:
            for name, n in updates.items():
                self._values[name] = self._values.get(name, 0) + n

    def set_gauge(self, name: str, value: Union[int, float]) -> None:
        with self._lock:
            self._values[name] = value

    def get(self, name: str) -> Union[int, float]:
        with self._lock:
            return self._values.get(name, 0)

    def snapshot(self) -> CounterSnapshot:
        """Point-in-time copy with a monotonic ``collected_at`` stamp
        (still a plain dict to every old consumer)."""
        with self._lock:
            return CounterSnapshot(self._values, time.monotonic())

    def reset(self) -> None:
        with self._lock:
            self._values.clear()


#: process-wide registry (one per process, like the global watchdog)
counters = TelemetryCounters()


def _leaf_cost_flops(fn: Callable, leaf) -> Optional[float]:
    """Static FLOP count of ``jit(fn)(leaf)`` via XLA's cost model."""
    import jax

    try:
        compiled = jax.jit(fn).lower(leaf).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax returns [dict]
            analysis = analysis[0] if analysis else {}
        flops = analysis.get("flops")
        return float(flops) if flops is not None else None
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.debug("cost_analysis unavailable (%s)", e)
        return None


def _leaf_cost_walltime(fn: Callable, leaf, repeats: int = 3) -> float:
    import jax

    from .utils import device_fence

    compiled = jax.jit(fn)
    device_fence(compiled(leaf))  # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        # readback fence: block_until_ready is not reliable on tunneled
        # transports and would time only the dispatch
        device_fence(compiled(leaf))
        best = min(best, time.perf_counter() - t0)
    return best


def _first_use_costs(loss_fn, params, batch) -> Optional[List[float]]:
    """Readiness cost per leaf from ONE jaxpr trace (no compiles).

    Reverse-mode autodiff produces gradients in roughly the reverse of
    forward execution order, and a parameter's forward position is the index
    of the first equation consuming it — so readiness rank = descending
    first-use index.  One trace regardless of model size (BERT-Large has
    ~400 leaves; per-leaf compilation would block the first step for hours).
    """
    import jax

    leaves, _ = jax.tree_util.tree_flatten(params)
    try:
        closed = jax.make_jaxpr(lambda p: loss_fn(p, batch))(params)
    except Exception as e:  # pragma: no cover - loss_fn may need real arrays
        logger.debug("telemetry: trace failed (%s)", e)
        return None
    jaxpr = closed.jaxpr
    invars = jaxpr.invars[: len(leaves)]  # flattened params come first
    first_use = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.extend.core.Var):
                continue
            if v not in first_use:
                first_use[v] = i
    n = len(jaxpr.eqns) + 1
    # used earlier in forward -> gradient ready LATER -> larger cost
    return [float(n - first_use.get(v, n)) for v in invars]


def profile_tensor_execution_order(
    loss_fn: Callable,
    params: Any,
    batch: Any,
    max_tensors: int = 512,
    mode: str = "static",
) -> List[Dict]:
    """Measure per-tensor gradient readiness order; returns spans (dicts with
    the reference's ``BaguaCoreTelemetrySpan`` shape) sorted by readiness.

    ``loss_fn(params, batch) -> scalar`` must be the training loss;
    ``params`` the user-shaped param pytree.  ``mode="static"`` (default)
    derives the order from one jaxpr trace — O(1) compiles, safe to run
    inline.  ``mode="flops"`` compiles a grad-to-leaf program per tensor and
    uses XLA's FLOP count (more precise, one compile per leaf — only for
    offline analysis of small models).
    """
    import jax

    from .tensor import _name_of_path

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    names_all = [_name_of_path(path) for path, _ in flat]

    if mode not in ("static", "flops"):
        raise ValueError(f"unknown telemetry mode {mode!r}")

    if mode == "static":
        costs = _first_use_costs(loss_fn, params, batch)
        names = names_all
        if costs is None:
            mode = "flops"  # trace failed; fall through to measurement

    if mode == "flops":
        if len(flat) > max_tensors:
            logger.warning(
                "telemetry: profiling only the %d largest of %d tensors",
                max_tensors, len(flat),
            )
            flat = sorted(flat, key=lambda kv: -kv[1].size)[:max_tensors]
        names = [_name_of_path(path) for path, _ in flat]

        def grad_fns():
            for path, leaf in flat:

                def grad_wrt_leaf(v, _path=path):
                    patched = _set_leaf(params, _path, v)
                    return loss_fn(patched, batch)

                yield jax.grad(grad_wrt_leaf), leaf

        # one consistent unit across ALL leaves: FLOPs when the cost model
        # answers for every leaf, else wall-time nanoseconds for every
        # leaf — mixing units would produce a garbage ordering
        costs = []
        for g, leaf in grad_fns():
            cost = _leaf_cost_flops(g, leaf)
            if cost is None:
                costs = []
                break
            costs.append(cost)
        if not costs:
            costs = [
                _leaf_cost_walltime(g, leaf) * 1e9  # ns: int() keeps order
                for g, leaf in grad_fns()
            ]

    spans = [
        {
            "trace_id": 0,
            "action": "tensor_ready",
            "tensor_name": name,
            "start_time": int(cost),
            "end_time": int(cost),
        }
        for name, cost in zip(names, costs)
    ]
    spans.sort(key=lambda s: s["start_time"])
    return spans


def _set_leaf(tree, target_path, value):
    """Replace the leaf at ``target_path`` with ``value`` (functional)."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [value if path == target_path else leaf for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
