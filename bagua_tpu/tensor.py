"""Named-parameter registry: pytree leaves <-> stable tensor names.

Counterpart of the reference's ``BaguaTensor`` patching + ``bagua_build_params``
(/root/reference/bagua/torch_api/tensor.py:24-80,
/root/reference/bagua/torch_api/distributed.py:49-100).  The reference wraps
live ``torch.Tensor`` storage; in JAX a "tensor" is a pytree leaf, so the
registry records (name, path, shape, dtype) and the bucket layer works on
flattened segments.  ``bagua_mark_communication_ready`` has no analog: under
XLA the collective schedule is fixed at compile time and overlap is done by
the latency-hiding scheduler, not by readiness events.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .define import TensorDeclaration
from .utils import to_bagua_datatype


@dataclass(frozen=True)
class NamedParam:
    """One registered tensor: a named view onto a pytree leaf."""

    name: str
    path: Tuple  # jax key path into the tree
    shape: Tuple[int, ...]
    dtype: Any

    @property
    def numel(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    def declaration(self) -> TensorDeclaration:
        return TensorDeclaration(
            name=self.name, num_elements=self.numel, dtype=to_bagua_datatype(self.dtype)
        )


def _name_of_path(path) -> str:
    s = jax.tree_util.keystr(path)
    s = re.sub(r"[\[\]'\.]+", ".", s).strip(".")
    return s


def build_params(tree, reverse: bool = True) -> List[NamedParam]:
    """Collect named params in (by default) reversed traversal order.

    The reference registers gradients in reversed module order because that is
    roughly backward-execution order (distributed.py:93-100, base.py:37-49);
    we keep the same order so bucket contents line up with the reference's.
    """
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = [
        NamedParam(
            name=_name_of_path(path),
            path=path,
            shape=tuple(leaf.shape),
            dtype=jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") else leaf.dtype,
        )
        for path, leaf in leaves
    ]
    if reverse:
        out = list(reversed(out))
    # duplicate detection (reference lib.rs:280-295)
    names = [p.name for p in out]
    if len(set(names)) != len(names):
        dup = [n for n in names if names.count(n) > 1]
        raise ValueError(f"duplicate tensor names in model: {sorted(set(dup))}")
    return out


def leaves_by_name(tree) -> Dict[str, jax.Array]:
    return {
        _name_of_path(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]
    }


def tree_from_named(tree_like, named: Dict[str, jax.Array]):
    """Rebuild a tree shaped like ``tree_like`` taking leaves from ``named``
    (by name) when present."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(tree_like)
    leaves = []
    for path, leaf in paths:
        name = _name_of_path(path)
        leaves.append(named.get(name, leaf))
    return jax.tree_util.tree_unflatten(treedef, leaves)
