"""Rendezvous rounds: admit within a join window, decide the world size,
assign dense ranks, publish the spec.

One round per restart attempt.  The coordinator is the launcher that hosts
the restart store (``--node_rank 0``); it is a fixed point — the store and
the JAX coordination service live on its host, so elasticity covers every
OTHER node.  Round shape:

1. :meth:`ElasticCoordinator.run_round` bumps ``elastic/epoch`` (fencing
   out all attempt-N zombies), joins itself, then collects join requests.
2. The window closes early when all ``max_nnodes`` slots joined, or when
   every *expected* survivor of the previous attempt has re-registered
   (crash restarts don't pay the full window), otherwise at
   ``join_window_s``.  Below ``min_nnodes`` the coordinator keeps waiting —
   up to ``timeout_s``, then :class:`RendezvousTimeout`.
3. Admitted ids get dense node ranks in id order (the coordinator, id 0,
   is always rank 0 — the JAX coordinator address must stay valid), and
   the :class:`~bagua_tpu.elastic.membership.WorldSpec` is published.

Members call :func:`join_round`: register, poll for the spec, and either
get their rank or learn they were excluded (:class:`ExcludedFromRound` —
a node that missed the window is NOT hung on; it waits for the next epoch
and rejoins, which the coordinator notices mid-attempt and answers with a
coordinated resize at the next attempt boundary).
"""

from __future__ import annotations

import logging
import time
from typing import Iterable, List, Optional, Set

from .membership import MembershipClient, WorldSpec

logger = logging.getLogger("bagua_tpu.elastic")


class RendezvousTimeout(RuntimeError):
    """The round could not assemble ``min_nnodes`` nodes in time."""


class ExcludedFromRound(RuntimeError):
    """This node joined after the window closed; the published world does
    not include it.  Wait for the next epoch and rejoin — do not hang."""

    def __init__(self, epoch: int, node_id: int, spec: WorldSpec):
        super().__init__(
            f"node {node_id} missed the join window of epoch {epoch}: the "
            f"round closed with {spec.nnodes} node(s) {sorted(spec.ranks)}; "
            "standing by for the next round"
        )
        self.epoch = epoch
        self.spec = spec


class Halted(RuntimeError):
    """The coordinator published a terminal verdict; stop rendezvousing."""

    def __init__(self, verdict: dict):
        super().__init__(f"job halted: {verdict.get('reason', '')}")
        self.verdict = verdict


class ElasticCoordinator:
    """Runs on the store-hosting launcher; owns epoch advancement and the
    per-round admit/decide/publish sequence."""

    def __init__(
        self,
        client: MembershipClient,
        min_nnodes: int,
        max_nnodes: int,
        master_addr: str,
        master_port: int,
        join_window_s: float = 30.0,
        timeout_s: float = 300.0,
        poll_s: float = 0.2,
    ):
        if not (1 <= min_nnodes <= max_nnodes):
            raise ValueError(
                f"need 1 <= min_nnodes <= max_nnodes, got "
                f"{min_nnodes}:{max_nnodes}"
            )
        self.client = client
        self.min_nnodes = int(min_nnodes)
        self.max_nnodes = int(max_nnodes)
        self.master_addr = master_addr
        self.master_port = int(master_port)
        self.join_window_s = float(join_window_s)
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)

    def run_round(
        self, epoch: int, expect: Optional[Iterable[int]] = None
    ) -> WorldSpec:
        """Open epoch ``epoch``, admit joiners, publish and return the
        world spec.  ``expect``: node ids known to be coming back (the
        previous attempt's survivors) — once they have all joined the
        window closes early."""
        self.client.open_epoch(epoch)
        self.client.join(epoch)
        admitted = self._admit(epoch, set(expect) if expect else None)
        ranks = {nid: rank for rank, nid in enumerate(sorted(admitted))}
        spec = WorldSpec(
            epoch=int(epoch),
            ranks=ranks,
            min_nnodes=self.min_nnodes,
            max_nnodes=self.max_nnodes,
            master_addr=self.master_addr,
            master_port=self.master_port,
        )
        self.client.publish_world(spec)
        logger.info(
            "rendezvous epoch %d: admitted %d node(s) %s (window %.1fs, "
            "min:max %d:%d)", epoch, spec.nnodes, sorted(admitted),
            self.join_window_s, self.min_nnodes, self.max_nnodes,
        )
        return spec

    def _admit(self, epoch: int, expect: Optional[Set[int]]) -> List[int]:
        t0 = time.monotonic()
        window_end = t0 + self.join_window_s
        deadline = t0 + self.timeout_s
        while True:
            joined = self.client.joined_ids(epoch)
            now = time.monotonic()
            if len(joined) >= self.max_nnodes:
                return joined[: self.max_nnodes]
            # early close on expected survivors must still respect the MIN
            # floor: after a lease expiry the survivor set can be smaller
            # than min_nnodes, and assembling it would under-shrink the job
            if (
                expect is not None
                and expect.issubset(joined)
                and len(joined) >= self.min_nnodes
            ):
                return joined
            if now >= window_end and len(joined) >= self.min_nnodes:
                return joined
            if now >= deadline:
                raise RendezvousTimeout(
                    f"rendezvous epoch {epoch} timed out after "
                    f"{self.timeout_s:.0f}s with {len(joined)} node(s) "
                    f"{joined} joined; min_nnodes={self.min_nnodes} — "
                    "start more nodes or lower --nnodes MIN"
                )
            time.sleep(self.poll_s)

    def standby_ids(self, spec: WorldSpec) -> List[int]:
        """Mid-attempt scan: node ids that registered for the CURRENT epoch
        but are not members — standbys asking for a scale-up.  The caller
        forces a coordinated resize at the next attempt boundary."""
        return [
            i for i in self.client.joined_ids(spec.epoch)
            if i not in spec.ranks
        ]


def join_round(
    client: MembershipClient,
    epoch: int,
    timeout_s: float = 300.0,
    poll_s: float = 0.2,
) -> WorldSpec:
    """Member-side rendezvous: register for ``epoch`` (following the fence
    if the coordinator has already moved on) and poll for the published
    world.  Returns the spec this node is part of; raises
    :class:`ExcludedFromRound` when the round closed without it,
    :class:`RendezvousTimeout` when nothing is published in time, and
    :class:`Halted` when the job has a terminal verdict."""
    deadline = time.monotonic() + timeout_s
    client.join(epoch)
    while True:
        halt = client.read_halt()
        if halt is not None:
            raise Halted(halt)
        fence = client.current_epoch()
        if fence is not None and fence > epoch:
            # the round moved on while we were tearing down: re-register
            # under the live epoch (our old join key is fenced garbage)
            epoch = fence
            client.join(epoch)
            continue
        spec = client.read_world(epoch)
        if spec is not None:
            if client.node_id in spec.ranks:
                return spec
            raise ExcludedFromRound(epoch, client.node_id, spec)
        if time.monotonic() > deadline:
            raise RendezvousTimeout(
                f"node {client.node_id} waited {timeout_s:.0f}s for the "
                f"world spec of epoch {epoch} — coordinator gone or "
                "rendezvous wedged"
            )
        time.sleep(poll_s)


def wait_for_next_epoch(
    client: MembershipClient,
    after_epoch: int,
    timeout_s: float = 300.0,
    poll_s: float = 0.2,
) -> int:
    """Block until the coordinator opens an epoch newer than
    ``after_epoch`` (or the job halts).  Used by excluded/standby nodes and
    by survivors waiting out a teardown."""
    deadline = time.monotonic() + timeout_s
    while True:
        halt = client.read_halt()
        if halt is not None:
            raise Halted(halt)
        fence = client.current_epoch()
        if fence is not None and fence > after_epoch:
            return fence
        if time.monotonic() > deadline:
            raise RendezvousTimeout(
                f"no epoch after {after_epoch} opened within "
                f"{timeout_s:.0f}s — coordinator gone"
            )
        time.sleep(poll_s)
