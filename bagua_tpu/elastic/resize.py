"""Worker-side resize hooks: consume the renegotiated ``BAGUA_*`` env,
rebuild the mesh, drive the checkpoint restore onto the new topology, and
re-split the data shard.

A worker spawned after a rendezvous round sees the standard env protocol
(``RANK``/``WORLD_SIZE``/``BAGUA_COORDINATOR_ADDR``) already rewritten for
the renegotiated world, plus the ``BAGUA_ELASTIC_*`` block describing the
round itself.  Nothing here mutates a live mesh — XLA worlds are static;
the hooks run at (re)start, which is the only honest resize point.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Any, Optional, Tuple

logger = logging.getLogger("bagua_tpu.elastic")


@dataclass(frozen=True)
class ElasticContext:
    """The ``BAGUA_ELASTIC_*`` env block, parsed.  ``enabled`` is False for
    non-elastic launches (every field then holds its fixed-world value), so
    workers can call :meth:`from_env` unconditionally."""

    enabled: bool
    epoch: int
    node_id: int
    rank: int
    world_size: int
    min_nnodes: int
    max_nnodes: int
    store_addr: Optional[str]
    # replicated restart store, comma-separated host:port (empty in
    # single-store mode): worker-side store writers (leave intent, drill
    # verdicts) should prefer this over ``store_addr`` so they survive a
    # coordinator takeover happening underneath them
    store_endpoints: str = ""

    @classmethod
    def from_env(cls) -> "ElasticContext":
        e = os.environ
        rank = int(e.get("RANK", "0"))
        world = int(e.get("WORLD_SIZE", "1"))
        return cls(
            enabled=e.get("BAGUA_ELASTIC") == "1",
            epoch=int(e.get("BAGUA_ELASTIC_EPOCH", "0")),
            node_id=int(e.get("BAGUA_ELASTIC_NODE_ID", e.get("NODE_RANK", "0"))),
            rank=rank,
            world_size=world,
            min_nnodes=int(e.get("BAGUA_ELASTIC_MIN_NNODES", "1")),
            max_nnodes=int(e.get("BAGUA_ELASTIC_MAX_NNODES", str(world))),
            store_addr=e.get("BAGUA_ELASTIC_STORE_ADDR"),
            store_endpoints=e.get("BAGUA_RESTART_STORE_ENDPOINTS", ""),
        )

    def init_process_group(self, **kwargs):
        """Rebuild the mesh/communicator for the renegotiated world — a
        plain :func:`bagua_tpu.init_process_group` call; the renegotiated
        env is already in place, this hook only names the intent."""
        import bagua_tpu

        mesh = bagua_tpu.init_process_group(**kwargs)
        if self.enabled:
            logger.info(
                "elastic worker up: epoch %d, rank %d/%d (node id %d, "
                "min:max %d:%d)", self.epoch, self.rank, self.world_size,
                self.node_id, self.min_nnodes, self.max_nnodes,
            )
        return mesh


def shard_bounds(total: int, rank: int, world_size: int) -> Tuple[int, int]:
    """Contiguous, balanced re-split of ``total`` samples for this rank
    after a world-size change: every rank gets ``total // world_size``,
    the first ``total % world_size`` ranks one extra.  Deterministic in
    ``(total, rank, world_size)`` only, so every member of a renegotiated
    world derives the identical partition with no extra coordination."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} outside world of {world_size}")
    base, rem = divmod(total, world_size)
    lo = rank * base + min(rank, rem)
    return lo, lo + base + (1 if rank < rem else 0)


def elastic_restore(
    manager,
    state_like: Any,
    expect_metadata: Optional[dict] = None,
    mesh: Optional[Any] = None,
) -> Tuple[Optional[int], Any]:
    """Drive :meth:`BaguaCheckpointManager.try_restore` onto the (possibly
    resized) topology, surfacing the topology transition in the log.

    The restore itself is topology-agnostic for plan-independent (leaf)
    layouts — the checkpoint manager rebuilds shardings for the live mesh.
    Pass ``mesh`` (the LIVE mesh of the renegotiated world) whenever the
    caller has it: on a topology change the checkpoint file's recorded
    shardings describe devices that no longer exist, and the restore must
    be anchored to the new mesh, not to what the file remembers.

    What this hook adds beyond ``try_restore`` is the membership story: it
    reads the layout sidecar of the step being restored and reports
    ``saved world -> live world``, and it strips ``world_size`` from the
    expectation for
    plan-independent layouts so an elastic restart does not trip the
    "metadata differs" warning on the one field that is SUPPOSED to differ.
    Plan-dependent (ZeRO flat) layouts keep the strict check: those
    checkpoints genuinely cannot cross topologies, and the manager's
    actionable error must fire."""
    step = manager.latest_step()
    if step is None:
        return None, state_like
    saved = manager._read_layout(step)
    expected = expect_metadata
    if (
        expected is not None
        and not expected.get("plan_dependent")
        and (saved is None or not saved.get("plan_dependent"))
    ):
        expected = {k: v for k, v in expected.items() if k != "world_size"}
    if saved is not None and expect_metadata is not None:
        was, now = saved.get("world_size"), expect_metadata.get("world_size")
        if was != now:
            logger.info(
                "elastic restore: checkpoint step %d saved at world_size=%s, "
                "restoring onto world_size=%s", step, was, now,
            )
    return manager.restore(
        state_like, step=step, expect_metadata=expected, mesh=mesh
    )
