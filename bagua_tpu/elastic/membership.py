"""Lease-based node registry on the restart KV store.

The store (``contrib.utils.tcp_store``) is a plain KV service: no TTLs, no
deletes, no key scans, no compare-and-swap.  The registry builds leases and
fencing out of what it does have:

* **Epoch fencing** — every key is namespaced by the rendezvous epoch
  (= restart attempt number): ``elastic/<epoch>/...``.  A zombie launcher
  or worker from attempt N keeps writing into N's keyspace, which nobody
  reads once the coordinator has bumped ``elastic/epoch`` to N+1 — stale
  writers cannot corrupt the next attempt, they only talk to themselves.
* **Enumerable node ids** — a node's stable identity is its launcher's
  ``--node_rank`` in ``[0, max_nnodes)``.  The store cannot list keys, but
  the coordinator can ``mget`` all ``max_nnodes`` possible slots, which
  makes membership scans one round-trip.
* **Leases without synchronized clocks** — members write a monotonically
  increasing heartbeat *sequence number*; the coordinator timestamps each
  observed change with ITS OWN clock and expires a lease when the sequence
  has not advanced for ``ttl_s``.  No cross-host clock comparison ever
  happens, so clock skew cannot produce false expiries.

Key layout (all under the restart store)::

    elastic/epoch                current epoch, coordinator-owned (fence)
    elastic/halt                 terminal verdict {code, reason} — job over
    elastic/<e>/join/<id>        join request {node_id, host, pid}
    elastic/<e>/world            published WorldSpec (see class below)
    elastic/<e>/hb/<id>          heartbeat sequence number — either the bare
                                 integer or ``{"seq": n, "health": {...}}``
                                 when the node publishes a health payload
                                 (grad-guard / async-staleness event counts)
    elastic/<e>/stop             first stop event of the attempt
                                 {kind, node, reason}; kinds: fail,
                                 lease_expired, leave, resize, health_fenced
    elastic/<e>/leave/<id>       leave intent (deliberate departure —
                                 watchdog exit, SIGINT — vs a silent hang)
    elastic/<e>/done/<id>        clean completion marker
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import env as _env
from ..faults import inject as _inject
from ..telemetry import counters as _counters

logger = logging.getLogger("bagua_tpu.elastic")

# stop-event kinds (the first event of an attempt wins; every launcher
# tears its gang down on whichever it observes)
STOP_FAIL = "fail"                    # a worker crashed
STOP_LEASE_EXPIRED = "lease_expired"  # a node's launcher went silent
STOP_LEAVE = "leave"                  # deliberate departure (watchdog, ^C)
STOP_RESIZE = "resize"                # standby joined; regroup at n+standby
STOP_HEALTH = "health_fenced"         # heartbeat health payload over limit


def _k_epoch() -> str:
    return "elastic/epoch"


def _k_halt() -> str:
    return "elastic/halt"


def _k_join(epoch: int, node_id: int) -> str:
    return f"elastic/{epoch}/join/{node_id}"


def _k_world(epoch: int) -> str:
    return f"elastic/{epoch}/world"


def _k_hb(epoch: int, node_id: int) -> str:
    return f"elastic/{epoch}/hb/{node_id}"


def _k_stop(epoch: int) -> str:
    return f"elastic/{epoch}/stop"


def _k_leave(epoch: int, node_id: int) -> str:
    return f"elastic/{epoch}/leave/{node_id}"


def _k_done(epoch: int, node_id: int) -> str:
    return f"elastic/{epoch}/done/{node_id}"


@dataclass
class WorldSpec:
    """The renegotiated world published by the coordinator for one epoch:
    which node ids are in, and the dense rank each one got."""

    epoch: int
    ranks: Dict[int, int]  # stable node id -> dense node rank
    min_nnodes: int
    max_nnodes: int
    master_addr: str
    master_port: int

    @property
    def nnodes(self) -> int:
        return len(self.ranks)

    def rank_of(self, node_id: int) -> Optional[int]:
        return self.ranks.get(node_id)

    def to_json(self) -> str:
        d = dict(self.__dict__)
        d["ranks"] = {str(k): v for k, v in self.ranks.items()}
        return json.dumps(d)

    @classmethod
    def from_json(cls, raw: bytes) -> "WorldSpec":
        d = json.loads(raw)
        d["ranks"] = {int(k): int(v) for k, v in d["ranks"].items()}
        return cls(**d)


class MembershipClient:
    """Typed view of the elastic keyspace over any store exposing
    ``set``/``get``/``mget`` (the launcher's reconnecting ``_RestartStore``
    or a raw :class:`~bagua_tpu.contrib.utils.tcp_store.TCPStore`)."""

    def __init__(self, store, node_id: int, max_nnodes: int):
        self.store = store
        self.node_id = int(node_id)
        self.max_nnodes = int(max_nnodes)

    # -- epoch fence --------------------------------------------------------

    def current_epoch(self) -> Optional[int]:
        v = self.store.get(_k_epoch())
        return int(v) if v is not None else None

    def open_epoch(self, epoch: int) -> None:
        """Coordinator-only: advance the fence.  Readers of any older
        epoch's keyspace are now talking to the void."""
        self.store.set(_k_epoch(), str(int(epoch)))

    # -- join / world -------------------------------------------------------

    def join(self, epoch: int, info: Optional[dict] = None) -> None:
        payload = {
            "node_id": self.node_id,
            "host": socket.gethostname(),
            "pid": os.getpid(),
        }
        if info:
            payload.update(info)
        self.store.set(_k_join(epoch, self.node_id), json.dumps(payload))

    def joined_ids(self, epoch: int) -> List[int]:
        keys = [_k_join(epoch, i) for i in range(self.max_nnodes)]
        vals = self.store.mget(keys)
        return [i for i, v in enumerate(vals) if v is not None]

    def publish_world(self, spec: WorldSpec) -> None:
        self.store.set(_k_world(spec.epoch), spec.to_json())

    def read_world(self, epoch: int) -> Optional[WorldSpec]:
        v = self.store.get(_k_world(epoch))
        return WorldSpec.from_json(v) if v is not None else None

    # -- heartbeats ---------------------------------------------------------

    def beat(self, epoch: int, seq: int,
             health: Optional[dict] = None) -> None:
        """Publish this node's heartbeat.  ``health`` (optional) rides the
        same key as a JSON payload — the cheapest channel to the
        coordinator that already exists and already has freshness
        semantics: a stale health report expires with its lease."""
        if health is None:
            payload = str(int(seq))
        else:
            payload = json.dumps({"seq": int(seq), "health": health})
        self.store.set(_k_hb(epoch, self.node_id), payload)

    @staticmethod
    def _parse_beat(v) -> Tuple[Optional[int], Optional[dict]]:
        """One heartbeat value -> (seq, health): accepts both the bare
        integer wire format (pre-health nodes keep working) and the JSON
        payload; unparseable values read as no-beat rather than crashing
        the monitor."""
        if v is None:
            return None, None
        try:
            return int(v), None
        except (TypeError, ValueError):
            pass
        try:
            d = json.loads(v)
            return int(d["seq"]), d.get("health")
        except (TypeError, ValueError, KeyError):
            logger.warning("unparseable heartbeat value %r ignored", v)
            return None, None

    def read_beats(self, epoch: int, node_ids: List[int]) -> Dict[int, Optional[int]]:
        return {
            i: seq
            for i, (seq, _) in self.read_beats_full(epoch, node_ids).items()
        }

    def read_beats_full(
        self, epoch: int, node_ids: List[int]
    ) -> Dict[int, Tuple[Optional[int], Optional[dict]]]:
        """Heartbeat sequence AND health payload per node (None, None for a
        node that never beat)."""
        vals = self.store.mget([_k_hb(epoch, i) for i in node_ids])
        return {i: self._parse_beat(v) for i, v in zip(node_ids, vals)}

    # -- stop / leave / done / halt ----------------------------------------

    def publish_stop(self, epoch: int, kind: str, node: int, reason: str,
                     rejoin: bool = True,
                     nodes: Optional[List[int]] = None) -> None:
        """``rejoin=False`` marks the named node(s) as NOT coming back
        (their launchers are gone — lease expiry, operator ^C), so the next
        round's early-close set excludes them instead of waiting the full
        window.  ``nodes`` names EVERY affected node when one event covers
        several (a rack loss expiring multiple leases in one poll);
        ``node`` stays the representative for logs."""
        self.store.set(
            _k_stop(epoch),
            json.dumps({"kind": kind, "node": int(node), "reason": reason,
                        "rejoin": bool(rejoin),
                        "nodes": [int(n) for n in (nodes or [node])]}),
        )

    def read_stop(self, epoch: int) -> Optional[dict]:
        v = self.store.get(_k_stop(epoch))
        return json.loads(v) if v is not None else None

    def publish_leave(self, epoch: int, reason: str) -> None:
        self.store.set(_k_leave(epoch, self.node_id), reason)

    def read_leave(self, epoch: int, node_id: int) -> Optional[str]:
        v = self.store.get(_k_leave(epoch, node_id))
        return v.decode() if v is not None else None

    def publish_done(self, epoch: int) -> None:
        self.store.set(_k_done(epoch, self.node_id), b"1")

    def done_ids(self, epoch: int, node_ids: List[int]) -> List[int]:
        vals = self.store.mget([_k_done(epoch, i) for i in node_ids])
        return [i for i, v in zip(node_ids, vals) if v is not None]

    def publish_halt(self, code: int, reason: str) -> None:
        self.store.set(
            _k_halt(), json.dumps({"code": int(code), "reason": reason})
        )

    def read_halt(self) -> Optional[dict]:
        v = self.store.get(_k_halt())
        return json.loads(v) if v is not None else None


# ---- health payload -------------------------------------------------------

#: telemetry counters that ride the heartbeat as the health payload: events
#: that mark a rank as a liability to the fleet (non-finite gradient steps
#: from the grad-guard sentinel, async model-average rounds the rank failed
#: to apply, its current staleness gauge)
_HEALTH_COUNTERS = {
    "grad_unhealthy": "grad_guard/unhealthy_steps",
    "grad_skipped": "grad_guard/skipped_steps",
    "async_missed": "async/missed_boundaries",
    "async_staleness": "async/staleness_max",
}


def local_health_snapshot() -> Optional[dict]:
    """This process's health payload from the telemetry counters — None
    when every counter is zero AND no obs summary exists, so idle/healthy
    non-training processes pay no payload bytes.

    A training process additionally rides its per-rank fleet-view summary
    (``obs`` key: step, step-dt percentiles, staleness, skip counts — see
    :func:`bagua_tpu.obs.export.local_obs_summary`) on the same channel;
    the fence scalar (:func:`health_event_count`) ignores it."""
    snap = {
        k: _counters.get(name) for k, name in _HEALTH_COUNTERS.items()
    }
    snap = {k: v for k, v in snap.items() if v}
    try:
        from ..obs.export import local_obs_summary

        obs = local_obs_summary()
    except Exception:  # noqa: BLE001 - health snapshots must never die
        obs = None
    if obs:
        snap["obs"] = obs
    return snap or None


def health_event_count(health: Optional[dict]) -> int:
    """The scalar the coordinator fences on: how many times this rank hurt
    the fleet — non-finite-gradient steps plus missed async negotiation
    rounds (staleness gauges are a symptom, not an event count)."""
    if not health:
        return 0
    return int(health.get("grad_unhealthy", 0)) + int(
        health.get("async_missed", 0)
    )


def write_health_beacon(path: Optional[str] = None) -> bool:
    """Publish this process's health snapshot to the beacon file named by
    ``BAGUA_ELASTIC_HEALTH_FILE`` (launcher-injected) so the LAUNCHER's
    lease heartbeat — a different process — can carry it to the
    coordinator.  Atomic (tmp + ``os.replace``) and exception-free: the
    callers are the trainer's health paths, which must never die on a full
    disk.  No-op (False) when no beacon path is configured."""
    p = path or _env.get_elastic_health_file()
    if not p:
        return False
    try:
        snap = local_health_snapshot() or {}
        tmp = f"{p}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        os.replace(tmp, p)
        return True
    except OSError as e:
        logger.debug("health beacon not written: %s", e)
        return False


def file_health_source(path: str) -> Callable[[], Optional[dict]]:
    """Health source reading a worker's beacon file — the launcher side of
    :func:`write_health_beacon`.  Missing/torn files read as healthy (the
    beacon only exists once something went wrong)."""

    def read() -> Optional[dict]:
        try:
            with open(path) as f:
                data = json.load(f)
            return data or None
        except (OSError, ValueError):
            return None

    return read


def merged_health_source(
    paths: List[str],
) -> Callable[[], Optional[dict]]:
    """Health source merging every local worker's beacon into one node
    payload (the launcher injects one beacon file PER local rank — a file
    shared across workers would be last-writer-wins, hiding all but one
    worker's events from the fence).  Event counts sum across workers;
    staleness gauges take the max; per-rank ``obs`` fleet-view summaries
    are kept side by side, keyed by each worker's global rank (the
    coordinator's fleet snapshot wants per-rank step/dt, not a sum)."""
    readers = [file_health_source(p) for p in paths]

    def read() -> Optional[dict]:
        merged: dict = {}
        for i, reader in enumerate(readers):
            snap = reader()
            if not snap:
                continue
            for key, val in snap.items():
                if key == "obs":
                    if isinstance(val, dict):
                        merged.setdefault("obs", {})[
                            str(val.get("rank", i))
                        ] = val
                elif key == "async_staleness":
                    merged[key] = max(int(merged.get(key, 0)), int(val))
                else:
                    merged[key] = int(merged.get(key, 0)) + int(val)
        return merged or None

    return read


class LeaseHeartbeat:
    """Per-node heartbeat thread: bumps this node's sequence number every
    ``interval_s`` on its OWN store connection (the monitor loop shares the
    launcher's main connection; a slow mget there must not delay beats).

    Epoch-fenced: each beat re-reads ``elastic/epoch`` and the thread stops
    itself the moment the coordinator has moved past the epoch it was
    started for — a zombie cannot keep a stale lease looking alive.

    Each beat also carries a **health payload** from ``health_source`` —
    default: this process's :func:`local_health_snapshot` (grad-guard and
    async-staleness event counters).  The launcher passes a
    :func:`file_health_source` reading the worker's beacon file instead.
    The coordinator's :class:`LeaseTracker` surfaces the payload and can
    fence chronically unhealthy members through the same stop/resize
    machinery that handles lease expiry."""

    def __init__(self, connect, node_id: int, epoch: int,
                 interval_s: float = 2.0, max_nnodes: int = 1,
                 health_source: Optional[Callable[[], Optional[dict]]] = None):
        self._connect = connect  # () -> store client
        self._node_id = int(node_id)
        self._epoch = int(epoch)
        self._interval_s = float(interval_s)
        self._max_nnodes = int(max_nnodes)
        self._health_source = (
            health_source if health_source is not None
            else local_health_snapshot
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"bagua-elastic-hb-{node_id}", daemon=True
        )

    def start(self) -> "LeaseHeartbeat":
        self._thread.start()
        return self

    def _run(self) -> None:
        client = None
        seq = 0
        while not self._stop.wait(self._interval_s):
            try:
                if client is None:
                    client = MembershipClient(
                        self._connect(), self._node_id, self._max_nnodes
                    )
                fence = client.current_epoch()
                if fence is not None and fence != self._epoch:
                    logger.info(
                        "heartbeat: epoch moved %d -> %d; node %d stops "
                        "beating into the old keyspace",
                        self._epoch, fence, self._node_id,
                    )
                    return
                if _inject.should_drop_heartbeat():
                    # chaos: an armed ``elastic.heartbeat`` fault starves
                    # this node's lease (the sequence number stops
                    # advancing) without killing any process — the
                    # coordinator must expire it and shrink the world
                    continue
                try:
                    health = self._health_source()
                except Exception as e:  # noqa: BLE001 - beats must survive
                    logger.debug("health source failed: %s", e)
                    health = None
                seq += 1
                client.beat(self._epoch, seq, health=health)
            except (ConnectionError, OSError, TimeoutError):
                client = None  # reconnect on the next tick

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


@dataclass
class _LeaseState:
    seq: Optional[int] = None
    changed_at: float = field(default_factory=time.monotonic)


class LeaseTracker:
    """Coordinator-side lease bookkeeping: a member's lease expires when its
    heartbeat sequence stops advancing for ``ttl_s`` (measured on the
    coordinator's monotonic clock — no cross-host time comparison).  The
    first ``ttl_s`` after construction is a grace period: a member whose
    first beat is still in flight is not declared dead.

    Each poll also harvests the members' heartbeat **health payloads**
    (:meth:`health_of`); with ``fence_unhealthy_after`` set,
    :meth:`unhealthy_members` names members whose reported event count
    (:func:`health_event_count`) reached the limit — the monitor converts
    them into a ``health_fenced`` stop, reusing the exact epoch/resize
    machinery lease expiry rides.

    ``observe_only_ids`` are polled for health but never lease-expired:
    the coordinator cannot meaningfully expire its own lease (a dead
    launcher cannot run the monitor at all), but it CAN read its own
    heartbeat's health payload — without this the fence has a silent
    coverage hole on exactly the coordinator node."""

    def __init__(self, client: MembershipClient, epoch: int,
                 member_ids: List[int], ttl_s: float = 10.0,
                 fence_unhealthy_after: Optional[int] = None,
                 observe_only_ids: Optional[List[int]] = None):
        self._client = client
        self._epoch = int(epoch)
        self._ttl_s = float(ttl_s)
        self._leases = {int(i): _LeaseState() for i in member_ids}
        self._observe_only = [
            int(i) for i in (observe_only_ids or ())
            if int(i) not in self._leases
        ]
        self._health: Dict[int, dict] = {}
        self._grace_until = 0.0
        if fence_unhealthy_after is not None and fence_unhealthy_after < 1:
            fence_unhealthy_after = None
        self._fence_unhealthy_after = fence_unhealthy_after

    def rearm(self, grace_s: Optional[float] = None) -> None:
        """Re-arm every member lease against THIS tracker's clock.

        A promoted standby coordinator calls this at takeover: the dead
        primary's lease timestamps died with its process, and the members'
        heartbeats spent the failover window retrying against a fenced
        store — judging their last-seen sequence numbers as ``ttl_s`` old
        would mass-expire a perfectly healthy fleet.  Re-arming stamps
        every lease ``now`` and (with ``grace_s > ttl_s``) additionally
        suspends expiry until the takeover grace window has passed, giving
        queued heartbeats time to drain to the promoted store."""
        now = time.monotonic()
        for lease in self._leases.values():
            lease.changed_at = now
        if grace_s is not None and grace_s > self._ttl_s:
            self._grace_until = now + float(grace_s)
        _counters.incr("elastic/lease_rearms", len(self._leases))

    def poll(self) -> List[int]:
        """One scan; returns member ids whose lease has expired."""
        beats = self._client.read_beats_full(
            self._epoch, list(self._leases) + self._observe_only
        )
        now = time.monotonic()
        for node_id in self._observe_only:
            _seq, health = beats.get(node_id, (None, None))
            if health is not None:
                self._health[node_id] = health
        expired = []
        in_grace = now < self._grace_until
        for node_id, lease in self._leases.items():
            seq, health = beats.get(node_id, (None, None))
            if health is not None:
                self._health[node_id] = health
            if seq is not None and seq != lease.seq:
                lease.seq = seq
                lease.changed_at = now
            elif not in_grace and now - lease.changed_at > self._ttl_s:
                expired.append(node_id)
        return expired

    def health_of(self, node_id: int) -> Optional[dict]:
        """Latest health payload observed for ``node_id`` (None = the node
        never reported one — healthy nodes publish nothing)."""
        return self._health.get(int(node_id))

    def unhealthy_members(self) -> List[int]:
        """Member ids whose reported health event count reached
        ``fence_unhealthy_after`` (empty when fencing is disabled)."""
        if self._fence_unhealthy_after is None:
            return []
        return [
            nid for nid in list(self._leases) + self._observe_only
            if health_event_count(self._health.get(nid))
            >= self._fence_unhealthy_after
        ]

    def expire_now(self, node_id: int) -> None:
        """Force-expire (test hook / explicit eviction); overrides any
        takeover grace window."""
        self._grace_until = 0.0
        self._leases[node_id].changed_at = -float("inf")


def publish_leave_intent(reason: str, timeout_s: float = 2.0) -> bool:
    """Best-effort leave intent from INSIDE a departing process, driven
    entirely by the ``BAGUA_ELASTIC_*`` env the launcher injected.  Called
    by the watchdog's abort path (and any other deliberate-exit path) so
    the coordinator can tell a purposeful departure from a silent hang.
    Bounded and exception-free: the caller is about to die and must not be
    delayed by a gone store."""
    addr = _env.get_elastic_store_addr()
    endpoints = _env.get_restart_store_endpoints()
    if not addr and not endpoints:
        return False
    try:
        from ..contrib.utils.tcp_store import TCPStore

        epoch = _env.get_elastic_epoch()
        node_id = _env.get_elastic_node_id()
        if endpoints:
            # replicated restart store: the primary may be mid-takeover
            # exactly when we are departing — the failover client walks
            # the endpoint list (and follows a fenced write to the new
            # primary) within the same bounded budget
            from .failover import FailoverStore

            store = FailoverStore(endpoints, connect_timeout_s=timeout_s,
                                  op_deadline_s=timeout_s,
                                  client_timeout_s=timeout_s)
            try:
                store.set(_k_leave(epoch, node_id), reason)
            finally:
                store.close()
        else:
            host, port = addr.rsplit(":", 1)
            store = TCPStore(host, int(port), timeout_s=timeout_s)
            try:
                store.set(_k_leave(epoch, node_id), reason)
            finally:
                try:
                    store._sock.close()
                except OSError:
                    pass
        logger.info("published leave intent (node %d, epoch %d): %s",
                    node_id, epoch, reason)
        return True
    except Exception as e:  # noqa: BLE001 - deliberately unconditional
        logger.debug("leave intent not published: %s", e)
        return False
