"""Elastic membership subsystem: lease-based rendezvous over the restart
KV store, world-size renegotiation between MIN and MAX nodes, and
resize-on-restart for the multi-node launcher.

The reference gets elasticity from torchelastic (``bagua.distributed.run``
wraps ``elastic_launch``; the BAGUA paper lists elastic training as a
headline v0.8.0 capability).  Under XLA a *running* world cannot resize —
SPMD programs compile against a fixed device set — so elasticity here is
implemented at the only boundary where it is honest: the gang-restart
boundary.  Each restart attempt is a *rendezvous round*: every surviving
launcher re-registers with the coordinator, whoever shows up within the
join window is admitted (``min_nnodes <= n <= max_nnodes``), dense node
ranks are assigned, and the gang respawns at the renegotiated world size,
resuming from the checkpoint (:mod:`bagua_tpu.checkpoint` restores sharded
pytrees across topology changes).

Modules:

* :mod:`.membership` — lease-based node registry on the existing TCPStore:
  per-node heartbeat thread, TTL leases tracked coordinator-side, and
  epoch-fenced keys so a zombie from attempt N cannot corrupt attempt N+1.
  Heartbeats also carry a **health payload** (grad-guard / async-staleness
  event counters via the node's beacon file) the coordinator can fence on
  (``BAGUA_ELASTIC_FENCE_UNHEALTHY``; see docs/robustness.md).
* :mod:`.coordinator` — rendezvous rounds: open, admit within the join
  window, decide the world size, assign dense ranks, publish the spec.
* :mod:`.resize` — worker-side hooks: rebuild the mesh from the
  renegotiated ``BAGUA_*`` env, drive
  :meth:`~bagua_tpu.checkpoint.BaguaCheckpointManager.try_restore` onto the
  new topology, re-split the data shard.
* :mod:`.failover` — coordinator failover: multi-endpoint store client
  with generation-fenced failover (``BAGUA_RESTART_STORE_ENDPOINTS``),
  the coordinator leadership lease, and the standby watch that promotes a
  follower store + takes the coordinator role over when the primary dies
  (docs/robustness.md).
"""

from .membership import (  # noqa: F401
    LeaseHeartbeat,
    LeaseTracker,
    MembershipClient,
    WorldSpec,
    file_health_source,
    health_event_count,
    local_health_snapshot,
    merged_health_source,
    publish_leave_intent,
    write_health_beacon,
)
from .coordinator import (  # noqa: F401
    ElasticCoordinator,
    ExcludedFromRound,
    Halted,
    RendezvousTimeout,
    join_round,
    wait_for_next_epoch,
)
from .resize import ElasticContext, elastic_restore, shard_bounds  # noqa: F401
from .failover import (  # noqa: F401
    CoordinatorLeaseKeeper,
    FailoverStore,
    StandbyCoordinatorWatch,
    StoreOpDeadlineError,
    parse_endpoints,
    read_coord_lease,
    write_coord_lease,
)
