"""Coordinator failover: multi-endpoint restart-store client + leadership.

The restart TCPStore is the substrate under every recovery path (leases,
stop events, autopilot state, historian rings, quarantine verdicts) — and
until this module it lived in exactly one launcher process.  Failover has
three cooperating parts:

* **Replicated store** (:mod:`bagua_tpu.contrib.utils.tcp_store`): the
  primary server streams its op log (snapshot fallback) to follower
  servers on standby nodes, with a monotonic *store generation* fencing
  any stale primary out of the write path after a takeover.

* **:class:`FailoverStore`** (here): a priority-ordered multi-endpoint
  client (``BAGUA_RESTART_STORE_ENDPOINTS``).  Every op runs under a
  per-op deadline budget (``BAGUA_RESTART_STORE_OP_DEADLINE_S``) and
  retries across reconnects and endpoint failovers with jittered backoff,
  never adopting a server whose generation is below the highest this
  client has seen.  With a single endpoint it degrades to exactly the old
  reconnect-and-retry client (plus the deadline budget).

* **Coordinator leadership** (here): leadership is a lease *in the store
  itself* (``coord/lease``, deliberately outside the epoch-fenced
  keyspace).  The active coordinator renews it from a
  :class:`CoordinatorLeaseKeeper` thread; each standby runs a
  :class:`StandbyCoordinatorWatch` that tracks renewals on its OWN
  monotonic clock and, after a full TTL of silence (staggered by standby
  index so takeovers don't race), promotes the store generation and
  claims the lease.  The store promotion doubles as the election lock:
  only one standby's ``PROMOTE`` can win a given generation.

This module must stay import-light (no jax): launchers, heartbeat threads
and the jax-free podsim coordinator process consume it.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple, Union

from .. import env as _env
from ..contrib.utils.tcp_store import StoreFencedError, TCPStore
from ..faults import inject as _inject
from ..telemetry import counters

logger = logging.getLogger(__name__)

__all__ = [
    "COORD_LEASE_KEY", "Endpoint", "FailoverStore", "StoreOpDeadlineError",
    "CoordinatorLeaseKeeper", "StandbyCoordinatorWatch",
    "parse_endpoint", "parse_endpoints", "read_coord_lease",
    "write_coord_lease",
]

Endpoint = Tuple[str, int]

#: leadership lease key — OUTSIDE the epoch-fenced ``elastic/<e>/`` keyspace
#: (like ``autopilot/state`` / ``obs/historian``) so it survives takeover
#: and rendezvous epochs alike
COORD_LEASE_KEY = "coord/lease"

#: errors one store op retries through (mirrors run.py's
#: ``_STORE_RETRY_ERRORS`` minus the futures timeout nobody raises here)
_RETRYABLE = (ConnectionError, OSError, TimeoutError)


def parse_endpoint(spec: Union[str, Endpoint]) -> Endpoint:
    if isinstance(spec, tuple):
        return spec[0], int(spec[1])
    host, port = spec.rsplit(":", 1)
    return host.strip(), int(port)


def parse_endpoints(specs: Sequence[Union[str, Endpoint]]) -> List[Endpoint]:
    eps = [parse_endpoint(s) for s in specs]
    if not eps:
        raise ValueError("empty restart-store endpoint list")
    return eps


class StoreOpDeadlineError(ConnectionError):
    """One store op exhausted its total retry budget
    (``BAGUA_RESTART_STORE_OP_DEADLINE_S``) across reconnects and endpoint
    failovers.  A ``ConnectionError`` subclass: the callers' store-down
    backoff paths already handle it — the budget just guarantees they get
    the chance to, instead of the op retrying forever inside a watchdog
    section."""


class FailoverStore:
    """Priority-ordered multi-endpoint restart-store client.

    Acquisition prefers, in order: a reachable *primary* endpoint, else
    any reachable endpoint (a follower serves reads; its write fence ack
    turns into a retry here until a standby coordinator promotes it).
    Servers running a generation below the highest this client has seen
    are refused outright — the client-side half of the generation fence:
    after a takeover this client can never fall back onto the stale
    primary, reachable or not.

    Thread-safe the same way :class:`TCPStore` is: one op at a time under
    an internal lock.  Heartbeat threads construct their own instance
    (one connection per thread), exactly as they did with the raw client.
    """

    def __init__(self, endpoints: Sequence[Union[str, Endpoint]],
                 connect_timeout_s: float = 60.0,
                 op_deadline_s: Optional[float] = None,
                 client_timeout_s: float = 30.0):
        self._endpoints = parse_endpoints(endpoints)
        self._multi = len(self._endpoints) > 1
        self._client_timeout_s = float(client_timeout_s)
        if op_deadline_s is None:
            op_deadline_s = _env.get_restart_store_op_deadline_s()
        self._op_deadline_s = float(op_deadline_s)
        self._lock = threading.Lock()
        self._idx = 0
        self._gen = 0
        self._client: Optional[TCPStore] = None
        self._suspect = False  # current endpoint known-bad: fail over first
        self._acquire(time.monotonic() + float(connect_timeout_s))

    # -- properties / introspection --

    @property
    def endpoint(self) -> Endpoint:
        with self._lock:
            return self._endpoints[self._idx]

    @property
    def generation(self) -> int:
        """Highest store generation this client has observed."""
        with self._lock:
            return self._gen

    def status(self) -> bool:
        try:
            self._run_op("ping", lambda c: c.status())
            return True
        except _RETRYABLE:
            return False

    def close(self) -> None:
        with self._lock:
            client, self._client = self._client, None
        self._close_client(client)

    @staticmethod
    def _close_client(client: Optional[TCPStore]) -> None:
        if client is not None:
            try:
                client._sock.close()
            except OSError:
                pass

    # -- connection management --
    #
    # Lock discipline: ``self._lock`` guards only the shared fields
    # (_client, _idx, _gen, _suspect) and is never held across socket IO
    # or backoff sleeps.  (Re)connection runs snapshot -> probe outside
    # the lock -> commit: a concurrent op's brief critical section never
    # wedges behind a multi-second endpoint scan.

    def _probe(self, idx: int, gen: int,
               timeout_s: float) -> Tuple[TCPStore, bool, int]:
        """Connect endpoint ``idx``; returns (client, is_primary,
        highest generation seen).  Pure IO — no shared state is touched.
        Raises ``_RETRYABLE`` on unreachable and ``StoreFencedError`` on a
        server whose generation is below ``gen`` (the client-side half of
        the generation fence)."""
        host, port = self._endpoints[idx]
        client = TCPStore(host, port, timeout_s=timeout_s)
        if not self._multi:
            # single-store mode: no generation probe — byte-identical to
            # the pre-replication client (and compatible with the native
            # C++ server, which drops unknown ops)
            return client, True, gen
        primary, sgen = client.generation()
        if sgen < gen:
            try:
                client._sock.close()
            except OSError:
                pass
            raise StoreFencedError(
                f"store {host}:{port} runs stale generation {sgen} < "
                f"{gen} (refusing a demoted primary)"
            )
        return client, primary, max(gen, sgen)

    def _acquire(self, deadline: float) -> None:
        """(Re)connect to the best endpoint.  Connect attempts and
        backoff sleeps run outside the lock."""
        with self._lock:
            prev_idx = self._idx
            gen = self._gen
            suspect = self._suspect
            old, self._client = self._client, None
        self._close_client(old)
        delay = 0.1
        attempts = 0
        last_err: Optional[BaseException] = None
        while True:
            order = list(range(len(self._endpoints)))
            # a suspect endpoint (injected failover, repeated errors) goes
            # LAST so the scan lands elsewhere first
            start = (prev_idx + 1) % len(order) if suspect else prev_idx
            order = order[start:] + order[:start]
            fallback: Optional[Tuple[int, TCPStore]] = None
            for idx in order:
                budget = deadline - time.monotonic()
                if budget <= 0:
                    break
                try:
                    client, primary, gen = self._probe(
                        idx, gen, timeout_s=max(0.5, min(5.0, budget))
                    )
                except (*_RETRYABLE, StoreFencedError) as e:
                    last_err = e
                    attempts += 1
                    continue
                if primary:
                    if fallback is not None:
                        try:
                            fallback[1]._sock.close()
                        except OSError:
                            pass
                    self._adopt(idx, client, gen, prev_idx)
                    return
                if fallback is None:
                    fallback = (idx, client)
                else:
                    try:
                        client._sock.close()
                    except OSError:
                        pass
            if fallback is not None:
                # no primary anywhere (takeover in flight): a follower
                # serves reads; writes fence -> the op loop retries
                self._adopt(fallback[0], fallback[1], gen, prev_idx)
                return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                eps = ",".join(f"{h}:{p}" for h, p in self._endpoints)
                raise ConnectionError(
                    f"restart store [{eps}] unreachable after "
                    f"{attempts} attempt(s) "
                    f"(last error: {type(last_err).__name__}: {last_err})"
                ) from last_err
            # jittered exponential backoff: after a gang restart every
            # node re-dials at the same instant — de-synchronize the herd
            time.sleep(min(delay * (0.5 + random.random()), remaining))
            delay = min(delay * 2, 5.0)

    def _adopt(self, idx: int, client: TCPStore, gen: int,
               prev_idx: int) -> None:
        """Commit a probed connection; racing committers are safe — the
        later commit closes the earlier one's client, whose in-flight op
        (if any) surfaces a socket error and retries."""
        with self._lock:
            old, self._client = self._client, client
            self._gen = max(self._gen, gen)
            self._suspect = False
            if idx != prev_idx:
                self._idx = idx
        self._close_client(old)
        if idx != prev_idx:
            counters.incr("store/failovers")
            host, port = self._endpoints[idx]
            logger.warning(
                "restart store failed over to endpoint %d (%s:%d, "
                "generation %d)", idx, host, port, gen,
            )

    # -- promotion (the takeover path's half of the generation fence) --

    def promote_store(self) -> bool:
        """Bump the first reachable endpoint (priority order) to primary at
        ``generation + 1``.  The promotion is the election lock: exactly
        one caller's PROMOTE wins a given generation — a False return
        means a peer (or the old primary, alive after all) already runs an
        equal/higher generation, and the caller must NOT take leadership.
        Only coordinator takeover calls this; ordinary clients never
        promote (a worker with a flaky NIC must not fence out a healthy
        primary)."""
        with self._lock:
            prev_idx = self._idx
            gen = self._gen
        try:
            for idx in range(len(self._endpoints)):
                try:
                    client, primary, gen = self._probe(idx, gen,
                                                       timeout_s=5.0)
                except (*_RETRYABLE, StoreFencedError):
                    continue
                if primary and self._multi:
                    # a live primary at (at least) our generation: nothing
                    # to promote — the caller lost the race / was wrong
                    try:
                        client._sock.close()
                    except OSError:
                        pass
                    return False
                try:
                    promoted, sgen = client.promote(gen + 1)
                except _RETRYABLE:
                    try:
                        client._sock.close()
                    except OSError:
                        pass
                    continue
                gen = max(gen, sgen)
                if promoted:
                    counters.incr("store/promotions")
                    with self._lock:
                        old, self._client = self._client, client
                        self._gen = max(self._gen, gen)
                        self._suspect = False
                        self._idx = idx
                    self._close_client(old)
                    host, port = self._endpoints[idx]
                    logger.warning(
                        "restart store: promoted %s:%d to primary "
                        "(generation %d)", host, port, sgen,
                    )
                    if idx != prev_idx:
                        counters.incr("store/failovers")
                    return True
                try:
                    client._sock.close()
                except OSError:
                    pass
                return False  # lost the promotion race
            return False
        finally:
            # record the highest generation observed even on a lost
            # election — the fence must never move backwards
            with self._lock:
                self._gen = max(self._gen, gen)

    # -- the op loop: fault hooks, deadline budget, failover retries --

    def _run_op(self, opname: str, fn: Callable[[TCPStore], object]):
        deadline = (
            time.monotonic() + self._op_deadline_s
            if self._op_deadline_s > 0 else float("inf")
        )
        retried = False
        injected = False
        while True:
            try:
                _inject.maybe_raise_store_error(opname)  # chaos: store.op
                try:
                    # chaos: store.failover declares the CURRENT endpoint
                    # dead — the retry must land on a different one
                    _inject.maybe_raise_store_error(
                        opname, point="store.failover")
                except _inject.InjectedFault:
                    with self._lock:
                        self._suspect = True
                    raise
                with self._lock:
                    client = self._client
                    if client is None:
                        raise ConnectionError("restart store disconnected")
                result = fn(client)
                if retried:
                    logger.info("restart store %s succeeded after retry",
                                opname)
                if injected:
                    _inject.record_recovery("store.op")
                    _inject.record_recovery("store.failover")
                return result
            except StoreFencedError as e:
                counters.incr("store/fenced_writes")
                self._handle_error(opname, e, deadline)
                # a fence means a takeover is IN FLIGHT (every reachable
                # endpoint is a follower, or a stale primary just got
                # demoted under us): reacquisition lands straight back on
                # a follower, so without a pause this loop spins at socket
                # speed until the standby promotes — wait a poll interval
                time.sleep(min(0.25 * (0.5 + random.random()),
                               max(0.0, deadline - time.monotonic())))
                retried = True
            except _RETRYABLE as e:
                injected = injected or isinstance(e, _inject.InjectedFault)
                self._handle_error(opname, e, deadline)
                retried = True

    def _handle_error(self, opname: str, err: BaseException,
                      deadline: float) -> None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            counters.incr("store/op_deadline_exceeded")
            raise StoreOpDeadlineError(
                f"restart store {opname} exhausted its "
                f"{self._op_deadline_s:.0f}s retry budget "
                f"(last error: {type(err).__name__}: {err})"
            ) from err
        logger.warning(
            "restart store %s failed (%s: %s); retrying "
            "(%.0fs of budget left)",
            opname, type(err).__name__, err, remaining,
        )
        try:
            self._acquire(deadline)
        except _RETRYABLE as e:
            # reacquisition ran the budget out: surface it as the deadline,
            # not as one more anonymous connect failure
            counters.incr("store/op_deadline_exceeded")
            raise StoreOpDeadlineError(
                f"restart store {opname} exhausted its "
                f"{self._op_deadline_s:.0f}s retry budget reconnecting "
                f"(last error: {type(e).__name__}: {e})"
            ) from e

    # -- Store surface --

    def set(self, key, value):
        return self._run_op(f"set({key!r})", lambda c: c.set(key, value))

    def get(self, key):
        return self._run_op(f"get({key!r})", lambda c: c.get(key))

    def mset(self, dictionary):
        return self._run_op(
            f"mset[{len(dictionary)}]", lambda c: c.mset(dictionary))

    def mget(self, keys):
        return self._run_op(f"mget[{len(keys)}]", lambda c: c.mget(keys))

    def num_keys(self):
        return self._run_op("num_keys", lambda c: c.num_keys())


# ---------------------------------------------------------------------------
# Coordinator leadership lease
# ---------------------------------------------------------------------------


def write_coord_lease(store, node_id: int, seq: int,
                      generation: int = 0) -> None:
    store.set(COORD_LEASE_KEY, json.dumps(
        {"node": int(node_id), "seq": int(seq), "gen": int(generation)}
    ))


def read_coord_lease(store) -> Optional[dict]:
    """Parsed leadership lease, or None (never held / unparseable)."""
    raw = store.get(COORD_LEASE_KEY)
    if raw is None:
        return None
    try:
        if isinstance(raw, bytes):
            raw = raw.decode()
        lease = json.loads(raw)
        return lease if isinstance(lease, dict) else None
    except (ValueError, UnicodeDecodeError):
        return None


class CoordinatorLeaseKeeper:
    """Renews the leadership lease from its own thread + connection at
    ``ttl_s / 3`` (same cadence logic as the member heartbeats).  Renewal
    errors are logged and retried next tick — a transient store blip must
    not make the ACTIVE coordinator look dead longer than it was."""

    def __init__(self, connect: Callable[[], object], node_id: int,
                 ttl_s: float, generation: int = 0, start_seq: int = 0):
        self._connect = connect
        self._node_id = int(node_id)
        self._ttl_s = float(ttl_s)
        self._generation = int(generation)
        self._seq = int(start_seq)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="coord-lease-keeper")

    def start(self) -> "CoordinatorLeaseKeeper":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    def _run(self) -> None:
        store = None
        while not self._stop.is_set():
            try:
                if store is None:
                    store = self._connect()
                self._seq += 1
                write_coord_lease(
                    store, self._node_id, self._seq, self._generation)
            except _RETRYABLE as e:
                logger.warning("coordinator lease renewal failed: %s", e)
                store = None  # reconnect on the next tick
            self._stop.wait(max(0.2, self._ttl_s / 3.0))


class StandbyCoordinatorWatch:
    """Standby-side leadership watch + takeover trigger.

    Tracks ``(node, seq)`` changes of the leadership lease on this
    process's OWN monotonic clock (no cross-host time comparison — the
    exact discipline :class:`LeaseTracker` uses for member leases).  After
    ``ttl_s`` of silence plus a per-standby stagger (standby 1 moves
    first; ties between standbys are broken by index, not by racing), it
    attempts takeover:

    1. :meth:`FailoverStore.promote_store` — the election lock.  Losing it
       (False) means another standby promoted first or the primary is
       alive after all: reset the staleness clock and keep watching.
    2. Claim the lease under our node id and fire ``on_promoted``.

    An unreadable lease (every endpoint down) does NOT advance staleness:
    takeover requires positive evidence the group is reachable — if this
    standby can't reach any store endpoint, the partition is on OUR side
    and promoting would mint exactly the double-primary the generation
    fence exists to stop."""

    def __init__(self, store: FailoverStore, node_id: int,
                 standby_index: int, ttl_s: float,
                 on_promoted: Optional[Callable[[], None]] = None,
                 poll_s: Optional[float] = None):
        self._store = store
        self._node_id = int(node_id)
        self._ttl_s = float(ttl_s)
        self._stagger_s = max(0, int(standby_index) - 1) * \
            max(0.5, float(ttl_s) / 4.0)
        self._poll_s = float(poll_s) if poll_s is not None \
            else max(0.2, float(ttl_s) / 4.0)
        self._on_promoted = on_promoted
        self._promoted = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="coord-standby-watch")

    def start(self) -> "StandbyCoordinatorWatch":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)

    @property
    def promoted(self) -> bool:
        """True once THIS standby took the coordinator role over."""
        return self._promoted.is_set()

    @property
    def store(self) -> FailoverStore:
        """The watch's own store client — after promotion it holds the
        new generation (the main client may not have failed over yet)."""
        return self._store

    def _run(self) -> None:
        last: Optional[Tuple[int, int]] = None
        changed_at = time.monotonic()
        while not self._stop.is_set():
            self._stop.wait(self._poll_s)
            if self._stop.is_set():
                return
            try:
                lease = read_coord_lease(self._store)
            except _RETRYABLE as e:
                logger.debug("coordinator lease unreadable: %s", e)
                continue  # no positive evidence: staleness clock holds
            now = time.monotonic()
            seen = None if lease is None \
                else (int(lease.get("node", -1)), int(lease.get("seq", -1)))
            if seen != last:
                last = seen
                changed_at = now
                continue
            if now - changed_at <= self._ttl_s + self._stagger_s:
                continue
            if last is not None and last[0] == self._node_id:
                continue  # our own stale claim: nothing to take over
            logger.warning(
                "coordinator lease stale for %.1fs (holder %s); standby %d "
                "attempting takeover", now - changed_at,
                "nobody" if last is None else f"node {last[0]}",
                self._node_id,
            )
            if not self._store.promote_store():
                # lost the election (peer promoted, or the primary is
                # alive at a fresh generation): restart the clock
                last = None
                changed_at = time.monotonic()
                continue
            try:
                write_coord_lease(
                    self._store, self._node_id, 0,
                    self._store.generation)
            except _RETRYABLE as e:
                logger.warning("lease claim after promotion failed: %s", e)
            counters.incr("coord/takeovers")
            self._promoted.set()
            if self._on_promoted is not None:
                try:
                    self._on_promoted()
                except Exception:  # noqa: BLE001 - promotion must stand
                    logger.exception("on_promoted callback failed")
            return
