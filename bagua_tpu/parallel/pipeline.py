"""Pipeline parallelism (GPipe-style) over a ``'pp'`` mesh axis.

Absent from the reference (SURVEY.md §2.3: PP "not present"); additive here.
SPMD formulation: transformer blocks are stacked along a leading layer dim
(``nn.scan``), that dim is sharded over ``'pp'`` so stage ``s`` holds layers
``[s*L/pp, (s+1)*L/pp)``, and one jitted step runs the classic microbatch
schedule as a ``lax.scan`` over ``n_micro + pp - 1`` ticks: every tick each
stage applies its blocks to the activation it holds, then ``lax.ppermute``
hands activations one hop down the pipeline (no wraparound — stages beyond
the end discard, stages before the start receive zeros, which is exactly
the warm-up/drain bubble).  The last stage accumulates the loss; a ``psum``
over ``'pp'`` replicates it.

Embedding / positional / final-norm / head parameters are replicated across
stages (SPMD: every stage traces the same program), so their gradients are
*partial* per stage — the trainer's ``pp_axis`` mode scales them by
``pp_size`` and lets the bucket allreduce span ``pp`` to sum them (see
``BaguaTrainer``).  Stage (block) leaves are sharded and averaged over data
axes only.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ..models.transformer import Block, RMSNorm, TransformerConfig
from .mesh import axis_bound as _axis_bound


class _ScanBlock(nn.Module):
    """Block adapter with scan signature (carry, _) -> (carry, None)."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, _):
        return Block(self.cfg, name="block")(x), None


class PipelinedTransformerLM(nn.Module):
    """Causal LM computing its LOSS inside the pipeline schedule.

    ``__call__(tokens [batch, seq+1]) -> scalar`` per-shard loss (replicated
    over pp).  ``cfg.n_layers`` must be divisible by ``pp_size``; the module
    creates the LOCAL stack of ``n_layers // pp_size`` blocks, so ``init``
    outside the mesh yields local-shape leaves — expand with
    :func:`globalize_pp_params` before handing them to the trainer.

    Outside ``shard_map`` (e.g. ``model.init``) the schedule degenerates to
    a plain sequential forward over the local blocks with a full-batch loss
    — shapes (and therefore params) are identical.
    """

    cfg: TransformerConfig
    pp_size: int
    n_microbatches: int = 1
    pp_axis: str = "pp"

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        assert cfg.n_layers % self.pp_size == 0, (cfg.n_layers, self.pp_size)
        n_local = cfg.n_layers // self.pp_size

        embed = nn.Embed(cfg.vocab_size, cfg.d_model, name="embed",
                         dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (cfg.max_seq_len, cfg.d_model), cfg.param_dtype)
        block_cls = nn.remat(_ScanBlock) if cfg.remat else _ScanBlock
        blocks = nn.scan(
            block_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True},
            length=n_local,
        )(cfg, name="blocks")
        final_norm = RMSNorm(cfg.dtype, cfg.param_dtype, name="final_norm")
        head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype, name="lm_head")

        def embed_fn(toks):
            s = toks.shape[1]
            return embed(toks) + pos[:s][None].astype(cfg.dtype)

        def loss_of(y, targets):
            import optax

            logits = head(final_norm(y)).astype(jnp.float32)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets
            ).mean()

        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        if not _axis_bound(self.pp_axis) or self.pp_size == 1:
            # degenerate path (init trace, or pp=1): plain sequential run
            y, _ = blocks(embed_fn(inputs), None)
            return loss_of(y, targets)

        pp, n_micro = self.pp_size, self.n_microbatches
        b = inputs.shape[0]
        assert b % n_micro == 0, (b, n_micro)
        mb_in = inputs.reshape(n_micro, b // n_micro, -1)
        mb_tgt = targets.reshape(n_micro, b // n_micro, -1)
        stage = lax.axis_index(self.pp_axis)
        perm = [(i, i + 1) for i in range(pp - 1)]

        def tick(carry, t):
            recv, acc = carry
            feed = jnp.clip(t, 0, n_micro - 1)
            x0 = embed_fn(mb_in[feed])
            x_in = jnp.where(stage == 0, x0, recv)
            y, _ = blocks(x_in, None)
            out_idx = t - (pp - 1)
            ls = loss_of(y, mb_tgt[jnp.clip(out_idx, 0, n_micro - 1)])
            take = jnp.logical_and(stage == pp - 1,
                                   jnp.logical_and(out_idx >= 0,
                                                   out_idx < n_micro))
            acc = acc + jnp.where(take, ls, 0.0)
            recv = lax.ppermute(y, self.pp_axis, perm)
            return (recv, acc), None

        recv0 = jnp.zeros((b // n_micro, inputs.shape[1], cfg.d_model),
                          cfg.dtype)
        (_, acc), _ = lax.scan(
            tick, (recv0, jnp.zeros((), jnp.float32)),
            jnp.arange(n_micro + pp - 1),
        )
        # only the last stage accumulated; replicate the mean loss.
        # tp_reduce (psum fwd, identity bwd), NOT a raw psum: under
        # unchecked shard_map psum transposes to psum, which would scale
        # every gradient by pp
        from .tensor_parallel import tp_reduce

        return tp_reduce(acc, self.pp_axis) / n_micro


def pp_param_dim(name: str) -> Optional[int]:
    """Stage-stacked leaves (everything under the ``blocks`` scan scope)
    are sharded along their leading layer dim.  Matching is by exact path
    SEGMENT — a user param like ``resblocks.conv.kernel`` is not captured
    (the substring hazard ``expert_keyword`` was deprecated for)."""
    return 0 if "blocks" in name.split(".") else None


def pp_lm_loss_fn(model: PipelinedTransformerLM):
    def loss_fn(params, batch):
        return model.apply({"params": params}, batch["tokens"])

    return loss_fn


def globalize_pp_params(params, rng, pp_size: int, tp_size: int = 1,
                        tp_param_dim=None):
    """Expand LOCAL stage stacks ``[L/pp, ...]`` to GLOBAL ``[L, ...]``.

    Norm scales are re-expanded as ones; kernels are re-drawn lecun-normal
    over their per-layer contracting dims (layer dim 0 excluded).  With
    ``tp_size > 1`` (3-D parallelism: the blocks also carry tensor-parallel
    kernels) each tp leaf's sharded dim — reported by ``tp_param_dim`` in
    per-layer coordinates, shifted past the stage dim — is expanded to its
    global width as well, and the redraw uses the GLOBAL fan-in.
    """
    from ..models.transformer import tp_param_fan_in_dims
    from ..tensor import _name_of_path
    from .tensor_parallel import redraw_lecun

    if tp_param_dim is None and tp_size > 1:
        from ..models.transformer import tp_param_dim as _default_tp_dim

        tp_param_dim = _default_tp_dim

    def fix(path, leaf):
        name = _name_of_path(path)
        if pp_param_dim(name) is None or (pp_size == 1 and tp_size == 1):
            return leaf
        shape = [leaf.shape[0] * pp_size, *leaf.shape[1:]]
        if name.endswith(".scale"):  # norm scales: ones
            return jnp.ones(tuple(shape), leaf.dtype)
        tpd = tp_param_dim(name) if tp_size > 1 else None
        if tpd is not None:
            shape[tpd + 1] = shape[tpd + 1] * tp_size
        nonlocal rng
        rng, sub = jax.random.split(rng)
        # per-layer kernels: contracting dims from the tp table, shifted
        # past the leading layer dim; default: all but first and last
        inner = tp_param_fan_in_dims(name)
        contracting = (
            tuple(ax + 1 for ax in inner) if inner is not None
            else tuple(range(1, len(shape) - 1))
        )
        return redraw_lecun(sub, tuple(shape), contracting, leaf.dtype)

    return jax.tree_util.tree_map_with_path(fix, params)
