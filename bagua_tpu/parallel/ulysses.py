"""Ulysses-style sequence parallelism: all-to-all head/sequence reshard.

Absent from the reference; SURVEY.md §5.7 notes its ``alltoall_v`` + MoE
all-to-all machinery are exactly the primitives Ulysses (DeepSpeed-Ulysses,
arXiv 2309.14509) needs.  Here it is two ``lax.all_to_all`` calls over the
``'sp'`` axis: heads are scattered so each shard sees the FULL sequence for
its subset of heads, runs an unmodified local attention, and reshards back.
Complements ring attention: Ulysses keeps attention math local (better for
short-ish sequences / many heads), the ring streams K/V (better for very
long sequences).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax


def make_ulysses_attention(
    sp_size: int,
    axis_name: str = "sp",
    inner_attn: Optional[Callable] = None,
):
    """Build an ``attn_fn(q, k, v, dtype)`` for ``TransformerLM``.

    Per-shard inputs [batch, seq_local, heads, head_dim]; ``heads`` must be
    divisible by ``sp_size``.  ``inner_attn`` is the local full-sequence
    attention (default: the model's standard causal attention).
    """

    def attn_fn(q, k, v, dtype):
        from ..models.transformer import causal_attention

        inner = inner_attn or causal_attention
        from .mesh import axis_bound

        if not axis_bound(axis_name):
            # outside shard_map (e.g. model.init): plain local attention
            return inner(q, k, v, dtype)
        if q.shape[2] % sp_size:
            raise ValueError(
                f"heads {q.shape[2]} not divisible by sp_size {sp_size}"
            )

        # [b, s_loc, h, d] -> [b, s_global, h/sp, d]
        def to_seq(x):
            return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

        # [b, s_global, h/sp, d] -> [b, s_loc, h, d]
        def to_heads(x):
            return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

        o = inner(to_seq(q), to_seq(k), to_seq(v), dtype)
        return to_heads(o)

    return attn_fn
