"""Device-mesh construction — the TPU-native substrate for every communicator.

The reference builds three NCCL communicators per model (global / intra-node /
inter-node, /root/reference/bagua/torch_api/communication.py:47-72) and runs
hierarchical collectives by hand (communicators/mod.rs:243-336).  On TPU the
same roles are mesh axes: a 2-D ``('inter', 'intra')`` mesh makes XLA route the
intra-node stage over ICI and the inter-node stage over DCN, so "hierarchical
reduce" is just a nested collective over the two axes.

Axis conventions used across bagua_tpu:

- ``dp``     data parallel (the reference's only first-class dimension)
- ``inter`` / ``intra``   hierarchical split of dp (node boundary)
- ``ep``     expert parallel (MoE all-to-all axis)
- ``sp``     sequence/context parallel (ring attention / Ulysses axis)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from .. import env

_GLOBAL_MESH: Optional[Mesh] = None


def build_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Create a named mesh over ``devices`` (default: all devices).

    ``axis_sizes`` maps axis name -> size; a single ``-1`` entry is inferred.
    Default is a 1-D data-parallel mesh ``{'dp': n_devices}``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if not axis_sizes:
        axis_sizes = {"dp": n}
    axis_sizes = dict(axis_sizes)

    unknown = [k for k, v in axis_sizes.items() if v == -1]
    known = int(np.prod([v for v in axis_sizes.values() if v != -1])) if axis_sizes else 1
    if len(unknown) > 1:
        raise ValueError("at most one axis size may be -1")
    if unknown:
        if n % known != 0:
            raise ValueError(f"{n} devices not divisible by {known}")
        axis_sizes[unknown[0]] = n // known
    total = int(np.prod(list(axis_sizes.values())))
    if total != n:
        raise ValueError(f"mesh {axis_sizes} needs {total} devices, have {n}")

    shape = tuple(axis_sizes.values())
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_sizes.keys()))


def hierarchical_mesh(
    intra_size: Optional[int] = None, devices: Optional[Sequence] = None
) -> Mesh:
    """2-D ``('inter', 'intra')`` mesh; ``intra`` is the node-local axis.

    Mirrors the reference's inter/intra communicator split
    (communication.py:156-227).  ``intra_size`` defaults to the local device
    count (devices per host), the direct analog of ``nranks_per_node``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if intra_size is None:
        intra_size = min(jax.local_device_count(), n)
        while n % intra_size != 0:
            intra_size //= 2
        intra_size = max(intra_size, 1)
    if n % intra_size != 0:
        raise ValueError(f"{n} devices not divisible by intra_size={intra_size}")
    return build_mesh({"inter": n // intra_size, "intra": intra_size}, devices)


def set_global_mesh(mesh: Mesh) -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh


def get_global_mesh() -> Mesh:
    """The process-wide default mesh (created on first use: 1-D dp mesh)."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh()
    return _GLOBAL_MESH


def get_global_mesh_if_set() -> Optional[Mesh]:
    """The explicitly registered mesh (via init_process_group/set_global_mesh),
    or None — never creates a default."""
    return _GLOBAL_MESH


def axis_bound(name: str) -> bool:
    """True when ``name`` is a live mesh axis, i.e. the caller is tracing
    inside ``shard_map`` over a mesh containing it.  Modules use this to
    degrade to a local computation during ``model.init`` outside the mesh."""
    import jax

    try:
        jax.lax.axis_index(name)
        return True
    except NameError:
        return False


def mesh_axis_size(mesh: Mesh, axes) -> int:
    if isinstance(axes, str):
        axes = (axes,)
    return int(math.prod(mesh.shape[a] for a in axes))


def make_global_array(mesh: Mesh, spec, local):
    """Assemble a global ``jax.Array`` from this process's local shard.

    Multi-host input path (reference: each rank feeds its own DataLoader
    shard; under JAX's single-program multi-controller model the per-process
    batch slices must be stitched into one global array before entering the
    jitted step).  ``local`` is this process's slice of the batch along the
    sharded axes of ``spec``; single-process meshes pass through unchanged.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(local), sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))
