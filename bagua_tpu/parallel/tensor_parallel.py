"""Tensor parallelism (Megatron-style) over a ``'tp'`` mesh axis.

Absent from the reference (SURVEY.md §2.3: TP "not present" — its
``model_parallel/`` holds only MoE); first-class here, additive, because
sharding the attention heads and FFN width over ICI is the natural TPU way
to fit models past one chip's HBM.

Layout (Shoeybi et al., arXiv 1909.08053, re-derived for shard_map):

- column-parallel matmuls (q/k/v projections, FFN up/gate) shard the OUTPUT
  feature dim: each shard holds heads/tp heads or d_ff/tp columns and
  consumes the replicated activation;
- row-parallel matmuls (attention output, FFN down) shard the INPUT dim and
  their partial outputs are summed with one ``lax.psum`` per block;
- the conjugate "g" function (:func:`tp_gather_grad`) is identity in
  forward and ``psum`` in backward, inserted right before each
  column-parallel matmul so that norm/embedding gradients — whose cotangent
  arrives partially from every shard's branch — come out exact under
  ``shard_map(check_vma=False)``, where no automatic replication bookkeeping
  exists.

Inside the jitted step each shard's parameters are its LOCAL slices
(natural shapes, no stacking); the trainer shards the global arrays along
the dimensions reported by the model's ``tp_param_dim``.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_gather_grad(x, axis_name: str):
    """Identity forward, ``psum`` over ``axis_name`` backward — Megatron's
    "g" function.  Place immediately before a column-parallel matmul."""
    return x


def _ggrad_fwd(x, axis_name):
    return x, None


def _ggrad_bwd(axis_name, _, ct):
    return (lax.psum(ct, axis_name),)


tp_gather_grad.defvjp(_ggrad_fwd, _ggrad_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def tp_reduce(x, axis_name: str):
    """``psum`` forward, identity backward — Megatron's "f" conjugate.
    Closes each row-parallel matmul (attention output / FFN down).

    A raw ``lax.psum`` would be wrong here: under ``shard_map``'s unchecked
    mode the transpose of ``psum`` is ``psum`` again, so the (already
    replicated) cotangent would be multiplied by the axis size at every
    block and the error compounds multiplicatively through the network.
    """
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, ct):
    return (ct,)


tp_reduce.defvjp(_reduce_fwd, _reduce_bwd)


# flax's truncated-normal initializers divide by the stddev of the
# [-2, 2]-truncated unit normal so the DRAWN stddev equals the target
_TRUNC_STD = 0.87962566103423978


def redraw_lecun(rng, shape, contracting, dtype):
    """One lecun-normal draw at ``shape`` with variance ``1/fan_in`` over
    the given contracting dims (flax-matching truncated normal).  Shared by
    the tp and pp global-init redraws."""
    fan_in = 1
    for ax in contracting:
        fan_in *= shape[ax]
    std = (1.0 / max(fan_in, 1)) ** 0.5 / _TRUNC_STD
    return std * jax.random.truncated_normal(
        rng, -2.0, 2.0, tuple(shape), jnp.float32
    ).astype(dtype)


def globalize_tp_params(params, rng, tp_size: int,
                        tp_param_dim: Callable[[str], Optional[int]],
                        fan_in_dims: Optional[Callable] = None):
    """Re-draw tensor-parallel leaves at GLOBAL shape.

    ``model.init`` outside the mesh yields tp leaves of LOCAL shape (e.g.
    ``[d, d_ff/tp]``) — identical on every shard, a bad symmetric init.
    This expands each leaf's sharded dim by ``tp_size`` with a fresh
    lecun-normal draw over the GLOBAL fan-in (``fan_in_dims(name)`` gives
    the contracting dims of the global kernel; default: the transformer
    family's table).  The returned tree is only valid through
    ``BaguaTrainer(tp_axis=...)``.
    """
    from ..tensor import _name_of_path

    if fan_in_dims is None:
        from ..models.transformer import tp_param_fan_in_dims

        fan_in_dims = tp_param_fan_in_dims

    def fix(path, leaf):
        name = _name_of_path(path)
        dim = tp_param_dim(name)
        if dim is None or tp_size == 1:
            return leaf
        nonlocal rng
        rng, sub = jax.random.split(rng)
        shape = list(leaf.shape)
        shape[dim] = shape[dim] * tp_size
        contracting = fan_in_dims(name) or tuple(range(len(shape) - 1))
        return redraw_lecun(sub, shape, contracting, leaf.dtype)

    return jax.tree_util.tree_map_with_path(fix, params)
