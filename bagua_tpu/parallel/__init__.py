from .mesh import (  # noqa: F401
    build_mesh,
    get_global_mesh,
    hierarchical_mesh,
    mesh_axis_size,
    set_global_mesh,
)
from .ring_attention import make_ring_attention  # noqa: F401
from .ulysses import make_ulysses_attention  # noqa: F401
