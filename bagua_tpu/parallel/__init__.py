from .mesh import (  # noqa: F401
    build_mesh,
    get_global_mesh,
    hierarchical_mesh,
    mesh_axis_size,
    set_global_mesh,
)
