"""Ring attention: causal flash-style attention over a sequence-parallel axis.

Absent from the reference (SURVEY.md §5.7 — its closest primitives are the
MoE all-to-all and ``alltoall_v``); first-class here because long-context is a
framework requirement.  Design is the TPU-native ring form (Liu et al.,
arXiv 2310.01889): the sequence is sharded over the ``'sp'`` mesh axis, each
step combines the resident K/V block with a numerically-stable online-softmax
update while ``lax.ppermute`` rotates K/V one hop around the ring — the
rotation rides ICI concurrently with the block matmuls, which is exactly the
compute/comm overlap the reference's Rust scheduler provided for DP, applied
to attention.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def make_ring_attention(sp_size: int, axis_name: str = "sp"):
    """Build an ``attn_fn(q, k, v, dtype)`` for ``TransformerLM`` that runs
    causal attention over a sequence sharded on ``axis_name``.

    Inputs per shard: [batch, seq_local, heads, head_dim] where shard i holds
    global positions [i*seq_local, (i+1)*seq_local).  Must run inside
    shard_map over a mesh containing ``axis_name`` (of size ``sp_size``).
    """

    def attn_fn(q, k, v, dtype):
        b, s, h, d = q.shape
        scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
        from .mesh import axis_bound

        if not axis_bound(axis_name):
            # outside shard_map (e.g. model.init): plain local attention —
            # shapes and params are identical, only used for tracing
            from ..models.transformer import causal_attention

            return causal_attention(q, k, v, dtype)
        my = lax.axis_index(axis_name)
        q32 = q.astype(jnp.float32)
        q_pos = my * s + jnp.arange(s)

        # ring neighbor: receive from the previous rank so that after t hops
        # we hold the K/V block originated by shard (my - t) mod sp
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

        def body(t, carry):
            o, m, l, k_blk, v_blk = carry
            src = (my - t) % sp_size
            k_pos = src * s + jnp.arange(s)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
            ) * scale
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, NEG_INF)

            m_new = jnp.maximum(m, logits.max(axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[..., None])
            # fully-masked blocks contribute nothing (exp(NEG_INF - m) == 0)
            l_new = l * corr + p.sum(axis=-1)
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
            o_new = o * corr[..., None] + pv

            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            return o_new, m_new, l_new, k_blk, v_blk

        o0 = jnp.zeros((b, h, s, d), jnp.float32)
        m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, s), jnp.float32)
        o, m, l, _, _ = lax.fori_loop(0, sp_size, body, (o0, m0, l0, k, v))
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 2, 1, 3).astype(dtype)  # [b, s, h, d]

    return attn_fn
