"""Ring attention: causal flash-style attention over a sequence-parallel axis.

Absent from the reference (SURVEY.md §5.7 — its closest primitives are the
MoE all-to-all and ``alltoall_v``); first-class here because long-context is a
framework requirement.  Design is the TPU-native ring form (Liu et al.,
arXiv 2310.01889): the sequence is sharded over the ``'sp'`` mesh axis, each
step combines the resident K/V block with a numerically-stable online-softmax
update while ``lax.ppermute`` rotates K/V one hop around the ring — the
rotation rides ICI concurrently with the block matmuls, which is exactly the
compute/comm overlap the reference's Rust scheduler provided for DP, applied
to attention.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def make_ring_attention(sp_size: int, axis_name: str = "sp",
                        use_flash: str = "auto", interpret: bool = False):
    """Build an ``attn_fn(q, k, v, dtype)`` for ``TransformerLM`` that runs
    causal attention over a sequence sharded on ``axis_name``.

    Inputs per shard: [batch, seq_local, heads, head_dim] where shard i holds
    global positions [i*seq_local, (i+1)*seq_local).  Must run inside
    shard_map over a mesh containing ``axis_name`` (of size ``sp_size``).

    ``use_flash``: ``"auto"`` (Pallas flash kernel per ring step when
    :func:`bagua_tpu.ops.flash_attention.flash_supported` says it pays),
    ``"always"`` (force the kernel path), or ``"never"``.  ``interpret``
    runs the kernels in the Pallas interpreter (CPU tests).  The flash form
    computes each resident K/V block with the fused kernel and combines
    blocks with the standard (o, logsumexp) merge — identical math to the
    inline online-softmax loop, but the [s_local, s_local] scores never
    touch HBM.
    """
    if use_flash not in ("auto", "always", "never"):
        raise ValueError(
            f"use_flash={use_flash!r}: expected 'auto', 'always', or 'never'"
        )

    def attn_fn(q, k, v, dtype):
        b, s, h, d = q.shape
        scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
        from .mesh import axis_bound

        if not axis_bound(axis_name):
            # outside shard_map (e.g. model.init): plain local attention —
            # shapes and params are identical, only used for tracing
            from ..models.transformer import causal_attention

            return causal_attention(q, k, v, dtype)

        from ..ops.flash_attention import flash_supported

        if use_flash == "always" or (
            use_flash == "auto" and flash_supported(s, d)
        ):
            return _ring_flash(q, k, v, dtype, sp_size, axis_name,
                               interpret=interpret)
        my = lax.axis_index(axis_name)
        q32 = q.astype(jnp.float32)
        q_pos = my * s + jnp.arange(s)

        # ring neighbor: receive from the previous rank so that after t hops
        # we hold the K/V block originated by shard (my - t) mod sp
        perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

        def body(t, carry):
            o, m, l, k_blk, v_blk = carry
            src = (my - t) % sp_size
            k_pos = src * s + jnp.arange(s)
            logits = jnp.einsum(
                "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
            ) * scale
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask[None, None], logits, NEG_INF)

            m_new = jnp.maximum(m, logits.max(axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.exp(logits - m_new[..., None])
            # fully-masked blocks contribute nothing (exp(NEG_INF - m) == 0)
            l_new = l * corr + p.sum(axis=-1)
            pv = jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
            o_new = o * corr[..., None] + pv

            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            return o_new, m_new, l_new, k_blk, v_blk

        o0 = jnp.zeros((b, h, s, d), jnp.float32)
        m0 = jnp.full((b, h, s), NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, h, s), jnp.float32)
        o, m, l, _, _ = lax.fori_loop(0, sp_size, body, (o0, m0, l0, k, v))
        out = o / jnp.maximum(l, 1e-30)[..., None]
        return out.transpose(0, 2, 1, 3).astype(dtype)  # [b, s, h, d]

    return attn_fn


def _merge_partials(o1, lse1, o2, lse2):
    """Combine two normalized partial attentions over disjoint K/V sets.
    ``o``: [b, s, h, d] f32, ``lse``: [b, h, s] f32."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    wsum = w1 + w2
    wt = lambda w: (w / wsum).transpose(0, 2, 1)[..., None]  # [b, s, h, 1]
    return wt(w1) * o1 + wt(w2) * o2, m + jnp.log(wsum)


def _ring_flash(q, k, v, dtype, sp_size, axis_name, interpret=False):
    """Ring attention with the fused flash kernel per resident block.

    Step 0 is the causal diagonal block; later steps are full
    (non-causal) cross-attention against earlier shards' K/V, merged with
    the (o, lse) statistics.  Blocks originating AFTER this shard are
    masked out by forcing their lse to -inf (zero merge weight, zero
    gradient) — same wasted bubble compute as the inline loop, but every
    matmul runs in the MXU-blocked kernel.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % sp_size) for i in range(sp_size)]

    o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                      interpret=interpret)
    k_blk, v_blk = k, v
    for t in range(1, sp_size):
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = (my - t) % sp_size
        o_t, lse_t = flash_attention_with_lse(q, k_blk, v_blk, causal=False,
                                              interpret=interpret)
        lse_t = jnp.where(src < my, lse_t, NEG_INF)
        o, lse = _merge_partials(o, lse, o_t, lse_t)
    return o.astype(dtype)
