"""QAdam: quantized-momentum Adam (1-bit-Adam family).

Counterpart of /root/reference/bagua/torch_api/algorithms/q_adam.py:13-203.
Two phases, switched by ``need_reset`` at the warmup boundary (:118-125):

- warmup (``step < warmup_steps``): gradients are full-precision averaged,
  both Adam moments update from the averaged gradient (:88-92), parameters
  step by the Adam rule (:94-100).
- compressed: the *momentum* (``exp_avg``) updates locally from the raw
  gradient (the reference's in-pipeline python op :178-189), is then
  8-bit-compressed scatter-gather averaged (:190-195), and the second moment
  is frozen (:88 guard).

The algorithm owns its optimizer (the reference requires the dedicated
``QAdamOptimizer``), so the trainer's optax path is bypassed.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..communication import LINK_DCN, LINK_ICI, ReduceOp
from ..compression import compressed_scatter_gather_allreduce
from .base import Algorithm, AlgorithmContext


class QAdamOptState(NamedTuple):
    exp_avg: object
    exp_avg_sq: object


class QAdamAlgorithm(Algorithm):
    name = "qadam"
    owns_optimizer = True
    #: the momenta are elementwise maps of the gradient, so they live as
    #: bucket flats under the resident layout and the compressed momentum
    #: pipeline consumes them with zero repacking
    supports_flat_resident = True
    #: non-hierarchical compressed-phase wire format (byte accounting)
    wire_codec_flat = "minmax_uint8"

    def __init__(
        self,
        warmup_steps: int = 100,
        lr: float = 1e-3,
        betas: Tuple[float, float] = (0.9, 0.999),
        eps: float = 1e-8,
        weight_decay: float = 0.0,
        hierarchical: bool = True,
        codec: str = "minmax_uint8",
    ):
        """
        Args:
            warmup_steps: Steps of full-precision gradient allreduce before
                switching to compressed momentum communication.
            lr / betas / eps / weight_decay: Adam hyperparameters (reference
                QAdamOptimizer q_adam.py:13-46).
            hierarchical: Enable hierarchical communication in the
                compressed phase.
            codec: Wire codec of the compressed DCN ring hops in the
                hierarchical compressed phase (overridable by
                ``BAGUA_COMPRESS_INTER``).
        """
        from ..compression.codecs import get_codec

        get_codec(codec)  # fail fast on a typo'd codec name
        self.warmup_steps = warmup_steps
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.hierarchical = hierarchical
        self.codec = codec
        self._compressed = False

    @property
    def wire_codec_dcn(self):
        return self.codec

    def need_reset(self, step: int) -> bool:
        if step == self.warmup_steps and not self._compressed:
            self._compressed = True
            return True
        return False

    def compile_key(self) -> tuple:
        # the traced step branches on _compressed at trace time; an autotune
        # switch back to qadam resets it to False mid-training, which must
        # NOT reuse the compressed-phase compile
        return (self._compressed,)

    def tensors_to_buckets(self, decl_buckets, named_params, world_size):
        from ..bucket import BucketPlan

        # world-size alignment for the compressed scatter-gather
        # (reference q_adam.py:158-166 aligns buckets to get_world_size())
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=world_size
        )

    # ---- phase 1: warmup grad allreduce ---------------------------------

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        if self._compressed:
            return grads, algo_state
        flats = ctx.bucket_flats(grads)
        flats = [ctx.hierarchical_allreduce(f, ReduceOp.AVG, False) for f in flats]
        return ctx.from_bucket_flats(flats, grads), algo_state

    # ---- optimizer -------------------------------------------------------

    def init_optimizer_state(self, params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return QAdamOptState(exp_avg=zeros, exp_avg_sq=jax.tree.map(jnp.zeros_like, params))

    def _communicate_momentum(self, ctx: AlgorithmContext, exp_avg):
        flats = ctx.bucket_flats(exp_avg)
        # the true two-level decomposition where the mesh supports it:
        # full-precision slice-local reduce-scatter (ICI is cheap), the
        # COMPRESSED RING allreduce of the 1/intra momentum shard across
        # slices (quantized ppermute hops, fp32 accumulation — the 1-bit
        # Adam relaxation applied ON the slow link's hops), slice-local
        # allgather.  Buckets are world-aligned (tensors_to_buckets), so
        # both tiers divide evenly.
        use_two_level = (
            self.hierarchical
            and ctx.two_tier()
            and ctx.internode.nranks() > 1
        )
        # legacy Leader form for hierarchical meshes the two-level gate
        # refuses (an extra comm axis folded in): full-precision intra
        # average, compressed scatter-gather across slices
        use_hier = (
            not use_two_level
            and self.hierarchical
            and ctx.internode is not None
            and ctx.intranode is not None
            and ctx.internode.nranks() > 1
            and ctx.intranode.nranks() > 1
        )
        out = []
        for f in flats:
            if use_two_level:
                f = ctx.tier_reduce_scatter(f, ReduceOp.AVG)
                f = ctx.tier_allreduce(f, ReduceOp.AVG, codec=self.codec)
                f = ctx.tier_allgather(f)
            elif use_hier:
                f = ctx.intranode.allreduce(f, ReduceOp.AVG)
                # the knob's `off` escape hatch holds on the legacy leg
                # too: full-precision inter average (tier_allreduce, so
                # the DCN chunk knob's ring schedule survives) instead
                # of the codec
                if ctx.codec_for(LINK_DCN, self.codec) is None:
                    f = ctx.tier_allreduce(f, ReduceOp.AVG)
                else:
                    f = compressed_scatter_gather_allreduce(
                        ctx.internode, f, average=True)
            elif ctx.comm.nranks() > 1:
                if ctx.codec_for(LINK_ICI, self.codec) is None:
                    # bucket_allreduce keeps the chunk knobs' ring
                    # schedule on the full-precision escape hatch
                    f = ctx.bucket_allreduce(f, ReduceOp.AVG, False)
                else:
                    f = compressed_scatter_gather_allreduce(
                        ctx.comm, f, average=True)
            out.append(f)
        return ctx.from_bucket_flats(out, exp_avg)

    def optimizer_update(self, ctx, params, grads, opt_state: QAdamOptState, algo_state, step):
        beta1, beta2 = self.betas
        # reference QAdamOptimizer.step increments step_id first (:77), so the
        # bias corrections use step_id = step + 1
        step_id = (step + 1).astype(jnp.float32)

        exp_avg = jax.tree.map(
            lambda m, g: m * beta1 + g * (1.0 - beta1), opt_state.exp_avg, grads
        )
        if self._compressed:
            # second moment frozen (q_adam.py:88 guard); momentum averaged
            # via the compressed pipeline
            exp_avg = self._communicate_momentum(ctx, exp_avg)
            exp_avg_sq = opt_state.exp_avg_sq
        else:
            exp_avg_sq = jax.tree.map(
                lambda v, g: v * beta2 + (g * g) * (1.0 - beta2),
                opt_state.exp_avg_sq,
                grads,
            )

        bias1 = 1.0 - beta1 ** step_id
        bias2 = 1.0 - beta2 ** step_id

        def upd(p, m, v):
            denom = jnp.sqrt(v) / jnp.sqrt(bias2) + self.eps
            new_p = p - (self.lr / bias1) * (m / denom)
            if self.weight_decay:
                new_p = new_p - self.lr * self.weight_decay * p
            return new_p

        new_params = jax.tree.map(upd, params, exp_avg, exp_avg_sq)
        return new_params, QAdamOptState(exp_avg, exp_avg_sq), algo_state
