"""Algorithm base class — the pluggable "what/when to communicate" contract.

Counterpart of /root/reference/bagua/torch_api/algorithms/base.py:8-156.  The
reference's 7 hooks are driven by autograd events (grad-ready marks, post
backward, post optimizer step); under XLA the whole train step is one traced
program, so the hooks become *functional stages* of the step:

  reference hook                        bagua_tpu stage
  ------------------------------------  ----------------------------------
  init_tensors / tensors_to_buckets     init_tensors / tensors_to_buckets (same)
  init_forward_pre_hook (mark ready)    (implicit: XLA schedules collectives)
  init_backward_hook (per-grad mark)    process_grads (bucketed comm on grads)
  init_post_backward_hook (wait ops)    process_pre_step (weight comm lands here)
  init_post_optimizer_step_hook         process_post_step
  init_operations                       the body of the stages above
  need_reset                            need_reset (host-side, triggers rebuild)

All stages except ``need_reset``/``init_tensors``/``tensors_to_buckets`` are
traced inside ``shard_map`` over the data-parallel mesh axes and may call
collectives through ``ctx``.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

#: comm-axes tuples a dropped-codec warning was already logged for (the
#: warning fires at trace time, once per topology, not once per bucket)
_CODEC_DROP_WARNED: set = set()

#: (family, codec, reason) triples a stateless-EF-codec warning was already
#: logged for — an error-feedback codec riding the wire WITHOUT its residual
#: is a deliberate honesty control (BAGUA_EF_RESIDUAL=off) or an unsupported
#: family, and either way the run should say so exactly once
_EF_STATELESS_WARNED: set = set()

from ..bucket import BucketPlan
from ..communication import BaguaCommunicator, ReduceOp
from ..define import TensorDeclaration
from ..tensor import NamedParam


@dataclass
class AlgorithmContext:
    """Static per-compile context handed to traced algorithm stages."""

    comm: BaguaCommunicator              # spans all dp axes ("global")
    internode: Optional[BaguaCommunicator]
    intranode: Optional[BaguaCommunicator]
    plan: BucketPlan
    world_size: int
    #: overlap scheduler active for this compiled step (the trainer streams
    #: per-bucket collectives via :meth:`Algorithm.reduce_bucket_grad`)
    overlap: bool = False
    #: target per-rank bytes of one independent ring sub-collective; None
    #: keeps the fused psum/psum_scatter primitives (no chunking).  The
    #: link-agnostic fallback for the per-tier knobs below.
    overlap_chunk_bytes: Optional[int] = None
    #: per-tier chunk targets: the ICI tiers (slice-local ``intra`` axis,
    #: and the single-axis flat path) and the DCN tier (cross-slice
    #: ``inter`` axis) size their ring chunks against DIFFERENT bytes —
    #: a chunk that amortizes an ICI hop is far too small for a DCN hop.
    #: None falls back to :attr:`overlap_chunk_bytes`.
    intra_chunk_bytes: Optional[int] = None
    inter_chunk_bytes: Optional[int] = None
    #: flat-resident layout active: params/grads/opt state trees handed to
    #: the algorithm stages are ``{"flats": (...), "local": {...}}`` bucket
    #: containers, NOT leaf pytrees — reach their flat buffers through
    #: :meth:`bucket_flats` / :meth:`from_bucket_flats` so one stage
    #: implementation serves both layouts
    flat_resident: bool = False
    #: per-link-class codec policy (docs/compression.md): what the ring
    #: hops of each bandwidth tier carry on the wire.  Values are the
    #: ``BAGUA_COMPRESS_{INTRA,INTER}`` knob values — ``auto`` (default)
    #: defers to the algorithm family's own wire codec (ByteGrad/QAdam
    #: compress the DCN tier natively; everything else stays full
    #: precision), ``off`` FORCES full precision on the tier, and a codec
    #: name forces that codec for every family riding the tier.
    intra_codec: Optional[str] = None
    inter_codec: Optional[str] = None
    #: error-feedback residual machinery allowed on this mesh/trainer:
    #: the trainer clears it on meshes whose state layout cannot carry
    #: the per-bucket residual (expert/sharded axes, stacked families) and
    #: when ``BAGUA_EF_RESIDUAL=off`` (the stateless honesty control).
    #: :meth:`Algorithm.ef_codec` gates on it.
    ef_enabled: bool = False

    def codec_for(self, link_class: str, family_default=None):
        """Resolve the wire codec for one link class: the tier's policy
        knob where it names a codec or forces ``off``, else the algorithm
        family's default (``None`` = full precision).  ``LINK_DCN``
        compressed / ``LINK_ICI`` full-precision is the default posture —
        only the compression families carry a DCN family default, and
        ``auto`` never compresses ICI."""
        from ..communication import LINK_DCN

        knob = (self.inter_codec if link_class == LINK_DCN
                else self.intra_codec)
        if knob in (None, "", "auto"):
            return family_default
        if knob == "off":
            return None
        return knob

    def flat_ring_codec(self, warn: bool = True):
        """The knob-resolved codec for the FLAT (whole-comm-world) ring —
        or None when this comm world cannot ride a ring (multiple mesh
        axes, or a single rank).  The ring is the only compressed carrier
        on the flat path, so a knob-forced codec there must either engage
        the ring or be LOUDLY dropped — and the byte accounting uses the
        same resolution, so it can never claim a wire reduction the
        collective did not deliver."""
        from ..communication import LINK_ICI

        codec = self.codec_for(LINK_ICI, None)
        if codec is None:
            return None
        if len(self.comm.axes) == 1 and self.comm.nranks() > 1:
            return codec
        if warn and self.comm.nranks() > 1 \
                and self.comm.axes not in _CODEC_DROP_WARNED:
            _CODEC_DROP_WARNED.add(self.comm.axes)
            logger.warning(
                "compress_intra=%r ignored: the flat comm world spans "
                "mesh axes %s and the compressed ring permutes over "
                "exactly one — this collective stays full precision "
                "(use hierarchical=True with compress_inter to compress "
                "the cross-slice tier)", codec, self.comm.axes,
            )
        return None

    def bucket_flats(self, tree) -> List:
        """The per-bucket flat gradient/param/state buffers of ``tree``
        under the active layout: the resident flats themselves (already
        bucket-flat — zero repacking), or the traced flatten of a leaf
        pytree.  The ONE accessor algorithm stages use, so the resident
        layout cannot silently re-pay the per-step flatten it removed."""
        if self.flat_resident:
            return list(tree["flats"])
        return self.plan.flatten_tree(tree)

    def from_bucket_flats(self, flats, like):
        """Inverse of :meth:`bucket_flats`: rebuild ``like``'s layout from
        per-bucket flat buffers — a no-copy container under the resident
        layout, the traced unflatten for leaf pytrees."""
        if self.flat_resident:
            return {"flats": tuple(flats), "local": like["local"]}
        return self.plan.unflatten_tree(flats, like)

    # ---- bandwidth tiers (hierarchical two-level decomposition) ----------
    #
    # A hierarchical (multi-slice) mesh has two link classes: the ``intra``
    # axis rides slice-local ICI, the ``inter`` axis rides cross-slice DCN
    # with orders of magnitude less bandwidth.  The reference's
    # Leader/Worker hierarchical communicator (communicators/mod.rs:243-336)
    # exists to keep the slow link's bytes minimal; the TPU rendering is a
    # true two-level decomposition
    #
    #     slice-local reduce-scatter  ->  cross-slice allreduce on the
    #     1/intra_size shard          ->  slice-local allgather
    #
    # so DCN carries ``1/intra_size`` of each bucket's bytes instead of the
    # full bucket the old nested-psum form moved.  Each stage is available
    # fused (psum_scatter/psum/all_gather) or as the chunked
    # double-buffered rings with PER-TIER chunk sizing.

    def two_tier(self) -> bool:
        """Whether the two-level decomposition is available: both tier
        communicators exist and together tile the comm world exactly (an
        extra comm axis — e.g. ``sp`` folded in for partial-grad summation
        — would be skipped by the tiered stages, so it forces the flat
        path; same guard as ZeRO's staged layout)."""
        return (
            self.internode is not None
            and self.intranode is not None
            and self.internode is not self.intranode
            and self.intranode.nranks() > 1
            and self.world_size
            == self.internode.nranks() * self.intranode.nranks()
        )

    def chunk_bytes_for(self, link_class: str) -> Optional[int]:
        """The ring chunk target for one link class: the per-tier knob
        where set, else the link-agnostic :attr:`overlap_chunk_bytes`."""
        from ..communication import LINK_DCN

        tier = (self.inter_chunk_bytes if link_class == LINK_DCN
                else self.intra_chunk_bytes)
        return tier if tier else self.overlap_chunk_bytes

    def _comm_chunks(self, comm: BaguaCommunicator, numel: int,
                     itemsize: int, link_class: str) -> int:
        """Sub-collective count for one tier's collective over ``comm``
        (1 = keep the fused XLA primitive).  The ONE gate for every bucket
        collective — flat and tiered — so the ring can never apply to one
        half of a scatter/gather pair and not the other."""
        from ..communication import ring_chunks_for

        target = self.chunk_bytes_for(link_class)
        if not target:
            return 1
        if len(comm.axes) != 1 or comm.nranks() <= 1:
            return 1  # ring permutes over exactly one mesh axis
        return ring_chunks_for(numel, itemsize, comm.nranks(), target,
                               link_class)

    def _ring_chunks(self, numel: int, itemsize: int) -> int:
        """Chunk gate for the FLAT (whole comm world) path."""
        from ..communication import LINK_ICI

        return self._comm_chunks(self.comm, numel, itemsize, LINK_ICI)

    # -- per-tier stage helpers (shared by allreduce/bytegrad/zero) --------

    def tier_reduce_scatter(self, flat, op: ReduceOp, codec=None):
        """Slice-local (ICI) reduce-scatter of ``flat`` — this rank's
        contiguous 1/intra chunk, ring-chunked against the ICI target.
        The ICI codec policy resolves against ``codec`` as the family
        default (full precision unless the knob names a codec — ICI bytes
        are cheap)."""
        from ..communication import LINK_ICI

        codec = self.codec_for(LINK_ICI, codec)
        k = self._comm_chunks(self.intranode, flat.shape[0],
                              flat.dtype.itemsize, LINK_ICI)
        if codec is not None:
            return self.intranode.ring_reduce_scatter(
                flat, op, num_chunks=k, codec=codec
            )
        if k > 1:
            return self.intranode.ring_reduce_scatter(flat, op, num_chunks=k)
        return self.intranode.reduce_scatter(flat, op)

    def tier_allreduce(self, chunk, op: ReduceOp, codec=None):
        """Cross-slice (DCN) allreduce of this rank's shard, ring-chunked
        against the DCN target — the only stage whose bytes cross the slow
        link, and therefore the stage the codec policy compresses: with a
        resolved codec the shard rides the compressed ring (quantized
        ppermute hops, fp32 accumulation), so compressed bytes are what
        actually cross DCN."""
        from ..communication import LINK_DCN

        codec = self.codec_for(LINK_DCN, codec)
        k = self._comm_chunks(self.internode, chunk.shape[0],
                              chunk.dtype.itemsize, LINK_DCN)
        if codec is not None:
            return self.internode.ring_allreduce(
                chunk, op, num_chunks=k, codec=codec
            )
        if k > 1:
            return self.internode.ring_allreduce(chunk, op, num_chunks=k)
        return self.internode.allreduce(chunk, op)

    def tier_allgather(self, chunk, codec=None):
        """Slice-local (ICI) allgather of this rank's chunk back to the
        full flat — same chunk gate as :meth:`tier_reduce_scatter` (sized
        on the full flat the chunk tiles) so the pair stays
        layout-symmetric."""
        from ..communication import LINK_ICI

        codec = self.codec_for(LINK_ICI, codec)
        k = self._comm_chunks(
            self.intranode, chunk.shape[0] * self.intranode.nranks(),
            chunk.dtype.itemsize, LINK_ICI,
        )
        if codec is not None:
            return self.intranode.ring_allgather(chunk, num_chunks=k,
                                                 codec=codec)
        if k > 1:
            return self.intranode.ring_allgather(chunk, num_chunks=k)
        return self.intranode.allgather(chunk, axis=0, tiled=True)

    def two_level_allreduce(self, flat, op: ReduceOp, dcn_codec=None):
        """The two-level hierarchical allreduce of one flat buffer:
        reduce-scatter over ``intra``, allreduce the 1/intra shard over
        ``inter``, allgather over ``intra``.  Buffers the intra world does
        not divide are zero-padded internally (sound for SUM/AVG) and
        sliced back.  AVG divides ONCE by the comm world after the summing
        stages — the same single division the flat ``pmean`` applies, so
        the only difference from the flat path is sum association order.
        ``dcn_codec`` is the family default for the DCN stage (the codec
        policy's ``auto`` resolution); with a codec the DCN ring's
        broadcast phase quantizes the UNDIVIDED inter-sum and the world
        division scales the dequantized fp32 afterwards — quantization is
        scale-invariant, so this equals dividing first."""
        assert op in (ReduceOp.SUM, ReduceOp.AVG), op
        n_intra = self.intranode.nranks()
        size = flat.shape[0]
        from ..communication import LINK_ICI

        ki = self._comm_chunks(self.intranode, size, flat.dtype.itemsize,
                               LINK_ICI)
        pad = (-size) % (n_intra * ki)
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)]
            )
        chunk = self.tier_reduce_scatter(flat, ReduceOp.SUM)
        chunk = self.tier_allreduce(chunk, ReduceOp.SUM, codec=dcn_codec)
        if op == ReduceOp.AVG:
            chunk = chunk / self.world_size
        full = self.tier_allgather(chunk)
        return full[:size] if pad else full

    def hierarchical_allreduce(self, flat, op: ReduceOp, hierarchical: bool,
                               dcn_codec=None):
        """Hierarchical = the two-level decomposition above (DCN carries the
        1/intra shard); non-hierarchical = one fused collective over the
        whole comm world.  Ops beyond SUM/AVG (and non-flat operands) keep
        the legacy nested form — correct, just not shard-reduced."""
        if not (hierarchical and self.two_tier()):
            return self.comm.allreduce(flat, op)
        if op not in (ReduceOp.SUM, ReduceOp.AVG) or jnp.ndim(flat) != 1:
            flat = self.intranode.allreduce(flat, op)
            return self.internode.allreduce(flat, op)
        return self.two_level_allreduce(flat, op, dcn_codec)

    def bucket_allreduce(self, flat, op: ReduceOp, hierarchical: bool,
                         dcn_codec=None):
        """One bucket's gradient allreduce under the active comm config:
        the two-level decomposition on hierarchical two-tier meshes
        (per-tier ring chunking when the overlap scheduler set targets,
        compressed DCN hops when the codec policy resolves one), the
        chunked double-buffered ring when a chunk size OR flat codec is
        set on a single-axis comm world, else the fused psum path.  The
        serialized non-hierarchical construction (``overlap=off``, codec
        knobs at default) always takes the fused psum path."""
        if hierarchical and self.two_tier():
            return self.hierarchical_allreduce(flat, op, True, dcn_codec)
        flat_codec = self.flat_ring_codec()
        k = self._ring_chunks(flat.shape[0], flat.dtype.itemsize)
        if flat_codec is not None:
            # a forced flat codec rides the ring for hierarchical
            # families too: past the branch above the hierarchical flag
            # is inert (two_tier() failed — hierarchical_allreduce would
            # lower the same fused psum), and the byte accounting
            # resolves through the identical flat_ring_codec gate, so
            # honoring the knob here is what keeps the spans truthful
            return self.comm.ring_allreduce(flat, op, num_chunks=k,
                                            codec=flat_codec)
        if k > 1 and not hierarchical:
            return self.comm.ring_allreduce(flat, op, num_chunks=k)
        return self.hierarchical_allreduce(flat, op, hierarchical)

    def bucket_reduce_scatter(self, flat, op: ReduceOp):
        """One bucket's reduce-scatter (ZeRO's grad half) under the active
        comm config; chunk layout is identical between the ring and
        ``psum_scatter`` paths (rank r owns the r-th contiguous slice).
        A knob-forced flat codec compresses these rings too — every
        family riding the flat tier honors the forced policy, so the byte
        accounting's claim stays true for ZeRO's scatter/gather dance."""
        codec = self.flat_ring_codec()
        k = self._ring_chunks(flat.shape[0], flat.dtype.itemsize)
        if codec is not None:
            return self.comm.ring_reduce_scatter(flat, op, num_chunks=k,
                                                 codec=codec)
        if k > 1:
            return self.comm.ring_reduce_scatter(flat, op, num_chunks=k)
        return self.comm.reduce_scatter(flat, op)

    def bucket_allgather(self, chunk):
        """Re-replication half of ZeRO's dance (this rank's chunk -> full
        flat), chunked-ring under the active comm config — same gate as
        :meth:`bucket_reduce_scatter` (sized on the full flat the chunk
        tiles) so the pair stays layout-symmetric."""
        codec = self.flat_ring_codec()
        k = self._ring_chunks(chunk.shape[0] * self.comm.nranks(),
                              chunk.dtype.itemsize)
        if codec is not None:
            return self.comm.ring_allgather(chunk, num_chunks=k,
                                            codec=codec)
        if k > 1:
            return self.comm.ring_allgather(chunk, num_chunks=k)
        return self.comm.allgather(chunk, axis=0, tiled=True)

    # -- bandwidth-tier-aware launch schedule ------------------------------

    def _wire_bytes(self, numel: int, itemsize: int, codec_name) -> int:
        """Host-side wire bytes of one ``numel``-element operand under a
        resolved codec name (None = full precision)."""
        if codec_name is None:
            return int(numel) * int(itemsize)
        from ..compression.codecs import get_codec

        return get_codec(codec_name).wire_bytes(int(numel))

    def bucket_tier_bytes(self, index: int, hierarchical: bool = True,
                          dcn_codec=None, flat_codec=None) -> dict:
        """Host-side per-tier bytes-on-wire estimate for one bucket's
        gradient collective under the ACTIVE config (ring model: a tier's
        allreduce moves ``2(n-1)/n`` of its operand, a scatter/gather half
        moves ``(n-1)/n``).  ``dcn_bytes`` is what crosses the slow link —
        the number the two-level decomposition exists to shrink, and the
        key the tier-aware overlap scheduler orders launches by.  On a
        tier-less mesh there is no slow link at all — ``dcn_bytes`` is 0.
        On a two-tier mesh with ``hierarchical=False``, ``dcn_bytes``
        reports the slow-link bytes the flat collective DOES pay there
        (its full operand crosses the slice boundary) — the comparison
        number the two-level decomposition is judged against.

        ``dcn_codec``/``flat_codec`` are the algorithm family's wire-codec
        defaults (``Algorithm.wire_codec_dcn``/``wire_codec_flat``); the
        tier knobs override them through :meth:`codec_for`, and the
        estimate then reports COMPRESSED wire bytes — so the launch spans,
        the DCN-first launch order, and ``obs/device_comm_dcn_s``
        attribution describe what actually crosses the wire, not the fp32
        operand the codec replaced."""
        import numpy as np

        b = self.plan.buckets[index]
        from ..communication import LINK_DCN, LINK_ICI

        itemsize = int(np.dtype(b.dtype).itemsize)
        numel = int(b.padded_numel)
        nbytes = numel * itemsize
        # the flat wire codec resolved exactly as the COLLECTIVES resolve
        # it — the accounting must never report compressed bytes the wire
        # did not carry.  A scatter-gather family (flat_codec set)
        # compresses on any comm world with its own pipeline unless the
        # knob forces `off` (a forced codec NAME keeps the family's
        # minmax pipeline — one wire format there); an exact family
        # compresses only when the knob names a codec AND the flat ring
        # can carry it (flat_ring_codec's validity gate).
        if flat_codec is not None:
            resolved_flat = (
                flat_codec
                if self.codec_for(LINK_ICI, flat_codec) is not None
                else None
            )
        else:
            resolved_flat = self.flat_ring_codec(warn=False)
        if not self.two_tier():
            wire = self._wire_bytes(numel, itemsize, resolved_flat)
            return {"tier": "flat", "bytes": nbytes,
                    "ici_bytes": wire, "dcn_bytes": 0,
                    "dcn_codec": None,
                    "flat_codec": resolved_flat}
        if not hierarchical:
            ne = self.internode.nranks()
            wire = self._wire_bytes(numel, itemsize, resolved_flat)
            return {"tier": "flat", "bytes": nbytes,
                    "ici_bytes": wire,
                    "dcn_bytes": int(2 * wire * (ne - 1) // ne),
                    "dcn_codec": resolved_flat,
                    "flat_codec": resolved_flat}
        ni = self.intranode.nranks()
        ne = self.internode.nranks()
        resolved_dcn = self.codec_for(LINK_DCN, dcn_codec)
        # the intra tier is single-axis with >1 ranks by two_tier(), so a
        # knob-forced ICI codec always engages its rings
        ici_codec = self.codec_for(LINK_ICI, None)
        ici_wire = self._wire_bytes(numel, itemsize, ici_codec)
        # full precision keeps the byte-granularity shard estimate the
        # launch-order pin certifies; a codec's payload is per-ELEMENT, so
        # its estimate rides the element-granularity shard
        dcn_wire = (
            -(-numel * itemsize // ni) if resolved_dcn is None
            else self._wire_bytes(-(-numel // ni), itemsize, resolved_dcn)
        )
        return {
            "tier": "two_level",
            "bytes": nbytes,
            # rs + ag halves over intra: 2 * (ni-1)/ni of the flat
            "ici_bytes": int(2 * ici_wire * (ni - 1) // ni),
            # the inter allreduce moves 2(ne-1)/ne of the 1/ni shard —
            # compressed where the codec policy resolves one
            "dcn_bytes": int(2 * dcn_wire * (ne - 1) // ne) if ne > 1 else 0,
            "dcn_codec": resolved_dcn if ne > 1 else None,
            "flat_codec": None,
        }

    def bucket_launch_order(self, hierarchical: bool,
                            dcn_codec=None) -> List[int]:
        """Launch order for the overlap scheduler's per-bucket collectives.
        On a two-tier mesh with the hierarchical path active, buckets whose
        DCN stage dominates are streamed FIRST (descending cross-slice
        bytes — COMPRESSED wire bytes where a codec rides the tier, stable)
        so the slow link is busy for the whole backward window; everywhere
        else the plan's (readiness) order stands.  Results are still
        assembled in plan order — only the traced issue order changes, so
        overlap-vs-serialized numerics are untouched."""
        n = len(self.plan.buckets)
        if not (self.overlap and hierarchical and self.two_tier()):
            return list(range(n))
        dcn = [self.bucket_tier_bytes(i, hierarchical,
                                      dcn_codec=dcn_codec)["dcn_bytes"]
               for i in range(n)]
        return sorted(range(n), key=lambda i: -dcn[i])


class Algorithm:
    """Base algorithm: plain distributed data parallelism hooks.

    Subclasses override stages; the default implementation is a no-op pass
    (gradients unchanged), matching the reference's ``Algorithm`` which only
    wires default bucketing/marking (base.py:24-125).
    """

    #: False for gossip-style algorithms whose weights differ across ranks;
    #: the trainer then keeps params/opt/algo state stacked per rank.
    replicated_params: bool = True
    #: True when the algorithm provides its own optimizer update (QAdam).
    owns_optimizer: bool = False
    #: True when the optimizer state is sharded over the comm axes (ZeRO-1):
    #: params stay replicated but opt_state is built per rank inside
    #: shard_map via ``init_optimizer_state_sharded(ctx, params)``.
    sharded_opt_state: bool = False
    #: Alignment for bucket padding (compressed ops need world_size).
    bucket_alignment: int = 1
    #: Hierarchical (intra-node then inter-node) communication.
    hierarchical: bool = False
    #: Overlap contract: when True the trainer's overlap scheduler may call
    #: :meth:`reduce_bucket_grad` once per bucket — in gradient-readiness
    #: order, as each bucket's accumulated gradient finalizes — instead of
    #: the whole-tree :meth:`process_grads`, then hand the per-bucket
    #: results to :meth:`grads_from_reduced`.  Families whose gradient comm
    #: is not a per-bucket map (gossip weight exchanges, QAdam's momentum
    #: pipeline) keep False and always run serialized.
    supports_overlap: bool = False
    #: Whether ``overlap="auto"`` may pick the overlap path for this family
    #: (explicit ``overlap="on"`` always wins).  Set False where the
    #: measured record (BENCH_OVERLAP.json) shows the serialized path
    #: faster despite the family supporting the contract.
    overlap_auto: bool = True
    #: Flat-resident contract: when True the trainer may keep params /
    #: grads / optimizer state as bucket-flat buffers across steps
    #: (``{"flats", "local"}`` containers) and every traced stage must go
    #: through :meth:`AlgorithmContext.bucket_flats` /
    #: :meth:`AlgorithmContext.from_bucket_flats` instead of touching leaf
    #: pytrees.  Families whose stages inspect leaf shapes stay False and
    #: always run the leaf layout.
    supports_flat_resident: bool = False
    #: Whether ``flat_resident="auto"`` may pick the resident layout for
    #: this family (explicit ``flat_resident="on"`` always wins) — the
    #: measured-record gate, like :attr:`overlap_auto` (BENCH_FLAT.json).
    flat_resident_auto: bool = True
    #: Straggler coupling: True when every train step synchronizes with
    #: every rank (a per-step gradient collective), so a slow peer gates
    #: the step — the ``step.straggle`` fault point then dilates each step.
    #: Asynchronous families whose steps run on stale local weights set
    #: False: a straggler binds them only at their own negotiated
    #: boundaries (they call :func:`bagua_tpu.faults.inject.maybe_straggle`
    #: there themselves).
    straggler_gates_step: bool = True
    #: Wire-codec defaults for the byte accounting AND the codec policy's
    #: ``auto`` resolution (docs/compression.md): ``wire_codec_dcn`` names
    #: the codec the family's hierarchical path rides on the cross-slice
    #: DCN stage (ByteGrad/QAdam compress it natively), ``wire_codec_flat``
    #: the codec its non-hierarchical bucket collective carries (ByteGrad's
    #: compressed scatter-gather).  None = full precision.
    wire_codec_dcn: Optional[str] = None
    wire_codec_flat: Optional[str] = None
    #: Error-feedback state contract: True when the family's gradient comm
    #: is the per-bucket flat reduction of :meth:`process_grads_bucketed` /
    #: :meth:`reduce_bucket_grad`, so a per-bucket fp32 residual flat can
    #: ride ``algo_state`` and :meth:`compensate_flats` can fold it into the
    #: buckets before they hit the wire.  Families whose comm is not a
    #: bucket map (gossip exchanges, QAdam's momentum pipeline, ZeRO's
    #: scatter/gather ownership) keep False — an error-feedback codec forced
    #: onto them rides STATELESS with a loud once-per-run warning.
    supports_ef_state: bool = False
    #: Gradient-health sentinel contract: True when the family's POST-comm
    #: gradient representation is bitwise-identical on every rank (a plain
    #: summed/averaged bucket reduce), so the per-bucket ``isfinite``
    #: verdict computed on it is already globally consistent — the guard
    #: then piggybacks on the existing bucket collectives with no extra
    #: launch (non-finite contributions survive the sum).  Families whose
    #: gradients stay rank-local or sharded after comm (gossip exchanges,
    #: ZeRO chunks, QAdam's compressed-momentum pipeline) keep False and
    #: the trainer fuses their local verdicts with one tiny ``pmin``.
    grad_health_replicated: bool = False

    def need_reset(self, step: int) -> bool:
        """Host-side: return True to rebuild buckets/recompile (reference
        base.py:15-22, used by QAdam's warmup boundary)."""
        return False

    def compile_key(self) -> tuple:
        """Host-side state that changes the TRACED program (beyond the
        phase counter).  Part of the trainer's compiled-step cache key —
        without it, flipping such state (e.g. QAdam's ``_compressed`` after
        an autotune switch re-anchors its warmup) would silently reuse a
        stale compile."""
        return ()

    def init_tensors(self, named_params: Sequence[NamedParam]) -> List[NamedParam]:
        """Which tensors to communicate, in registration order (reference
        base.py:24-49 registers grads in reversed module order — the caller
        already passes reversed order)."""
        return list(named_params)

    def tensors_to_buckets(
        self,
        decl_buckets: Sequence[Sequence[TensorDeclaration]],
        named_params: Sequence[NamedParam],
        world_size: int,
    ) -> BucketPlan:
        """Declarations -> concrete plan (reference base.py:51-70)."""
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=self.bucket_alignment
        )

    # ---- traced stages --------------------------------------------------

    def init_state(self, ctx: AlgorithmContext, params) -> Any:
        """Create algorithm state (peer-weight replicas, momenta, ...).
        The base state is the error-feedback residual container when an EF
        codec is active under this context, else None."""
        return self.ef_init_state(ctx, None)

    # ---- error-feedback residual (stateful codecs) -----------------------
    #
    # The 1-bit and top-k codecs are BIASED quantizers: their per-step error
    # does not average out, so SGD on their raw output diverges.  Error
    # feedback (EF-SignSGD, arXiv:1901.09847; 1-bit Adam, arXiv:2102.02888)
    # restores convergence by carrying the quantization error forward: each
    # step compresses ``grad + residual`` and keeps the part the wire lost.
    # The residual lives in ``algo_state["ef"]["buckets"]`` as one fp32 flat
    # per bucket ([1, padded_numel] per shard, stacked [world, padded_numel]
    # globally) so it rides the existing state machinery: grad-guard skips
    # rewind it with the step, rebuckets migrate it through
    # ``relayout_flats``, and checkpoints carry it with a layout sidecar.
    #
    # One local encode/decode roundtrip per bucket models the wire error.
    # The ring's per-hop re-quantization of PARTIAL sums is not captured —
    # the residual compensates the dominant (input quantization) error term,
    # which is the published algorithms' formulation too; the hop error
    # shrinks with chunk count and accumulates in fp32.

    def ef_codec(self, ctx: AlgorithmContext):
        """The error-feedback codec whose residual this family accumulates
        under the ACTIVE config, or None.  Resolution mirrors what the
        wire actually carries: the DCN then ICI tier codecs on the
        hierarchical two-tier path, the flat ring codec otherwise (skipped
        for scatter-gather families with their own flat pipeline — a
        forced codec NAME never engages there, so neither may EF).  An EF
        codec that resolves on an unsupported family, or with the residual
        disabled (``BAGUA_EF_RESIDUAL=off`` — the honesty control), rides
        STATELESS with a once-per-run warning."""
        from ..communication import LINK_DCN, LINK_ICI
        from ..compression.codecs import get_codec

        names: List = []
        if getattr(self, "hierarchical", False) and ctx.two_tier():
            names.append(ctx.codec_for(LINK_DCN, self.wire_codec_dcn))
            names.append(ctx.codec_for(LINK_ICI, None))
        elif self.wire_codec_flat is None:
            names.append(ctx.flat_ring_codec(warn=False))
        codec = None
        for name in names:
            if name is None:
                continue
            c = get_codec(name)
            if getattr(c, "error_feedback", False):
                codec = c
                break
        if codec is None:
            return None
        if self.supports_ef_state and ctx.ef_enabled:
            return codec
        reason = ("unsupported_family" if not self.supports_ef_state
                  else "residual_disabled")
        key = (type(self).__name__, codec.name, reason)
        if key not in _EF_STATELESS_WARNED:
            _EF_STATELESS_WARNED.add(key)
            logger.warning(
                "codec %r is an error-feedback codec but its residual is "
                "OFF (%s) for %s: the wire carries raw %s output, whose "
                "quantization bias is known to stall/diverge SGD — only "
                "use this as a convergence control",
                codec.name, reason, type(self).__name__, codec.name,
            )
        return None

    def ef_init_state(self, ctx: AlgorithmContext, state: Any) -> Any:
        """Merge the error-feedback residual container into ``state``
        (traced, per shard): one zero fp32 flat per bucket — this shard's
        ``[1, padded_numel]`` row of the stacked ``[world, padded_numel]``
        global.  Identity when no EF codec is active, so families that
        build their own state just wrap it through here."""
        if self.ef_codec(ctx) is None:
            return state
        ef = {"buckets": tuple(
            jnp.zeros((1, b.padded_numel), jnp.float32)
            for b in ctx.plan.buckets
        )}
        if state is None:
            return {"ef": ef}
        assert isinstance(state, dict) and "ef" not in state, state
        return {**state, "ef": ef}

    def algo_state_specs(self, ctx: AlgorithmContext, default, stacked):
        """shard_map partition specs (pytree prefixes) for this family's
        algo state: ``default`` is the trainer's replicated spec,
        ``stacked`` its per-rank stacked-leading-axis spec — which is what
        the EF residual's ``[world, padded_numel]`` buckets ride."""
        if self.ef_codec(ctx) is None:
            return default
        return {"ef": stacked}

    def compensate_flats(self, ctx: AlgorithmContext, flats, algo_state):
        """Fold the per-bucket error-feedback residual into the bucket
        flats about to hit the wire and accumulate the new quantization
        error: ``c = grad + r``; the wire carries ``encode(c)``; ``r' =
        c - decode(encode(c))``.  Identity (no traced ops at all) when no
        EF codec is active — the compiled step with compression off is
        byte-identical to one without this hook."""
        codec = self.ef_codec(ctx)
        if codec is None:
            return flats, algo_state
        ef = algo_state.get("ef") if isinstance(algo_state, dict) else None
        if ef is None:
            # state predates the codec flip; the trainer's knob-sync
            # migration adds the container before the next compiled step
            return flats, algo_state
        out, residuals = [], []
        for flat, res in zip(flats, ef["buckets"]):
            c = flat.astype(jnp.float32) + res[0]
            dec = codec.decode(codec.encode(c[None, :]), c.shape[0])[0]
            residuals.append((c - dec)[None, :])
            out.append(c.astype(flat.dtype))
        new_state = dict(algo_state)
        new_state["ef"] = {"buckets": tuple(residuals)}
        return out, new_state

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        """Gradient communication stage (runs where the reference's backward
        hooks + wait_pending_comm_ops ran)."""
        return grads, algo_state

    # ---- overlap scheduler stages (supports_overlap families) -----------

    def reduce_bucket_grad(self, ctx: AlgorithmContext, index: int, flat):
        """Communicate ONE bucket's final flat gradient (traced).  The
        trainer's overlap scheduler calls this per bucket so each
        collective's operands are exactly that bucket's finalized gradient —
        open dataflow XLA's latency-hiding scheduler can overlap with the
        backward compute still producing later buckets.  Returns the
        communicated buffer: the full reduced flat for dense families, this
        rank's owned chunk for sharded-opt-state families."""
        raise NotImplementedError(
            f"{type(self).__name__} does not implement the overlap contract"
        )

    def grads_from_reduced(self, ctx: AlgorithmContext, reduced, grads,
                           algo_state, step):
        """Assemble the post-communication gradient representation from the
        per-bucket :meth:`reduce_bucket_grad` results (the overlap path's
        replacement for :meth:`process_grads`).  Default: rebuild the
        gradient layout from the reduced buckets — the resident flat
        container under flat residency, the leaf unflatten otherwise."""
        return ctx.from_bucket_flats(reduced, grads), algo_state

    def process_grads_bucketed(self, ctx: AlgorithmContext, grads, params,
                               algo_state, step):
        """The serialized comm stage for ``supports_overlap`` families:
        the same per-bucket reduction the overlap scheduler streams, issued
        after the full backward — one implementation, so the two paths
        cannot drift numerically.  Dense families alias ``process_grads``
        to this.  Under the flat-resident layout the grads already ARE the
        bucket flats, so this stage communicates them with zero repacking.
        Launch order rides :meth:`AlgorithmContext.bucket_launch_order`
        (DCN-dominant buckets first on hierarchical two-tier meshes under
        the overlap scheduler); results assemble in plan order."""
        flats = ctx.bucket_flats(grads)
        flats, algo_state = self.compensate_flats(ctx, flats, algo_state)
        order = ctx.bucket_launch_order(getattr(self, "hierarchical", False),
                                        dcn_codec=self.wire_codec_dcn)
        reduced: List = [None] * len(flats)
        for i in order:
            reduced[i] = self.reduce_bucket_grad(ctx, i, flats[i])
        return self.grads_from_reduced(ctx, reduced, grads, algo_state, step)

    # ---- flat-resident layout hooks (supports_flat_resident families) ----

    def relayout_algo_state(self, old_plan, new_plan, algo_state):
        """Migrate plan-keyed algorithm state when the trainer re-buckets
        resident flat state (autotune / overlap-readiness re-bucketing,
        cross-plan checkpoint restore).  Families whose state holds flat
        bucket buffers (gossip peer replicas) override with a
        :func:`bagua_tpu.bucket.relayout_flats` pass; param-shaped or empty
        state needs no migration."""
        if algo_state is None:
            return None
        if isinstance(algo_state, dict) and set(algo_state) == {"ef"}:
            from ..bucket import relayout_flats

            flats = relayout_flats(old_plan, new_plan,
                                   list(algo_state["ef"]["buckets"]))
            # the residual is fp32 regardless of the bucket dtype the
            # relayout cast its segments through (exact for fp32 plans;
            # sub-fp32 plans round the carried error once per rebucket)
            return {"ef": {"buckets": tuple(
                f.astype(jnp.float32) for f in flats
            )}}
        raise NotImplementedError(
            f"{type(self).__name__} carries algorithm state but does not "
            "implement relayout_algo_state; re-bucketing its flat-resident "
            "state would corrupt plan-keyed buffers"
        )

    def process_pre_step(self, ctx: AlgorithmContext, params, algo_state, step):
        """Weight transformation after backward, before the optimizer update
        (the reference's post-backward copy-back for decentralized ops)."""
        return params, algo_state

    def process_post_step(self, ctx: AlgorithmContext, params, algo_state, step):
        """Weight transformation after the optimizer update (the reference's
        post-optimizer-step hook, used by low-precision decentralized)."""
        return params, algo_state

    def optimizer_update(self, ctx, params, grads, opt_state, algo_state, step):
        raise NotImplementedError("only algorithms with owns_optimizer=True")

    def init_optimizer_state(self, params):
        raise NotImplementedError("only algorithms with owns_optimizer=True")

    # ---- host-side hook --------------------------------------------------

    def host_pre_step(self, trainer, state):
        """Host-side (untraced) hook run at the top of every
        ``BaguaTrainer.train_step`` — the between-steps boundary where
        asynchronous algorithms swap weights (reference async
        init_forward_pre_hook's lock, async_model_average.py:156-168)."""
        return state

    def on_restore(self, trainer) -> None:
        """Host-side hook run after ``BaguaTrainer.restore_checkpoint``
        materialized a state for this trainer (elastic restarts included).
        Algorithms carrying host-side schedule state tied to the PREVIOUS
        run (async model averaging's in-flight round, launch anchor, agreed
        period) reset it here so the resumed run starts from a clean
        window instead of consuming stale cross-resize state."""
        return None
