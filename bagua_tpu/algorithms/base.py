"""Algorithm base class — the pluggable "what/when to communicate" contract.

Counterpart of /root/reference/bagua/torch_api/algorithms/base.py:8-156.  The
reference's 7 hooks are driven by autograd events (grad-ready marks, post
backward, post optimizer step); under XLA the whole train step is one traced
program, so the hooks become *functional stages* of the step:

  reference hook                        bagua_tpu stage
  ------------------------------------  ----------------------------------
  init_tensors / tensors_to_buckets     init_tensors / tensors_to_buckets (same)
  init_forward_pre_hook (mark ready)    (implicit: XLA schedules collectives)
  init_backward_hook (per-grad mark)    process_grads (bucketed comm on grads)
  init_post_backward_hook (wait ops)    process_pre_step (weight comm lands here)
  init_post_optimizer_step_hook         process_post_step
  init_operations                       the body of the stages above
  need_reset                            need_reset (host-side, triggers rebuild)

All stages except ``need_reset``/``init_tensors``/``tensors_to_buckets`` are
traced inside ``shard_map`` over the data-parallel mesh axes and may call
collectives through ``ctx``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..bucket import BucketPlan
from ..communication import BaguaCommunicator, ReduceOp
from ..define import TensorDeclaration
from ..tensor import NamedParam


@dataclass
class AlgorithmContext:
    """Static per-compile context handed to traced algorithm stages."""

    comm: BaguaCommunicator              # spans all dp axes ("global")
    internode: Optional[BaguaCommunicator]
    intranode: Optional[BaguaCommunicator]
    plan: BucketPlan
    world_size: int

    def hierarchical_allreduce(self, flat, op: ReduceOp, hierarchical: bool):
        """Hierarchical = intra-node stage then inter-node stage, the reference's
        Leader/Worker pattern (communicators/mod.rs:243-336) collapsed into
        nested mesh-axis collectives (XLA routes intra over ICI, inter over DCN)."""
        if (
            hierarchical
            and self.internode is not None
            and self.intranode is not None
            and self.internode is not self.intranode
        ):
            flat = self.intranode.allreduce(flat, op)
            return self.internode.allreduce(flat, op)
        return self.comm.allreduce(flat, op)


class Algorithm:
    """Base algorithm: plain distributed data parallelism hooks.

    Subclasses override stages; the default implementation is a no-op pass
    (gradients unchanged), matching the reference's ``Algorithm`` which only
    wires default bucketing/marking (base.py:24-125).
    """

    #: False for gossip-style algorithms whose weights differ across ranks;
    #: the trainer then keeps params/opt/algo state stacked per rank.
    replicated_params: bool = True
    #: True when the algorithm provides its own optimizer update (QAdam).
    owns_optimizer: bool = False
    #: True when the optimizer state is sharded over the comm axes (ZeRO-1):
    #: params stay replicated but opt_state is built per rank inside
    #: shard_map via ``init_optimizer_state_sharded(ctx, params)``.
    sharded_opt_state: bool = False
    #: Alignment for bucket padding (compressed ops need world_size).
    bucket_alignment: int = 1
    #: Hierarchical (intra-node then inter-node) communication.
    hierarchical: bool = False

    def need_reset(self, step: int) -> bool:
        """Host-side: return True to rebuild buckets/recompile (reference
        base.py:15-22, used by QAdam's warmup boundary)."""
        return False

    def compile_key(self) -> tuple:
        """Host-side state that changes the TRACED program (beyond the
        phase counter).  Part of the trainer's compiled-step cache key —
        without it, flipping such state (e.g. QAdam's ``_compressed`` after
        an autotune switch re-anchors its warmup) would silently reuse a
        stale compile."""
        return ()

    def init_tensors(self, named_params: Sequence[NamedParam]) -> List[NamedParam]:
        """Which tensors to communicate, in registration order (reference
        base.py:24-49 registers grads in reversed module order — the caller
        already passes reversed order)."""
        return list(named_params)

    def tensors_to_buckets(
        self,
        decl_buckets: Sequence[Sequence[TensorDeclaration]],
        named_params: Sequence[NamedParam],
        world_size: int,
    ) -> BucketPlan:
        """Declarations -> concrete plan (reference base.py:51-70)."""
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=self.bucket_alignment
        )

    # ---- traced stages --------------------------------------------------

    def init_state(self, ctx: AlgorithmContext, params) -> Any:
        """Create algorithm state (peer-weight replicas, momenta, ...)."""
        return None

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        """Gradient communication stage (runs where the reference's backward
        hooks + wait_pending_comm_ops ran)."""
        return grads, algo_state

    def process_pre_step(self, ctx: AlgorithmContext, params, algo_state, step):
        """Weight transformation after backward, before the optimizer update
        (the reference's post-backward copy-back for decentralized ops)."""
        return params, algo_state

    def process_post_step(self, ctx: AlgorithmContext, params, algo_state, step):
        """Weight transformation after the optimizer update (the reference's
        post-optimizer-step hook, used by low-precision decentralized)."""
        return params, algo_state

    def optimizer_update(self, ctx, params, grads, opt_state, algo_state, step):
        raise NotImplementedError("only algorithms with owns_optimizer=True")

    def init_optimizer_state(self, params):
        raise NotImplementedError("only algorithms with owns_optimizer=True")

    # ---- host-side hook --------------------------------------------------

    def host_pre_step(self, trainer, state):
        """Host-side (untraced) hook run at the top of every
        ``BaguaTrainer.train_step`` — the between-steps boundary where
        asynchronous algorithms swap weights (reference async
        init_forward_pre_hook's lock, async_model_average.py:156-168)."""
        return state
