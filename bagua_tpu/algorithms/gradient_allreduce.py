"""Centralized synchronous full-precision data parallelism.

Counterpart of /root/reference/bagua/torch_api/algorithms/gradient_allreduce.py:8-38
plus its backing comm op
(comm_ops/centralized_full_precision_synchronous.rs:16-56).  One fused
``psum``/``pmean`` per bucket; XLA's latency-hiding scheduler overlaps the
collectives with remaining backward compute, which is the whole job the
reference's Rust scheduler + dedicated CUDA stream existed to do.
"""

from __future__ import annotations

from typing import Optional

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext


class GradientAllReduceAlgorithm(Algorithm):
    name = "gradient_allreduce"
    supports_overlap = True
    #: the per-bucket allreduce consumes resident bucket flats directly
    #: (zero repacking) — measured on-par-to-faster than the leaf layout
    #: on the cpu-sim mesh (BENCH_FLAT.json), so ``auto`` takes it
    supports_flat_resident = True
    #: reduced buckets are replicated (plain psum/ring sum — a NaN/Inf
    #: contribution from any rank survives into every rank's copy), so the
    #: gradient-health sentinel rides them with no extra collective
    grad_health_replicated = True
    #: the per-bucket flat reduction can carry an error-feedback residual
    #: when the codec policy forces a stateful codec (onebit_ef / topk)
    #: onto its rings
    supports_ef_state = True

    def __init__(
        self,
        hierarchical: bool = False,
        average: bool = True,
        comm_dtype: Optional[object] = None,
    ):
        """
        Args:
            hierarchical: Enable hierarchical (intra-node then inter-node)
                communication.
            average: If True average gradients over ranks, else sum.
            comm_dtype: Optional on-the-wire dtype for the allreduce (e.g.
                ``jnp.bfloat16`` halves the bytes on ICI/DCN; gradients are
                cast back afterwards, so params and optimizer state stay in
                full precision).  TPU-idiomatic middle ground between
                full-precision allreduce and ByteGrad's uint8 pipeline —
                bf16 keeps f32's exponent range, so no scale factor is
                needed.  The reduction itself accumulates in f32 (XLA
                upcasts psum accumulators on TPU).
        """
        self.hierarchical = hierarchical
        self.average = average
        self.comm_dtype = comm_dtype

    def reduce_bucket_grad(self, ctx: AlgorithmContext, index: int, flat):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        if self.comm_dtype is None:
            return ctx.bucket_allreduce(flat, op, self.hierarchical)
        orig = flat.dtype
        flat = ctx.bucket_allreduce(
            flat.astype(self.comm_dtype), op, self.hierarchical
        )
        return flat.astype(orig)

    process_grads = Algorithm.process_grads_bucketed
