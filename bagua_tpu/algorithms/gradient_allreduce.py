"""Centralized synchronous full-precision data parallelism.

Counterpart of /root/reference/bagua/torch_api/algorithms/gradient_allreduce.py:8-38
plus its backing comm op
(comm_ops/centralized_full_precision_synchronous.rs:16-56).  One fused
``psum``/``pmean`` per bucket; XLA's latency-hiding scheduler overlaps the
collectives with remaining backward compute, which is the whole job the
reference's Rust scheduler + dedicated CUDA stream existed to do.
"""

from __future__ import annotations

from typing import Optional

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext


class GradientAllReduceAlgorithm(Algorithm):
    name = "gradient_allreduce"

    def __init__(
        self,
        hierarchical: bool = False,
        average: bool = True,
        comm_dtype: Optional[object] = None,
    ):
        """
        Args:
            hierarchical: Enable hierarchical (intra-node then inter-node)
                communication.
            average: If True average gradients over ranks, else sum.
            comm_dtype: Optional on-the-wire dtype for the allreduce (e.g.
                ``jnp.bfloat16`` halves the bytes on ICI/DCN; gradients are
                cast back afterwards, so params and optimizer state stay in
                full precision).  TPU-idiomatic middle ground between
                full-precision allreduce and ByteGrad's uint8 pipeline —
                bf16 keeps f32's exponent range, so no scale factor is
                needed.  The reduction itself accumulates in f32 (XLA
                upcasts psum accumulators on TPU).
        """
        self.hierarchical = hierarchical
        self.average = average
        self.comm_dtype = comm_dtype

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        flats = ctx.plan.flatten_tree(grads)
        orig_dtypes = [f.dtype for f in flats]
        if self.comm_dtype is not None:
            flats = [f.astype(self.comm_dtype) for f in flats]
        flats = [
            ctx.hierarchical_allreduce(f, op, self.hierarchical) for f in flats
        ]
        if self.comm_dtype is not None:
            flats = [f.astype(d) for f, d in zip(flats, orig_dtypes)]
        return ctx.plan.unflatten_tree(flats, grads), algo_state
