"""Centralized synchronous full-precision data parallelism.

Counterpart of /root/reference/bagua/torch_api/algorithms/gradient_allreduce.py:8-38
plus its backing comm op
(comm_ops/centralized_full_precision_synchronous.rs:16-56).  One fused
``psum``/``pmean`` per bucket; XLA's latency-hiding scheduler overlaps the
collectives with remaining backward compute, which is the whole job the
reference's Rust scheduler + dedicated CUDA stream existed to do.
"""

from __future__ import annotations

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext


class GradientAllReduceAlgorithm(Algorithm):
    name = "gradient_allreduce"

    def __init__(self, hierarchical: bool = False, average: bool = True):
        """
        Args:
            hierarchical: Enable hierarchical (intra-node then inter-node)
                communication.
            average: If True average gradients over ranks, else sum.
        """
        self.hierarchical = hierarchical
        self.average = average

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        op = ReduceOp.AVG if self.average else ReduceOp.SUM
        flats = ctx.plan.flatten_tree(grads)
        flats = [ctx.hierarchical_allreduce(f, op, self.hierarchical) for f in flats]
        return ctx.plan.unflatten_tree(flats, grads), algo_state
