"""ByteGrad: 8-bit compressed gradient allreduce.

Counterpart of /root/reference/bagua/torch_api/algorithms/bytegrad.py (buckets
aligned to the world size :38-43, centralized op with
``scattergather=True, compression="MinMaxUInt8"`` :50-56) backed by
comm_ops/centralized_low_precision_synchronous.rs.

Hierarchical mode follows the reference's Leader pattern
(communicators/mod.rs:264-297): average full-precision inside the node (ICI is
cheap), then run the compressed scatter-gather across nodes.
"""

from __future__ import annotations

from ..communication import ReduceOp
from ..compression import compressed_scatter_gather_allreduce
from .base import Algorithm, AlgorithmContext


class ByteGradAlgorithm(Algorithm):
    name = "bytegrad"
    supports_overlap = True
    #: the codec pipeline already runs on flat buckets, so the resident
    #: layout feeds it with zero repacking (BENCH_FLAT.json)
    supports_flat_resident = True
    #: measured (BENCH_OVERLAP.json, 8-dev cpu-sim mesh): the overlap
    #: restructure was never clearly faster for the codec pipeline
    #: (0.69-0.95x in early block runs, noise-bound under interleaved
    #: A/B), so ``auto`` keeps bytegrad serialized; opt in with
    #: ``overlap="on"`` (worth re-measuring on a real multi-chip ICI/DCN
    #: mesh, where the quantize sits on the critical comm path)
    overlap_auto = False

    def __init__(self, hierarchical: bool = True, average: bool = True):
        """
        Args:
            hierarchical: Enable hierarchical communication (intra-node
                full-precision average, inter-node compressed).
            average: If True average the reduced gradients, else sum.
        """
        self.hierarchical = hierarchical
        self.average = average

    def tensors_to_buckets(self, decl_buckets, named_params, world_size):
        from ..bucket import BucketPlan

        # align bucket length to the world size so each rank owns an equal
        # chunk in the scatter-gather (reference bytegrad.py:38-43)
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=world_size
        )

    def reduce_bucket_grad(self, ctx: AlgorithmContext, index: int, flat):
        # the whole codec (compress → alltoall → decompress → chunk-reduce →
        # compress → allgather → decompress) runs per bucket, so under the
        # overlap scheduler it sits inside the overlap window: bucket i's
        # quantize + scatter-gather can proceed while bucket i+1's gradient
        # is still being produced by the backward
        use_hier = (
            self.hierarchical
            and ctx.two_tier()
            and ctx.internode.nranks() > 1
        )
        if use_hier:
            # two-level form, codec on the DCN stage ONLY — compress where
            # bytes are expensive: full-precision slice-local
            # reduce-scatter (ICI is cheap), the compressed scatter-gather
            # runs on the 1/intra shard across slices (DCN carries
            # compressed bytes of the SHARD, not of the whole bucket), then
            # a full-precision slice-local allgather re-replicates.  The
            # shard divides the inter world because buckets are padded to
            # the full world size (tensors_to_buckets above).
            op = ReduceOp.AVG if self.average else ReduceOp.SUM
            chunk = ctx.tier_reduce_scatter(flat, op)
            chunk = compressed_scatter_gather_allreduce(
                ctx.internode, chunk, average=self.average
            )
            return ctx.tier_allgather(chunk)
        if ctx.comm.nranks() > 1:
            return compressed_scatter_gather_allreduce(
                ctx.comm, flat, average=self.average
            )
        return flat

    process_grads = Algorithm.process_grads_bucketed
