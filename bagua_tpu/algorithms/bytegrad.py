"""ByteGrad: 8-bit compressed gradient allreduce.

Counterpart of /root/reference/bagua/torch_api/algorithms/bytegrad.py (buckets
aligned to the world size :38-43, centralized op with
``scattergather=True, compression="MinMaxUInt8"`` :50-56) backed by
comm_ops/centralized_low_precision_synchronous.rs.

Hierarchical mode follows the reference's Leader pattern
(communicators/mod.rs:264-297): average full-precision inside the node (ICI is
cheap), then run the compressed scatter-gather across nodes.
"""

from __future__ import annotations

from ..communication import ReduceOp
from ..compression import compressed_scatter_gather_allreduce
from .base import Algorithm, AlgorithmContext


class ByteGradAlgorithm(Algorithm):
    name = "bytegrad"

    def __init__(self, hierarchical: bool = True, average: bool = True):
        """
        Args:
            hierarchical: Enable hierarchical communication (intra-node
                full-precision average, inter-node compressed).
            average: If True average the reduced gradients, else sum.
        """
        self.hierarchical = hierarchical
        self.average = average

    def tensors_to_buckets(self, decl_buckets, named_params, world_size):
        from ..bucket import BucketPlan

        # align bucket length to the world size so each rank owns an equal
        # chunk in the scatter-gather (reference bytegrad.py:38-43)
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=world_size
        )

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        flats = ctx.plan.flatten_tree(grads)
        use_hier = (
            self.hierarchical
            and ctx.internode is not None
            and ctx.intranode is not None
            and ctx.internode.nranks() > 1
            and ctx.intranode.nranks() > 1
        )
        out = []
        for f in flats:
            if use_hier:
                f = ctx.intranode.allreduce(
                    f, ReduceOp.AVG if self.average else ReduceOp.SUM
                )
                f = compressed_scatter_gather_allreduce(
                    ctx.internode, f, average=self.average
                )
            else:
                comm = ctx.comm
                if comm.nranks() > 1:
                    f = compressed_scatter_gather_allreduce(comm, f, average=self.average)
            out.append(f)
        return ctx.plan.unflatten_tree(out, grads), algo_state
