"""ByteGrad: 8-bit compressed gradient allreduce.

Counterpart of /root/reference/bagua/torch_api/algorithms/bytegrad.py (buckets
aligned to the world size :38-43, centralized op with
``scattergather=True, compression="MinMaxUInt8"`` :50-56) backed by
comm_ops/centralized_low_precision_synchronous.rs.

Hierarchical mode follows the reference's Leader pattern
(communicators/mod.rs:264-297): reduce full-precision inside the slice (ICI
is cheap), compress across slices.  Since ISSUE 15 the cross-slice stage is
the fused compressed ring (``tier_allreduce(codec=)``): each DCN ``ppermute``
hop carries the quantized partial sum + sidecar and accumulates in fp32 —
compressed bytes ARE the wire bytes, where the previous form ran the codec
as a discrete scatter-gather stage between full-precision tier collectives.
"""

from __future__ import annotations

from ..communication import LINK_ICI, ReduceOp
from ..compression import compressed_scatter_gather_allreduce
from .base import Algorithm, AlgorithmContext


class ByteGradAlgorithm(Algorithm):
    name = "bytegrad"
    supports_overlap = True
    #: the codec pipeline already runs on flat buckets, so the resident
    #: layout feeds it with zero repacking (BENCH_FLAT.json)
    supports_flat_resident = True
    #: measured (BENCH_OVERLAP.json, 8-dev cpu-sim mesh): the overlap
    #: restructure was never clearly faster for the codec pipeline
    #: (0.69-0.95x in early block runs, noise-bound under interleaved
    #: A/B), so ``auto`` keeps bytegrad serialized; opt in with
    #: ``overlap="on"`` (worth re-measuring on a real multi-chip ICI/DCN
    #: mesh, where the quantize sits on the critical comm path)
    overlap_auto = False
    #: non-hierarchical path wire format (the compressed scatter-gather):
    #: the byte-accounting default for ``bucket_tier_bytes``
    wire_codec_flat = "minmax_uint8"
    #: the hierarchical DCN stage can carry an error-feedback residual when
    #: ``BAGUA_COMPRESS_INTER`` escalates the ring to a stateful codec
    #: (onebit_ef / topk); the flat scatter-gather pipeline never does —
    #: it has one wire format (minmax_uint8)
    supports_ef_state = True

    def __init__(self, hierarchical: bool = True, average: bool = True,
                 codec: str = "minmax_uint8"):
        """
        Args:
            hierarchical: Enable hierarchical communication (slice-local
                full-precision reduce, compressed cross-slice ring).
            average: If True average the reduced gradients, else sum.
            codec: Wire codec of the compressed DCN ring hops
                (``minmax_uint8`` — the reference format — or ``int8`` /
                ``fp8_e4m3`` / ``fp8_e5m2``).  The per-tier policy knobs
                (``BAGUA_COMPRESS_INTER``) override it.
        """
        from ..compression.codecs import get_codec

        get_codec(codec)  # fail fast on a typo'd codec name
        self.hierarchical = hierarchical
        self.average = average
        self.codec = codec

    @property
    def wire_codec_dcn(self):
        """The DCN tier's family-default codec (byte accounting + the
        ``auto`` policy resolution ride this)."""
        return self.codec

    def tensors_to_buckets(self, decl_buckets, named_params, world_size):
        from ..bucket import BucketPlan

        # align bucket length to the world size so each rank owns an equal
        # chunk in the scatter-gather (reference bytegrad.py:38-43)
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=world_size
        )

    def reduce_bucket_grad(self, ctx: AlgorithmContext, index: int, flat):
        # the whole codec (compress → alltoall → decompress → chunk-reduce →
        # compress → allgather → decompress) runs per bucket, so under the
        # overlap scheduler it sits inside the overlap window: bucket i's
        # quantize + scatter-gather can proceed while bucket i+1's gradient
        # is still being produced by the backward
        use_hier = (
            self.hierarchical
            and ctx.two_tier()
            and ctx.internode.nranks() > 1
        )
        if use_hier:
            # two-level form, codec on the DCN stage ONLY — compress where
            # bytes are expensive: full-precision slice-local
            # reduce-scatter (ICI is cheap), then the COMPRESSED RING
            # allreduce of the 1/intra shard across slices — every DCN
            # ppermute hop carries the codec payload (quantize-on-send,
            # fp32 accumulate on receive; the shard is re-quantized once
            # for the ring's broadcast phase), so compressed bytes are
            # what actually cross the slow link — then a full-precision
            # slice-local allgather re-replicates.  The shard divides the
            # inter world because buckets are padded to the full world
            # size (tensors_to_buckets above).  The policy knob
            # (BAGUA_COMPRESS_INTER) can override the codec or force the
            # DCN stage back to full precision.
            op = ReduceOp.AVG if self.average else ReduceOp.SUM
            chunk = ctx.tier_reduce_scatter(flat, op)
            chunk = ctx.tier_allreduce(chunk, op, codec=self.codec)
            return ctx.tier_allgather(chunk)
        if ctx.comm.nranks() > 1:
            if ctx.codec_for(LINK_ICI, self.codec) is None:
                # the policy knob forced `off`: full precision even on
                # the family's own flat pipeline — the documented
                # debug-a-divergence escape hatch.  (A forced codec NAME
                # keeps the minmax scatter-gather: that pipeline has one
                # wire format; the ring tiers honor forced names.)
                # bucket_allreduce, not a bare fused psum: the chunk
                # knobs' ring schedule must survive the escape hatch.
                op = ReduceOp.AVG if self.average else ReduceOp.SUM
                return ctx.bucket_allreduce(flat, op, False)
            return compressed_scatter_gather_allreduce(
                ctx.comm, flat, average=self.average
            )
        return flat

    process_grads = Algorithm.process_grads_bucketed
