"""ZeRO-1: optimizer-state sharding over the data-parallel axes.

Additive capability — the reference has no ZeRO/FSDP analog (SURVEY.md §2.3
lists it as absent; its closest relative is the flat-param
``contrib/fused_optimizer.py``).  On TPU this is the natural next step past
plain DP: optimizer state is the largest per-chip memory consumer for Adam
(2× params in f32), and the bucket flat buffers already partition evenly
across ranks (world-size alignment), so the classic ZeRO-1 dance maps to two
XLA collectives per bucket:

    reduce_scatter(grads)  ->  shard-local optimizer update  ->  all_gather(params)

which costs exactly the same bytes on the wire as the allreduce it replaces
(an allreduce IS a reduce-scatter + all-gather — pinned by the compiled-HLO
byte audit in tests/test_hlo_comm_bytes.py), while storing only
``1/world_size`` of the optimizer state per chip.

On pure-dp meshes the params are FLAT-RESIDENT: ``TrainState.params`` holds
the bucket flat buffers across steps and the trainer differentiates the
loss w.r.t. the flats directly — the forward materializes leaf views by
slicing (XLA fuses it) and autodiff's scatter-add IS the gradient flatten,
so the per-step leaf->flat->leaf round trip the leaf layout paid is gone.
Measured on one v5e chip (ResNet50, batch 128, comm a no-op, both families
at the HBM roofline — 909 vs 920 GB/s): the leaf layout trailed plain
allreduce by 7.7%; flat-resident trails by ~2% (2590 vs 2644 img/s, two
runs), the residual being the per-step re-laying of updated flat segments
into conv layouts.  That is the single-chip price of 1/world_size
optimizer memory; on a real dp mesh the collective bytes are identical.
Model-parallel compositions (tp/pp/ep) keep the leaf layout; leaf pytrees
for eval/checkpoint/user code come from ``trainer.unstack_params(state)``.

The wrapped optax transformation must be *elementwise* (adam, adamw, sgd,
rmsprop, ...): the update for element ``i`` may depend only on gradient /
param / state values at ``i``, because each rank updates its own flat chunk
independently.  Global-norm gradient clipping — the one norm-coupled
transform everyone needs — is built in (``clip_global_norm``): the norm of
the *averaged* gradient is assembled with one extra scalar psum over the
already-sharded chunks.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import optax

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext


class ZeroOptimizerAlgorithm(Algorithm):
    """ZeRO stage-1 data parallelism: replicated params, sharded optimizer
    state, reduce-scatter gradient averaging.

    Args:
        optimizer: an elementwise optax ``GradientTransformation``
            (default ``optax.adam(1e-3)``).  Its state is built per flat
            bucket *chunk* — each rank stores only its ``1/world_size``
            slice.
        clip_global_norm: optional max global grad norm.  Computed on the
            averaged gradient (post reduce-scatter) with a scalar psum, so
            every rank applies the identical scale — the distributed analog
            of ``optax.clip_by_global_norm``.
    """

    owns_optimizer = True
    sharded_opt_state = True
    #: flat residency is ZeRO's native pure-dp layout (this is where the
    #: machinery was born — the measured ~7% leaf->flat->leaf round trip,
    #: VERDICT r3 #4); ``flat_resident="off"`` opts back into the leaf
    #: layout, which model-parallel compositions use regardless
    supports_flat_resident = True
    #: overlap contract (flat-resident layout only — the trainer gates on
    #: ``_zero_flat``): the per-bucket reduce-scatter is issued inside the
    #: overlap window and ``optimizer_update`` consumes the pre-reduced
    #: chunks instead of running its own collective
    supports_overlap = True
    #: measured (BENCH_OVERLAP.json, interleaved A/B on the 8-dev cpu-sim
    #: mesh): the overlap restructure was never clearly faster — one
    #: controlled run measured 0.89-0.94x of serialized in every trial
    #: (splitting the reduce-scatter away from the chunk update defeats
    #: XLA:CPU's fusion), the rest were noise-bound — so ``auto`` keeps
    #: ZeRO serialized there; opt in with ``overlap="on"`` (re-measure on
    #: real ICI, where the early reduce-scatter is the point)
    overlap_auto = False

    def __init__(
        self,
        optimizer: Optional[optax.GradientTransformation] = None,
        clip_global_norm: Optional[float] = None,
        hierarchical: bool = False,
        check_elementwise: bool = True,
    ):
        """``hierarchical=True`` (r5): the STAGED layout — optimizer state is
        sharded over the *intra* axis only (replicated across *inter*), and
        the per-bucket dance becomes

            reduce_scatter(grads, intra) -> allreduce(chunk, inter)
            -> shard-local update -> all_gather(params, intra)

        so the inter tier (DCN on multi-pod meshes) carries only
        ``1/intra_size`` of the flat bytes per step — the same wire shape as
        the other families' hierarchical mode — at the cost of storing
        ``1/intra_size`` (not ``1/world``) of the optimizer state per chip.
        On a mesh without the inter/intra tiers it falls back to the flat
        path, like the other families' ``hierarchical`` flag."""
        self.optimizer = optimizer if optimizer is not None else optax.adam(1e-3)
        self.clip_global_norm = clip_global_norm
        self.hierarchical = hierarchical
        if check_elementwise:
            self._check_elementwise()

    def _check_elementwise(self) -> None:
        """Fail loudly at construction when the wrapped transform is not
        elementwise (e.g. ``optax.chain(clip_by_global_norm(...), adam(...))``):
        each rank updates only its own flat chunk, so a norm-coupled update
        would silently train on per-chunk norms.  Probe: stepping a 2-vector
        must equal stepping its two halves independently.  Multiple steps
        with gradients of VARYING norm are required — adam-family updates
        are invariant to a per-element-constant gradient scale (m and sqrt v
        scale together), so a single step cannot expose clipping.  Runs on
        the CPU backend (tiny arrays; keeps TPU compile out of __init__)."""
        try:
            # must be an ADDRESSABLE device: jax.devices("cpu")[0] is
            # process 0's device, and committing the probe to it from any
            # other process crashes that process alone — a divergent-dispatch
            # hang (caught by tests/test_multiprocess_families.py[zero])
            device = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            # CPU backend excluded (e.g. JAX_PLATFORMS=tpu): probe on the
            # default device — two tiny compiles, still worth the guard
            device = jax.local_devices()[0]
        with jax.default_device(device):
            # norms 5, 0.14, 2.2: the clip factor changes per step, and
            # differs between the full vector and each half
            gs = [jnp.asarray([3.0, -4.0]), jnp.asarray([0.1, 0.1]),
                  jnp.asarray([-1.0, 2.0])]
            p_full = jnp.asarray([0.5, -1.5])
            st_full = self.optimizer.init(p_full)
            for g in gs:
                up, st_full = self.optimizer.update(g, st_full, p_full)
                p_full = optax.apply_updates(p_full, up)
            halves = []
            for i in range(2):
                p = jnp.asarray([0.5, -1.5])[i:i + 1]
                st = self.optimizer.init(p)
                for g in gs:
                    up, st = self.optimizer.update(g[i:i + 1], st, p)
                    p = optax.apply_updates(p, up)
                halves.append(p)
            if not jnp.allclose(p_full, jnp.concatenate(halves),
                                rtol=1e-5, atol=1e-7):
                raise ValueError(
                    "ZeroOptimizerAlgorithm requires an ELEMENTWISE optax "
                    "transform (adam/adamw/sgd/rmsprop/...): updating a "
                    "vector and updating its halves independently disagree, "
                    "so the transform couples elements (global-norm "
                    "clipping?).  Use the built-in clip_global_norm= for "
                    "distributed clipping, or pass check_elementwise=False "
                    "if the coupling is intentional."
                )

    def tensors_to_buckets(self, decl_buckets, named_params, world_size):
        from ..bucket import BucketPlan

        # world-size alignment so every bucket splits into equal rank chunks
        # (the same alignment the compressed scatter-gather ops use,
        # reference bytegrad.py:38-43)
        return BucketPlan.from_declaration_buckets(
            decl_buckets, named_params, alignment=world_size
        )

    # ---- chunk helpers ---------------------------------------------------

    def _staged(self, ctx: AlgorithmContext) -> bool:
        """Whether the hierarchical (intra-sharded) layout is active.  Must
        agree with the trainer's spec-side decision
        (``BaguaTrainer._zero_staged``).  The staged collectives span
        exactly inter × intra, so any extra comm axis (e.g. ``sp`` folded
        into the comm world for partial-grad summation) forces the flat
        path — staged rs/allreduce would skip that axis's reduction."""
        return (
            self.hierarchical
            and ctx.internode is not None
            and ctx.intranode is not None
            and ctx.internode is not ctx.intranode
            and ctx.world_size
            == ctx.internode.nranks() * ctx.intranode.nranks()
        )

    def _shard_comm(self, ctx: AlgorithmContext):
        """The axis the optimizer state shards over: intra when staged,
        the full comm world otherwise."""
        return ctx.intranode if self._staged(ctx) else ctx.comm

    def _chunk_size(self, ctx: AlgorithmContext, flat) -> int:
        n = self._shard_comm(ctx).nranks()
        assert flat.shape[0] % n == 0, (
            f"bucket numel {flat.shape[0]} not divisible by shard count {n}"
        )
        return flat.shape[0] // n

    def _my_chunk(self, ctx: AlgorithmContext, flat):
        size = self._chunk_size(ctx, flat)
        start = self._shard_comm(ctx).rank() * size
        return jax.lax.dynamic_slice(flat, (start,), (size,))

    def _avg_scatter(self, ctx: AlgorithmContext, flat):
        """Average ``flat`` over the whole comm world and return this rank's
        owned chunk.  Flat: one reduce_scatter over all comm axes.  Staged:
        reduce_scatter over intra, then allreduce the owned chunk over inter
        — the global average with only ``1/intra`` of the bytes crossing the
        inter tier (avg-of-avgs is exact: intra rows are equal-sized)."""
        if not self._staged(ctx):
            # chunked ring when the overlap scheduler set a chunk size,
            # fused psum_scatter otherwise (identical chunk layout)
            return ctx.bucket_reduce_scatter(flat, ReduceOp.AVG)
        # staged: the per-tier helpers ring-chunk each stage against its
        # own link-class target (ICI for the intra scatter, DCN for the
        # inter allreduce) when the overlap scheduler set them; fused
        # psum_scatter/psum otherwise — jaxpr-identical to the pre-tier
        # construction
        chunk = ctx.tier_reduce_scatter(flat, ReduceOp.AVG)
        return ctx.tier_allreduce(chunk, ReduceOp.AVG)

    # ---- overlap scheduler stages ---------------------------------------

    def reduce_bucket_grad(self, ctx: AlgorithmContext, index: int, flat):
        """One bucket's gradient comm = the averaging reduce-scatter; the
        returned buffer is this rank's owned chunk."""
        return self._avg_scatter(ctx, flat)

    def grads_from_reduced(self, ctx: AlgorithmContext, reduced, grads,
                           algo_state, step):
        """Flat-resident layout only: the pre-reduced chunks ride to
        ``optimizer_update``, which then skips its own reduce-scatter (the
        collective was already issued inside the overlap window)."""
        return {"chunks": tuple(reduced), "local": grads["local"]}, algo_state

    # ---- optimizer contract ---------------------------------------------
    #
    # State protocol (shared with the trainer): ``{"buckets": (optax state
    # per bucket chunk, ...), "local": optax state over the name->array dict
    # of NON-plan leaves}``.  "local" covers tp/pp-sharded leaves (3-D
    # parallelism): each shard owns its slice outright and its gradient
    # arrives already dp-averaged from the trainer, so a shard-local
    # elementwise update is exact — no collective, state sharded like the
    # leaf.  With no model-parallel axes "local" is an empty dict's state.

    def _local_named(self, ctx: AlgorithmContext, tree):
        from ..tensor import leaves_by_name

        plan_names = set(ctx.plan.tensor_names)
        return {
            name: leaf for name, leaf in leaves_by_name(tree).items()
            if name not in plan_names
        }

    def init_optimizer_state_sharded(self, ctx: AlgorithmContext, params):
        """Per-rank optimizer state (runs inside ``shard_map``): one optax
        state per bucket built for that rank's flat chunk, plus the local
        state for non-plan (model-parallel) leaves."""
        flats = ctx.plan.flatten_tree(params)
        return {
            "buckets": tuple(
                self.optimizer.init(self._my_chunk(ctx, f)) for f in flats
            ),
            "local": self.init_optimizer_state_local(
                self._local_named(ctx, params)
            ),
        }

    def init_optimizer_state_local(self, local_named: dict):
        """Axis-free init for the non-plan (tp/pp-sharded) leaves — also
        used by the trainer via ``eval_shape`` to derive sharding specs."""
        return self.optimizer.init(local_named)

    def init_optimizer_state(self, params):  # pragma: no cover - guard
        raise NotImplementedError(
            "ZeroOptimizerAlgorithm state is sharded; the trainer must call "
            "init_optimizer_state_sharded inside shard_map"
        )

    def optimizer_update(self, ctx: AlgorithmContext, params, grads, opt_state,
                         algo_state, step):
        if isinstance(params, dict) and "flats" in params:
            # flat-resident layout (pure-dp meshes): the trainer already
            # differentiates w.r.t. the bucket flats, so there is no
            # leaf<->flat round trip here at all — reduce-scatter the flat
            # grads, update the owned chunk, allgather back to flat
            return self._optimizer_update_flat(
                ctx, params, grads, opt_state, algo_state, step
            )
        if self._staged(ctx):
            # backend gates this earlier with its own actionable error; the
            # guard here keeps direct algorithm users honest too
            raise NotImplementedError(
                "hierarchical ZeRO supports the flat-resident (pure-dp) "
                "layout only; drop hierarchical=True when composing with "
                "tp/pp/expert axes"
            )
        gflats = ctx.plan.flatten_tree(grads)
        pflats = ctx.plan.flatten_tree(params)
        # grad averaging and sharding in one collective per bucket
        gchunks = [ctx.comm.reduce_scatter(gf, ReduceOp.AVG) for gf in gflats]
        local_g = self._local_named(ctx, grads)

        if self.clip_global_norm is not None:
            # ||avg grad||² = psum of each rank's chunk contributions
            # (bucket padding is zeros and does not perturb the norm).
            # Local (model-parallel) leaves are excluded: their slices live
            # on tp/pp/ep axes outside this communicator, so a correct
            # global norm would need a second psum over those axes — ZeRO
            # with clipping is supported for pure-dp/sp meshes only.
            if local_g:
                raise NotImplementedError(
                    "clip_global_norm with model-parallel (tp/pp/expert) "
                    "leaves is not supported"
                )
            ssq = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gchunks
            )
            gnorm = jnp.sqrt(ctx.comm.allreduce(ssq, ReduceOp.SUM))
            scale = jnp.minimum(1.0, self.clip_global_norm / (gnorm + 1e-12))
            gchunks = [(g * scale.astype(g.dtype)) for g in gchunks]

        new_pflats, new_states = [], []
        for gchunk, pf, st in zip(gchunks, pflats, opt_state["buckets"]):
            pchunk = self._my_chunk(ctx, pf)
            updates, st = self.optimizer.update(gchunk, st, pchunk)
            pchunk = optax.apply_updates(pchunk, updates)
            # re-replicate the updated params (rank chunks in rank order)
            new_pflats.append(ctx.comm.allgather(pchunk, tiled=True))
            new_states.append(st)
        named = ctx.plan.unflatten_to_named(new_pflats)

        local_state = opt_state["local"]
        if local_g:
            local_p = self._local_named(ctx, params)
            updates, local_state = self.optimizer.update(
                local_g, local_state, local_p
            )
            named.update(optax.apply_updates(local_p, updates))

        from ..tensor import tree_from_named

        new_params = tree_from_named(params, named)
        return new_params, {"buckets": tuple(new_states),
                            "local": local_state}, algo_state

    def _optimizer_update_flat(self, ctx: AlgorithmContext, params, grads,
                               opt_state, algo_state, step):
        shard = self._shard_comm(ctx)
        if "chunks" in grads:
            # overlap path: the reduce-scatter already ran per bucket
            # inside the overlap window (grads_from_reduced)
            gchunks = list(grads["chunks"])
        else:
            gchunks = [self._avg_scatter(ctx, gf) for gf in grads["flats"]]
        if self.clip_global_norm is not None:
            # chunks across the SHARD axis tile the whole flat exactly once
            # (staged: chunks are replicated over inter, so summing over
            # intra alone is the full norm — a comm-world psum would count
            # every element inter_size times)
            ssq = sum(
                jnp.sum(jnp.square(g.astype(jnp.float32))) for g in gchunks
            )
            gnorm = jnp.sqrt(shard.allreduce(ssq, ReduceOp.SUM))
            scale = jnp.minimum(1.0, self.clip_global_norm / (gnorm + 1e-12))
            gchunks = [(g * scale.astype(g.dtype)) for g in gchunks]

        new_flats, new_states = [], []
        for gchunk, pf, st in zip(gchunks, params["flats"],
                                  opt_state["buckets"]):
            pchunk = self._my_chunk(ctx, pf)
            updates, st = self.optimizer.update(gchunk, st, pchunk)
            pchunk = optax.apply_updates(pchunk, updates)
            # re-replicate (rank chunks in rank order over the shard axis;
            # staged: every inter row gathers the identical chunks, so the
            # result stays replicated across inter with no inter traffic).
            # Both gathers are chunk-aware, so the ring pair stays
            # layout-symmetric when overlap chunking is on (the staged one
            # against the ICI tier's target).
            new_flats.append(
                ctx.bucket_allgather(pchunk) if shard is ctx.comm
                else ctx.tier_allgather(pchunk)
            )
            new_states.append(st)
        new_params = {"flats": tuple(new_flats), "local": params["local"]}
        return new_params, {"buckets": tuple(new_states),
                            "local": opt_state["local"]}, algo_state
