"""Decentralized SGD algorithms (gossip weight averaging).

Counterparts of /root/reference/bagua/torch_api/algorithms/decentralized.py and
the Rust comm ops:

- :class:`DecentralizedAlgorithm` — full-precision weight averaging, peer
  modes ``all`` (allreduce-avg of weights) and ``shift_one`` (pairwise
  exchange with a step-rotating partner, peer formula from
  comm_ops/decentralized_full_precision_synchronous.rs:79-83), executed as
  ``lax.pmean`` / ``lax.ppermute`` over the mesh.
- :class:`LowPrecisionDecentralizedAlgorithm` — ring compressed-difference
  exchange (comm_ops/decentralized_low_precision_synchronous.rs:45-151):
  each rank keeps replicas of its own and both neighbors' weights, sends the
  MinMaxUInt8-compressed difference ``x + L/3 + R/3 - 5w/3`` both ways, and
  applies the quantized update — communication happens after the optimizer
  step (reference decentralized.py:142-152).

Timing note: the reference starts weight communication in the forward-pre
hook (weights as of step start) and copies the averaged peer weight back in
the post-backward hook, i.e. *before* the optimizer step.  Functionally the
weights are unchanged between those two points, so here the full-precision
average runs in ``process_pre_step`` on the same values — identical math, and
XLA still overlaps it with backward because the collective's inputs are ready
before the gradients are.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from ..communication import BaguaCommunicator, ReduceOp
from ..compression import compress_chunked, decompress_chunked
from .base import Algorithm, AlgorithmContext


def shift_one_peer(rank: int, nranks: int, step: int) -> int:
    """Partner formula from decentralized_full_precision_synchronous.rs:79-83.

    Symmetric pairing: ranks in the lower half pair with a step-rotating rank
    in the upper half; requires an even world size.
    """
    half = nranks // 2
    if rank < half:
        return (step + rank) % ((nranks + 1) // 2) + half
    return (rank - half - step) % half


class DecentralizedAlgorithm(Algorithm):
    replicated_params = False
    #: the gossip exchange already runs on flat buckets; under the
    #: resident layout the weights ARE those buckets, so the exchange (and
    #: the tracked peer replicas) needs no per-step flatten at all
    supports_flat_resident = True

    def __init__(
        self,
        hierarchical: bool = True,
        peer_selection_mode: str = "all",
        communication_interval: int = 1,
        track_peer_weights: bool = False,
    ):
        """
        Args:
            hierarchical: Enable hierarchical communication (intra-node
                average first, gossip across nodes).
            peer_selection_mode: ``"all"`` (average everyone) or
                ``"shift_one"`` (rotating pairwise exchange).
            communication_interval: Iterations between communications
                (reference decentralized.py:34-36).
            track_peer_weights: keep the post-communication weights in the
                algorithm state (the analog of the reference's ``peer_weight``
                bucket tensor, bucket.py:197-263) — lets tests assert the
                exact peer-equality invariant at the communication point.
        """
        assert peer_selection_mode in ("all", "shift_one"), peer_selection_mode
        self.hierarchical = hierarchical
        self.peer_selection_mode = peer_selection_mode
        self.communication_interval = communication_interval
        self.track_peer_weights = track_peer_weights

    def init_state(self, ctx: AlgorithmContext, params) -> Any:
        if not self.track_peer_weights:
            return None
        return {"peer_weights": ctx.plan.flatten_tree(params)}

    def _exchange(self, ctx: AlgorithmContext, flat, step):
        use_hier = (
            self.hierarchical
            and ctx.internode is not None
            and ctx.intranode is not None
            and ctx.intranode.nranks() > 1
            and ctx.internode is not ctx.intranode
        )
        gossip_comm = ctx.internode if use_hier else ctx.comm
        if use_hier:
            flat = ctx.intranode.allreduce(flat, ReduceOp.AVG)
        n = gossip_comm.nranks()
        if n <= 1:
            return flat
        if self.peer_selection_mode == "all":
            return gossip_comm.allreduce(flat, ReduceOp.AVG)
        assert n % 2 == 0, (
            "shift_one requires an even number of ranks, got %d" % n
        )
        comm_idx = step // self.communication_interval
        peer_val = gossip_comm.exchange_with_peer(flat, shift_one_peer, comm_idx)
        return (flat + peer_val) * 0.5

    def process_pre_step(self, ctx: AlgorithmContext, params, algo_state, step):
        flats = ctx.bucket_flats(params)

        def do_comm(fs):
            return [self._exchange(ctx, f, step) for f in fs]

        if self.communication_interval > 1:
            # non-communication steps must KEEP the previously tracked
            # peer weights, not overwrite them with local weights
            prev_peer = (
                algo_state["peer_weights"] if self.track_peer_weights else flats
            )

            def comm_branch(op):
                fs, _ = op
                out = do_comm(fs)
                return out, out

            def skip_branch(op):
                fs, prev = op
                return fs, prev

            flats, peer = lax.cond(
                step % self.communication_interval == 0,
                comm_branch, skip_branch, (flats, prev_peer),
            )
        else:
            flats = do_comm(flats)
            peer = flats
        if self.track_peer_weights:
            algo_state = {"peer_weights": peer}
        return ctx.from_bucket_flats(flats, params), algo_state

    def relayout_algo_state(self, old_plan, new_plan, algo_state):
        if algo_state is None:
            return None
        from ..bucket import relayout_flats

        return {"peer_weights": relayout_flats(
            old_plan, new_plan, algo_state["peer_weights"]
        )}


class LowPrecisionDecentralizedAlgorithm(Algorithm):
    replicated_params = False
    #: the compressed ring exchange and its three weight replicas are
    #: flat-bucket-shaped already; the resident layout feeds them directly
    supports_flat_resident = True

    def __init__(self, hierarchical: bool = True, communication_interval: int = 1):
        """
        Args:
            hierarchical: Enable hierarchical communication.
            communication_interval: Iterations between communications.
        """
        self.hierarchical = hierarchical
        self.communication_interval = communication_interval

    def init_state(self, ctx: AlgorithmContext, params) -> Any:
        # three weight replicas per bucket: left peer, right peer, self
        # (reference decentralized.py:154-165 _init_states)
        flats = ctx.plan.flatten_tree(params)
        return {
            "left": [jnp.array(f) for f in flats],
            "right": [jnp.array(f) for f in flats],
            "self": [jnp.array(f) for f in flats],
        }

    def _ring_step(self, ctx: AlgorithmContext, x, left, right, mine):
        """One compressed ring exchange for one bucket
        (decentralized_low_precision_synchronous.rs:45-151)."""
        use_hier = (
            self.hierarchical
            and ctx.internode is not None
            and ctx.intranode is not None
            and ctx.intranode.nranks() > 1
            and ctx.internode is not ctx.intranode
        )
        ring_comm = ctx.internode if use_hier else ctx.comm
        if use_hier:
            x = ctx.intranode.allreduce(x, ReduceOp.AVG)
        n = ring_comm.nranks()
        if n <= 1:
            return x, left, right, mine

        diff = x + left / 3.0 + right / 3.0 - (5.0 / 3.0) * mine
        mn, mx, payload = compress_chunked(diff, 1)

        # ring neighbors: value sent left arrives from the right, etc.
        right_shift = [(r, (r + 1) % n) for r in range(n)]   # recv from left
        left_shift = [(r, (r - 1) % n) for r in range(n)]    # recv from right
        from_left = (
            ring_comm.ppermute(mn, right_shift),
            ring_comm.ppermute(mx, right_shift),
            ring_comm.ppermute(payload, right_shift),
        )
        from_right = (
            ring_comm.ppermute(mn, left_shift),
            ring_comm.ppermute(mx, left_shift),
            ring_comm.ppermute(payload, left_shift),
        )

        left = left + decompress_chunked(*from_left)
        right = right + decompress_chunked(*from_right)
        # apply own quantized diff: x' = w + Q(diff); w' = x'
        x_new = mine + decompress_chunked(mn, mx, payload)
        return x_new, left, right, x_new

    def process_post_step(self, ctx: AlgorithmContext, params, algo_state, step):
        flats = ctx.bucket_flats(params)

        def do_comm(operand):
            fs, st = operand
            new_fs, nl, nr, nw = [], [], [], []
            for f, l, r, w in zip(fs, st["left"], st["right"], st["self"]):
                f2, l2, r2, w2 = self._ring_step(ctx, f, l, r, w)
                new_fs.append(f2)
                nl.append(l2)
                nr.append(r2)
                nw.append(w2)
            return new_fs, {"left": nl, "right": nr, "self": nw}

        if self.communication_interval > 1:
            flats, algo_state = lax.cond(
                step % self.communication_interval == 0,
                do_comm,
                lambda op: op,
                (flats, algo_state),
            )
        else:
            flats, algo_state = do_comm((flats, algo_state))
        return ctx.from_bucket_flats(flats, params), algo_state

    def relayout_algo_state(self, old_plan, new_plan, algo_state):
        if algo_state is None:
            return None
        from ..bucket import relayout_flats

        return {
            k: relayout_flats(old_plan, new_plan, algo_state[k])
            for k in ("left", "right", "self")
        }
