from .async_model_average import AsyncModelAverageAlgorithm  # noqa: F401
from .base import Algorithm, AlgorithmContext  # noqa: F401
from .bytegrad import ByteGradAlgorithm  # noqa: F401
from .decentralized import (  # noqa: F401
    DecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    shift_one_peer,
)
from .gradient_allreduce import GradientAllReduceAlgorithm  # noqa: F401
from .q_adam import QAdamAlgorithm, QAdamOptState  # noqa: F401
from .zero import ZeroOptimizerAlgorithm  # noqa: F401

#: Families the autotuner may switch between at a check-in (stateless,
#: replicated, trainer-owned-optimizer algorithms only — swapping them never
#: invalidates TrainState).  Gossip/owner families change the state layout
#: and must be chosen up front.
SWITCHABLE_ALGORITHMS = {
    "gradient_allreduce": lambda hierarchical: GradientAllReduceAlgorithm(
        hierarchical=hierarchical
    ),
    "bytegrad": lambda hierarchical: ByteGradAlgorithm(hierarchical=hierarchical),
}
