from .async_model_average import AsyncModelAverageAlgorithm  # noqa: F401
from .base import Algorithm, AlgorithmContext  # noqa: F401
from .bytegrad import ByteGradAlgorithm  # noqa: F401
from .decentralized import (  # noqa: F401
    DecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    shift_one_peer,
)
from .gradient_allreduce import GradientAllReduceAlgorithm  # noqa: F401
from .q_adam import QAdamAlgorithm, QAdamOptState  # noqa: F401
from .zero import ZeroOptimizerAlgorithm  # noqa: F401

#: Families the autotuner (and the fleet autopilot's escalation ladder,
#: through the same recommendation path) may switch between at a check-in.
#: Stateless replicated trainer-owned-optimizer families
#: (gradient_allreduce, bytegrad) swap freely; QAdam is switchable through
#: the trainer's state-migration adapter (its momenta are param-shaped, so
#: they can be adopted from an adam-family optax state — or start from
#: zeros — and its warmup contract is re-anchored at the switch step; see
#: ``BaguaTrainer._prepare_state_migration``).  Async model averaging
#: crosses the replicated<->stacked state boundary and rides
#: ``BaguaTrainer._prepare_replication_migration`` (replicated state is
#: stacked per rank on the way in; a synchronous catch-up average
#: collapses the rows on the way out) — but only from families that
#: neither own the optimizer nor keep flat-resident state, on pure-dp
#: meshes.  Sharded-opt-state families (ZeRO) change the TrainState
#: layout irreversibly and must be chosen up front.
SWITCHABLE_ALGORITHMS = {
    "gradient_allreduce": lambda hierarchical: GradientAllReduceAlgorithm(
        hierarchical=hierarchical
    ),
    "bytegrad": lambda hierarchical: ByteGradAlgorithm(hierarchical=hierarchical),
    # short warmup: the tuner samples this config for ~100 steps, so the
    # compressed phase must begin well inside the scoring window
    "qadam": lambda hierarchical: QAdamAlgorithm(
        warmup_steps=20, hierarchical=hierarchical
    ),
    # mid-run entry needs no warmup (the run is already warmed up) and no
    # hierarchical flag (averaging rounds are whole-model allreduces);
    # period calibration starts fresh at the switch step
    "async": lambda hierarchical: AsyncModelAverageAlgorithm(
        warmup_steps=0
    ),
}
