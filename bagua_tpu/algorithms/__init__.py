from .base import Algorithm, AlgorithmContext  # noqa: F401
from .gradient_allreduce import GradientAllReduceAlgorithm  # noqa: F401
