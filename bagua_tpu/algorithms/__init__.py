from .async_model_average import AsyncModelAverageAlgorithm  # noqa: F401
from .base import Algorithm, AlgorithmContext  # noqa: F401
from .bytegrad import ByteGradAlgorithm  # noqa: F401
from .decentralized import (  # noqa: F401
    DecentralizedAlgorithm,
    LowPrecisionDecentralizedAlgorithm,
    shift_one_peer,
)
from .gradient_allreduce import GradientAllReduceAlgorithm  # noqa: F401
from .q_adam import QAdamAlgorithm, QAdamOptState  # noqa: F401
