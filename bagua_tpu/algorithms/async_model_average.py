"""Asynchronous model averaging.

Counterpart of /root/reference/bagua/torch_api/algorithms/async_model_average.py
(:156-233) + comm_ops/decentralized_full_precision_asynchronous.rs: a
background loop continuously allreduce-averages the weights while compute
proceeds, with a lock so weights are swapped only between steps, and
``abort``/``resume`` control.

TPU-native mechanism: the reference needs a worker thread + CUDA stream +
weight mutex because torch executes eagerly.  JAX's async dispatch already
gives us a "background stream": the averaging is its own tiny jitted
collective, dispatched without blocking the Python loop; train steps keep
executing on stale local weights while it's in flight (same staleness
semantics as the reference), and the result is swapped into the train state
between steps — the functional equivalent of the reference's weight lock held
during forward/backward (:156-168).  ``warmup_steps`` of synchronous gradient
allreduce match the reference (:60, :125-131).

Multi-process correctness: under XLA every process driving a shared mesh must
dispatch the *same* global programs in the *same* order, so the reference's
"launch a round whenever the local wall clock says so" gate
(async_model_average.py:170-177) cannot be ported as-is — two hosts with
skewed clocks would interleave the averaging collective differently against
train steps and deadlock.  Instead the launch schedule is **deterministic in
the step counter**: after warmup, a short calibration window measures the
local step time, all processes agree on the slowest host's value (the
reference's gloo side-channel, :59-60, here a tiny cross-process allgather),
and rounds launch every ``k``-th step with ``k`` derived from
``sync_interval_ms`` and the agreed step time.  ``abort``/``resume`` are
likewise *negotiated*: a request only takes effect at the next scheduled
boundary, simultaneously on every process (reference RESUME/ABORT
negotiation each background round, :170-233).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext

logger = logging.getLogger(__name__)

_RUNNING = 0
_ABORTED = 1

# per-boundary control intents (edge-triggered: consumed at negotiation, so
# a later resume() from a DIFFERENT rank than the aborter still takes effect)
_REQ_NONE = 0
_REQ_RESUME = 1
_REQ_ABORT = 2  # highest: abort wins when both are requested the same round


def _agree_max(value: float, watchdog=None, label: str = "async-negotiate") -> float:
    """All-process max of a host scalar (single-process: identity).

    The cross-process control channel — plays the role of the reference's
    gloo process group used for RESUME/ABORT negotiation
    (async_model_average.py:59-60).  Every process must call this at the
    same step boundary (the schedule guarantees that).  The blocking gather
    runs inside a watchdog-watched section when one is supplied: a peer
    dying between rounds would otherwise hang survivors here with no active
    watched section to trip hang detection.
    """
    if jax.process_count() == 1:
        return float(value)
    from contextlib import nullcontext

    from jax.experimental import multihost_utils

    guard = watchdog.watch(label) if watchdog is not None else nullcontext()
    with guard:
        gathered = multihost_utils.process_allgather(
            np.asarray(value, dtype=np.float64)
        )
    return float(np.max(gathered))


class AsyncModelAverageAlgorithm(Algorithm):
    replicated_params = False

    def __init__(
        self,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
        calibration_steps: int = 4,
        period_steps: Optional[int] = None,
        recalibrate_rounds: Optional[int] = 64,
    ):
        """
        Args:
            peer_selection_mode: Only ``"all"`` is supported (as in the
                reference async op).
            sync_interval_ms: Target milliseconds between averaging rounds
                (reference sync_interval_ms).  Converted to a step period at
                calibration; ``0`` means every step.
            warmup_steps: Initial steps of synchronous gradient allreduce
                before going asynchronous (reference :60).
            calibration_steps: Steps used to measure the (slowest) host's
                step time before the first round launches.
            period_steps: Pin the averaging period to an exact step count and
                skip wall-clock calibration entirely.  Use when the cadence
                must be machine-load-independent (e.g. convergence gates);
                ``sync_interval_ms`` is ignored when set.
            recalibrate_rounds: Re-run the fenced calibration after this many
                averaging rounds so the agreed period tracks sustained step-
                time changes (phase recompiles, rebucketing, input-dependent
                slowdowns).  ``None`` disables; ignored with ``period_steps``.
        """
        assert peer_selection_mode == "all"
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps
        self.calibration_steps = max(1, calibration_steps)
        self.period_steps = period_steps
        self.recalibrate_rounds = (
            None if recalibrate_rounds is None else max(1, recalibrate_rounds)
        )
        self._request = _REQ_NONE    # this rank's pending abort()/resume()
        self._status = _RUNNING      # negotiated, changes only at boundaries
        self._pending: Optional[Any] = None
        self._avg_fn = None
        self._period: Optional[int] = None   # agreed steps between rounds
        self._anchor: Optional[int] = None   # step the schedule starts from
        self._calib_t0: Optional[float] = None
        self._calib_start: Optional[int] = None  # step the window opened at
        self._calib_skip = 1         # steps to skip before opening a window
        self._rounds = 0             # rounds since the period was agreed
        self._lock = threading.Lock()
        # _request has its own tiny lock so abort()/resume() callers never
        # block behind the boundary's cross-process gather (held under _lock)
        self._req_lock = threading.Lock()

    # ---- traced stages ---------------------------------------------------

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        # warmup: plain synchronous allreduce of gradients (reference
        # :125-131 registers a centralized op during warmup)
        if self.warmup_steps > 0:
            flats = ctx.plan.flatten_tree(grads)

            def sync(fs):
                return [ctx.comm.allreduce(f, ReduceOp.AVG) for f in fs]

            flats = jax.lax.cond(step < self.warmup_steps, sync, lambda fs: fs, flats)
            grads = ctx.plan.unflatten_tree(flats, grads)
        return grads, algo_state

    # ---- host-side async loop -------------------------------------------

    def _ensure_avg_fn(self, trainer):
        if self._avg_fn is not None:
            return
        mesh = trainer.mesh
        comm = trainer._comm
        spec = P(comm.axis_name if len(comm.axes) == 1 else comm.axes)

        def avg(params_stacked):
            p = jax.tree.map(lambda x: x[0], params_stacked)
            p = jax.tree.map(lambda x: comm.allreduce(x, ReduceOp.AVG), p)
            return jax.tree.map(lambda x: x[None], p)

        from ..compat import shard_map

        self._avg_fn = jax.jit(
            shard_map(avg, mesh=mesh, in_specs=spec, out_specs=spec,
                      check_vma=False)
        )
        # apply the averaging as a DELTA onto the current weights, exactly the
        # reference kernel's `x += reduced/n - copy` under the weight lock
        # (decentralized_full_precision_asynchronous.rs:121-126): local
        # progress made while the collective was in flight is preserved.
        self._combine_fn = jax.jit(
            lambda cur, avg_, snap: jax.tree.map(
                lambda c, a, s: c + a - s, cur, avg_, snap
            )
        )
        self._snap_fn = jax.jit(lambda t: jax.tree.map(jnp.copy, t))

    def _warm_compiles(self, trainer, params) -> None:
        """Build + compile the aux jits off the steady-state window (a cache
        hit later): at a boundary they would land inside the user's training
        loop — several seconds of remote compile on tunneled devices.

        Done-once per param avals: ``.lower().compile()`` bypasses the jit
        cache and re-lowers every call, so without the guard each periodic
        recalibration (``recalibrate_rounds``) re-paid three compiles on
        unchanged shapes (ADVICE.md)."""
        key = tuple(
            (tuple(jnp.shape(x)), str(jnp.asarray(x).dtype))
            for x in jax.tree.leaves(params)
        )
        if getattr(self, "_warmed_key", None) == key:
            return
        self._ensure_avg_fn(trainer)
        self._snap_fn.lower(params).compile()
        self._avg_fn.lower(params).compile()
        self._combine_fn.lower(params, params, params).compile()
        self._warmed_key = key

    def _apply_pending(self, state, watchdog=None, block=False):
        """Apply the in-flight round to ``state`` (caller holds the lock).

        Deterministic: every process launched the identical round at the
        identical step, so every process applies it at the identical step.
        The scheduled path does NOT wait for completion — the jitted
        combine consumes ``avg_result`` through a device-side data
        dependency, so XLA keeps train steps and the averaging collective
        overlapped (host-blocking here was measured to cost 5x throughput
        on tunneled transports).  ``block=True`` (barrier/final drain)
        additionally fences, watchdog-guarded: a peer dying mid-collective
        would otherwise hang survivors with no watched section active."""
        avg_result, snapshot = self._pending
        if block:
            from contextlib import nullcontext

            guard = (
                watchdog.watch("async-drain") if watchdog is not None
                else nullcontext()
            )
            with guard:
                jax.block_until_ready(avg_result)
        state = state._replace(
            params=self._combine_fn(state.params, avg_result, snapshot)
        )
        self._pending = None
        return state

    def _calibrate(self, trainer, state, step: int, watchdog=None) -> None:
        """Agree a launch period from the slowest host's measured step time
        (replaces the reference's per-host wall-clock gate, :170-177).

        Both window edges are FENCED with a scalar readback of the step
        counter: the host dispatch loop runs far ahead of the device, so an
        unfenced wall-clock window measures dispatch cadence, not step time
        (observed to mis-calibrate the period by 5x either way).  The
        averaging/combine/snapshot jits are also compiled HERE — at the
        first boundary they would land inside the user's steady-state
        window (several seconds of remote compile on tunneled devices).

        Restartable: periodic re-calibration (``recalibrate_rounds``) resets
        the window state and re-enters here, so a sustained step-time change
        (recompile, rebucketing) re-derives the period deterministically on
        all processes."""
        if self._calib_skip > 0:
            # skip step(s) right after warmup / a recalibration trigger:
            # they may include trace/compile time
            self._calib_skip -= 1
            return
        if self._calib_start is None:
            self._warm_compiles(trainer, state.params)
            np.asarray(state.step)  # fence: start from a drained pipeline
            self._calib_t0 = time.monotonic()
            self._calib_start = step
        elif step >= self._calib_start + self.calibration_steps:
            np.asarray(state.step)  # fence: include the full device work
            window = step - self._calib_start
            local_dt = (time.monotonic() - self._calib_t0) / window
            agreed_dt = _agree_max(local_dt, watchdog, "async-calibrate")
            self._period = max(
                1, int(round(self.sync_interval_ms / (agreed_dt * 1000.0)))
            )
            self._anchor = step
            self._rounds = 0
            logger.info(
                "async model average: agreed step time %.4fs (local %.4fs) "
                "-> averaging every %d step(s)",
                agreed_dt, local_dt, self._period,
            )

    def host_pre_step(self, trainer, state):
        """Between-steps swap point (the reference's weight lock boundary)."""
        from ..communication import is_aborted

        if is_aborted():
            # the global abort flag (watchdog or user) stops the averaging
            # control loop exactly like a local abort() call — no new
            # rounds are launched, pending results are dropped; this process
            # is about to exit for gang restart, so cross-rank agreement is
            # moot here
            with self._lock:
                self._pending = None
            return state
        step = trainer._step_counter
        if step <= self.warmup_steps:
            return state
        if trainer._comm.nranks() == 1:
            # the averaging collective is an identity on a 1-rank comm world:
            # skip snapshot/avg/combine entirely (the reference's async CI
            # floor is the HIGHEST of all families — async must never cost;
            # round 4 measured ~10% single-chip overhead from these hops)
            return state
        watchdog = getattr(trainer, "_watchdog", None)
        with self._lock:
            if self._period is None:
                if self.period_steps is not None:
                    # pinned cadence: no wall-clock dependence at all
                    self._warm_compiles(trainer, state.params)
                    self._period = max(1, int(self.period_steps))
                    self._anchor = step
                    self._rounds = 0
                else:
                    self._calibrate(trainer, state, step, watchdog)
                return state
            if (step - self._anchor) % self._period != 0:
                return state
            # ---- scheduled boundary: negotiate, drain, launch ------------
            # every process reaches this branch at the same step, so the
            # control allgather and the collectives below line up globally.
            # Requests are edge-triggered: the atomic read-then-clear under
            # _req_lock means an abort()/resume() issued from another thread
            # while the gather below is in flight stays pending for the next
            # boundary instead of being wiped.
            with self._req_lock:
                my_req, self._request = self._request, _REQ_NONE
            req = _agree_max(float(my_req), watchdog)
            if req >= _REQ_ABORT:
                new_status = _ABORTED
            elif req >= _REQ_RESUME:
                new_status = _RUNNING
            else:
                new_status = self._status
            if new_status != self._status:
                logger.info(
                    "async model average: negotiated %s at step %d",
                    "ABORT" if new_status == _ABORTED else "RESUME", step,
                )
            self._status = new_status
            if self._pending is not None:
                # the previous round was launched by all processes; drain it
                # deterministically whether we stay running or just aborted
                state = self._apply_pending(state, watchdog)
            if self._status != _RUNNING:
                return state
            # ---- RUNNING-only sequence: count the round, maybe
            # recalibrate, else launch.  Aborted windows run none of this —
            # recalibration firing there would repeatedly drain the
            # pipeline and stall a pending resume behind a fresh
            # calibration window.
            self._rounds += 1
            if (
                self.period_steps is None
                and self.recalibrate_rounds is not None
                and self._rounds >= self.recalibrate_rounds
            ):
                # periodic re-calibration: reset the window state machine so
                # the period re-derives from CURRENT step time.  Step-count
                # driven, hence simultaneous on every process.
                self._period = None
                self._calib_start = None
                self._calib_skip = 1
                logger.info(
                    "async model average: recalibrating period at step %d "
                    "after %d rounds", step, self._rounds,
                )
                return state
            self._ensure_avg_fn(trainer)
            # snapshot = explicit copy (the reference op copies weights on
            # the torch stream first, rs:50-60): the train step donates
            # state.params, so the retained snapshot needs its own buffers
            snapshot = self._snap_fn(state.params)
            # dispatch is async: train steps keep running while the
            # averaging collective is in flight
            self._pending = (self._avg_fn(snapshot), snapshot)
        return state

    # ---- control (reference :203-233) -----------------------------------

    def abort(self):
        """Request a stop of background averaging (e.g. before evaluation).

        Takes effect at the next scheduled boundary on ALL processes
        simultaneously (the reference's negotiated ABORT, :203-218); may be
        called from any single rank — and cleared by a ``resume()`` from any
        rank, not just the one that aborted."""
        with self._req_lock:
            self._request = _REQ_ABORT
        logger.info("async model average abort requested")

    def resume(self):
        """Request that background averaging resumes (negotiated RESUME)."""
        with self._req_lock:
            self._request = _REQ_RESUME
        logger.info("async model average resume requested")

    def barrier(self, trainer, state):
        """Drain any in-flight averaging and apply it (the reference's
        post-abort synchronization).  Collective: call on every process."""
        with self._lock:
            if self._pending is not None:
                state = self._apply_pending(
                    state, getattr(trainer, "_watchdog", None), block=True
                )
        return state
