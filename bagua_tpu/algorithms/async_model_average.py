"""Asynchronous model averaging.

Counterpart of /root/reference/bagua/torch_api/algorithms/async_model_average.py
(:156-233) + comm_ops/decentralized_full_precision_asynchronous.rs: a
background loop continuously allreduce-averages the weights while compute
proceeds, with a lock so weights are swapped only between steps, and
``abort``/``resume`` control.

TPU-native mechanism: the reference needs a worker thread + CUDA stream +
weight mutex because torch executes eagerly.  JAX's async dispatch already
gives us a "background stream": the averaging is its own tiny jitted
collective, dispatched without blocking the Python loop; train steps keep
executing on stale local weights while it's in flight (same staleness
semantics as the reference), and the result is swapped into the train state
between steps — the functional equivalent of the reference's weight lock held
during forward/backward (:156-168).  ``warmup_steps`` of synchronous gradient
allreduce match the reference (:60, :125-131).

Multi-process correctness: under XLA every process driving a shared mesh must
dispatch the *same* global programs in the *same* order, so the reference's
"launch a round whenever the local wall clock says so" gate
(async_model_average.py:170-177) cannot be ported as-is — two hosts with
skewed clocks would interleave the averaging collective differently against
train steps and deadlock.  Instead the launch schedule is **deterministic in
the step counter**: after warmup, a short calibration window measures the
local step time, all processes agree on the slowest host's value (the
reference's gloo side-channel, :59-60, here a tiny cross-process allgather),
and rounds launch every ``k``-th step with ``k`` derived from
``sync_interval_ms`` and the agreed step time.  ``abort``/``resume`` are
likewise *negotiated*: a request only takes effect at the next scheduled
boundary, simultaneously on every process (reference RESUME/ABORT
negotiation each background round, :170-233).

Bounded staleness (the straggler/partition story): *launching* a round is
global (the averaging collective needs every rank), but *applying* its delta
is a purely local elementwise combine — so a rank may locally sit a round
out without breaking the SPMD dispatch schedule.  Two things make it do so:
a gradient-guard rewind landed while the round was in flight (applying the
delta on top of a rewound state would smuggle the skipped step's progress
back in), or an armed ``async.partition`` fault dropped it from the round.
Each rank's applied-round counter rides the negotiation gather; when the
worst rank's lag reaches ``max_staleness_rounds``, every process
deterministically agrees to a **synchronous catch-up average**: block on a
full model average and assign it, leaving every rank's replica bit-identical
and the counters equalized.  Slow or flaky ranks therefore degrade round
freshness instead of gating the step — and persistent offenders surface to
the elastic coordinator through the heartbeat health payload
(``async/missed_boundaries``; see docs/robustness.md).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from .. import env
from ..communication import ReduceOp
from ..faults import inject as _inject
from ..obs.spans import trace_span
from ..telemetry import counters
from .base import Algorithm, AlgorithmContext

logger = logging.getLogger(__name__)

_RUNNING = 0
_ABORTED = 1

# per-boundary control intents (edge-triggered: consumed at negotiation, so
# a later resume() from a DIFFERENT rank than the aborter still takes effect)
_REQ_NONE = 0
_REQ_RESUME = 1
_REQ_ABORT = 2  # highest: abort wins when both are requested the same round


def _negotiate(payload, watchdog=None, label: str = "async-negotiate"):
    """All-process gather of a small per-process control vector; returns a
    ``(process_count, len(payload))`` float64 array (single-process: the
    payload itself as one row).

    The cross-process control channel — plays the role of the reference's
    gloo process group used for RESUME/ABORT negotiation
    (async_model_average.py:59-60), generalized from a scalar max to a full
    per-rank gather so the boundary can also exchange applied-round
    counters for bounded-staleness tracking.  Every process must call this
    at the same step boundary (the schedule guarantees that).  The blocking
    gather runs inside a watchdog-watched section when one is supplied: a
    peer dying between rounds would otherwise hang survivors here with no
    active watched section to trip hang detection.
    """
    vec = np.asarray(payload, dtype=np.float64).reshape(1, -1)
    if jax.process_count() == 1:
        return vec
    from contextlib import nullcontext

    from jax.experimental import multihost_utils

    guard = watchdog.watch(label) if watchdog is not None else nullcontext()
    with guard:
        gathered = multihost_utils.process_allgather(vec[0])
    return np.asarray(gathered, dtype=np.float64).reshape(
        jax.process_count(), -1
    )


def _agree_max(value: float, watchdog=None, label: str = "async-negotiate") -> float:
    """All-process max of a host scalar (single-process: identity)."""
    return float(np.max(_negotiate([float(value)], watchdog, label)[:, 0]))


class AsyncModelAverageAlgorithm(Algorithm):
    name = "async"
    replicated_params = False
    #: async steps run on stale local weights — a slow peer binds this
    #: family only at its negotiated boundaries (which call the
    #: ``step.straggle`` hook themselves), never per step
    straggler_gates_step = False

    def __init__(
        self,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
        calibration_steps: int = 4,
        period_steps: Optional[int] = None,
        recalibrate_rounds: Optional[int] = 64,
        max_staleness_rounds: Optional[int] = None,
    ):
        """
        Args:
            peer_selection_mode: Only ``"all"`` is supported (as in the
                reference async op).
            sync_interval_ms: Target milliseconds between averaging rounds
                (reference sync_interval_ms).  Converted to a step period at
                calibration; ``0`` means every step.
            warmup_steps: Initial steps of synchronous gradient allreduce
                before going asynchronous (reference :60).
            calibration_steps: Steps used to measure the (slowest) host's
                step time before the first round launches.
            period_steps: Pin the averaging period to an exact step count and
                skip wall-clock calibration entirely.  Use when the cadence
                must be machine-load-independent (e.g. convergence gates);
                ``sync_interval_ms`` is ignored when set.
            recalibrate_rounds: Re-run the fenced calibration after this many
                averaging rounds so the agreed period tracks sustained step-
                time changes (phase recompiles, rebucketing, input-dependent
                slowdowns).  ``None`` disables; ignored with ``period_steps``.
            max_staleness_rounds: Bounded-staleness cap: when any rank's
                applied-round counter reaches this many rounds behind the
                launched count (gradient-guard rewinds and
                ``async.partition`` drops both stall it), that boundary
                forces a synchronous catch-up average — blocking, applied
                on every rank, leaving replicas bit-identical — so the lag
                NEVER exceeds the cap.  ``0`` disables the bound (purely
                asynchronous); ``None`` reads ``BAGUA_ASYNC_MAX_STALENESS``
                (default 4).
        """
        assert peer_selection_mode == "all"
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps
        self.calibration_steps = max(1, calibration_steps)
        self.period_steps = period_steps
        self.recalibrate_rounds = (
            None if recalibrate_rounds is None else max(1, recalibrate_rounds)
        )
        if max_staleness_rounds is None:
            max_staleness_rounds = env.get_async_max_staleness()
        if max_staleness_rounds < 0:
            raise ValueError(
                f"max_staleness_rounds must be >= 0 (0 disables the bound), "
                f"got {max_staleness_rounds}"
            )
        self.max_staleness_rounds = int(max_staleness_rounds)
        self._request = _REQ_NONE    # this rank's pending abort()/resume()
        self._status = _RUNNING      # negotiated, changes only at boundaries
        self._pending: Optional[Any] = None
        self._avg_fn = None
        self._period: Optional[int] = None   # agreed steps between rounds
        self._anchor: Optional[int] = None   # step the schedule starts from
        self._calib_t0: Optional[float] = None
        self._calib_start: Optional[int] = None  # step the window opened at
        self._calib_skip = 1         # steps to skip before opening a window
        self._agreed_dt: Optional[float] = None  # slowest host's step time
        self._rounds = 0             # rounds since the period was agreed
        # bounded-staleness bookkeeping: launches are global (negotiated),
        # applies are local — the counters may diverge per rank
        self._rounds_launched = 0
        self._rounds_applied = 0
        self._rounds_dropped = 0
        self._drop_next = False      # async.partition: sit the next apply out
        self._rewinds_at_launch = 0  # trainer grad-guard rewind count @launch
        self._lock = threading.Lock()
        # _request has its own tiny lock so abort()/resume() callers never
        # block behind the boundary's cross-process gather (held under _lock)
        self._req_lock = threading.Lock()

    # ---- traced stages ---------------------------------------------------

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        # warmup: plain synchronous allreduce of gradients (reference
        # :125-131 registers a centralized op during warmup)
        if self.warmup_steps > 0:
            flats = ctx.plan.flatten_tree(grads)

            def sync(fs):
                return [ctx.comm.allreduce(f, ReduceOp.AVG) for f in fs]

            flats = jax.lax.cond(step < self.warmup_steps, sync, lambda fs: fs, flats)
            grads = ctx.plan.unflatten_tree(flats, grads)
        return grads, algo_state

    # ---- host-side async loop -------------------------------------------

    def _ensure_avg_fn(self, trainer):
        if self._avg_fn is not None:
            return
        mesh = trainer.mesh
        comm = trainer._comm
        spec = P(comm.axis_name if len(comm.axes) == 1 else comm.axes)

        def avg(params_stacked):
            p = jax.tree.map(lambda x: x[0], params_stacked)
            p = jax.tree.map(lambda x: comm.allreduce(x, ReduceOp.AVG), p)
            return jax.tree.map(lambda x: x[None], p)

        from ..compat import shard_map

        self._avg_fn = jax.jit(
            shard_map(avg, mesh=mesh, in_specs=spec, out_specs=spec,
                      check_vma=False)
        )
        # apply the averaging as a DELTA onto the current weights, exactly the
        # reference kernel's `x += reduced/n - copy` under the weight lock
        # (decentralized_full_precision_asynchronous.rs:121-126): local
        # progress made while the collective was in flight is preserved.
        self._combine_fn = jax.jit(
            lambda cur, avg_, snap: jax.tree.map(
                lambda c, a, s: c + a - s, cur, avg_, snap
            )
        )
        self._snap_fn = jax.jit(lambda t: jax.tree.map(jnp.copy, t))

    def _warm_compiles(self, trainer, params) -> None:
        """Build + compile the aux jits off the steady-state window (a cache
        hit later): at a boundary they would land inside the user's training
        loop — several seconds of remote compile on tunneled devices.

        Done-once per param avals: ``.lower().compile()`` bypasses the jit
        cache and re-lowers every call, so without the guard each periodic
        recalibration (``recalibrate_rounds``) re-paid three compiles on
        unchanged shapes (ADVICE.md).  The key read is metadata-only
        (``jnp.result_type``, never ``asarray``): materializing every leaf
        just to spell its dtype would fetch whole buffers over tunneled
        transports."""
        key = tuple(
            (tuple(jnp.shape(x)), str(jnp.result_type(x)))
            for x in jax.tree.leaves(params)
        )
        if getattr(self, "_warmed_key", None) == key:
            return
        self._ensure_avg_fn(trainer)
        self._snap_fn.lower(params).compile()
        self._avg_fn.lower(params).compile()
        self._combine_fn.lower(params, params, params).compile()
        self._warmed_key = key

    def _apply_pending(self, state, watchdog=None, block=False):
        """Apply the in-flight round to ``state`` (caller holds the lock).

        Deterministic: every process launched the identical round at the
        identical step, so every process applies it at the identical step.
        The scheduled path does NOT wait for completion — the jitted
        combine consumes ``avg_result`` through a device-side data
        dependency, so XLA keeps train steps and the averaging collective
        overlapped (host-blocking here was measured to cost 5x throughput
        on tunneled transports).  ``block=True`` (barrier/final drain)
        additionally fences, watchdog-guarded: a peer dying mid-collective
        would otherwise hang survivors with no watched section active."""
        avg_result, snapshot = self._pending
        if block:
            from contextlib import nullcontext

            guard = (
                watchdog.watch("async-drain") if watchdog is not None
                else nullcontext()
            )
            with guard:
                jax.block_until_ready(avg_result)
        state = state._replace(
            params=self._combine_fn(state.params, avg_result, snapshot)
        )
        self._pending = None
        self._drop_next = False
        self._rounds_applied += 1
        counters.incr("async/rounds_applied")
        return state

    def _drop_pending(self, why: str, health_event: bool = True) -> None:
        """Discard the in-flight round WITHOUT applying its delta (caller
        holds the lock): the rank sits this round out and its applied
        counter stalls — the staleness the negotiated catch-up bounds.

        ``health_event=False`` for drops that happen on EVERY rank at once
        (catch-up supersede, comm abort): ``async/missed_boundaries`` feeds
        the coordinator's fence scalar, and counting fleet-wide drops there
        would let one chronic straggler push every HEALTHY node past
        ``fence_unhealthy_after`` — the fence must name the offender, whose
        own partition/rewind drops were already counted."""
        self._pending = None
        self._drop_next = False
        self._rounds_dropped += 1
        counters.incr("async/rounds_dropped")
        if health_event:
            counters.incr("async/missed_boundaries")
            # missed rounds are a fenceable health event: publish them to
            # the beacon file so the launcher's heartbeat carries them to
            # the coordinator (grad-guard is the only other writer —
            # without this, a rank that drops rounds with finite gradients
            # never surfaces)
            from ..elastic.membership import write_health_beacon

            write_health_beacon()
        logger.warning(
            "async model average: round %d NOT applied on this rank (%s); "
            "applied %d/%d", self._rounds_launched, why,
            self._rounds_applied, self._rounds_launched,
        )

    def _pending_veto(self, trainer):
        """``(will_drop, reason)`` for the in-flight round — the ONE veto
        both the scheduled boundary and ``_drain_pending`` enforce (caller
        holds the lock).  Flushes not-yet-inspected grad-guard verdicts
        first: the guard runs one step behind, and a rewind the host has
        not seen yet must still veto the delta — applying a round on top
        of a rewound state would smuggle the skipped step's progress back
        in."""
        if self._pending is None:
            return False, None
        if getattr(trainer, "grad_guard", "off") != "off":
            trainer.flush_grad_health()
        if (getattr(trainer, "_guard_rewinds_total", 0)
                != self._rewinds_at_launch):
            return True, "grad-guard rewind during the round"
        if self._drop_next:
            return True, "partitioned out of the negotiation round"
        return False, None

    def _drain_pending(self, trainer, state, watchdog, block=False):
        """Drain the in-flight round under the SAME veto the scheduled
        boundary enforces (caller holds the lock): a grad-guard rewind
        since launch, or a fired partition drop, discards the delta
        instead of applying it.  Without the veto, ``barrier()`` or
        ``sync_for_checkpoint()`` called between boundaries would combine
        a pre-rewind snapshot's delta into the rewound state, or apply the
        very round an armed ``async.partition`` promised this rank never
        applies."""
        if self._pending is None:
            return state
        will_drop, reason = self._pending_veto(trainer)
        if will_drop:
            self._drop_pending(reason)
            return state
        return self._apply_pending(state, watchdog, block=block)

    def _catchup_sync(self, trainer, state, watchdog, step: int,
                      reason: str):
        """Forced synchronous model average (caller holds the lock): drop
        any in-flight round (the full sync supersedes its delta), block on
        an averaging collective over the CURRENT weights, and assign the
        result — every rank's replica is bit-identical afterwards and the
        applied counters equalize to the launched count.  Deterministic:
        the decision derives from the negotiated gather, so every process
        takes this branch at the same boundary."""
        from contextlib import nullcontext

        if self._pending is not None:
            # every rank drops here (launches are global) — not a
            # this-rank fault, so no fenceable health event
            self._drop_pending(f"superseded by catch-up sync ({reason})",
                               health_event=False)
        self._ensure_avg_fn(trainer)
        # a blocking full-fleet collective: the one async point a straggler
        # genuinely gates
        self._gated_straggle(trainer, "async.catchup")
        guard = (
            watchdog.watch("async-catchup") if watchdog is not None
            else nullcontext()
        )
        _t0 = time.monotonic()
        with trace_span("async/catchup", step=step, reason=reason,
                        launched=self._rounds_launched,
                        applied=self._rounds_applied), guard:
            avg = self._avg_fn(state.params)
            jax.block_until_ready(avg)
        self._note_collective_phase(trainer, time.monotonic() - _t0)
        state = state._replace(params=avg)
        self._rounds_applied = self._rounds_launched
        counters.incr("async/catchup_syncs")
        counters.set_gauge("async/staleness_max", 0)
        if reason == "staleness":
            _inject.record_recovery("async.partition")
        logger.warning(
            "async model average: synchronous catch-up average at step %d "
            "(%s) — replicas re-synced bit-identically after %d round(s)",
            step, reason, self._rounds_launched,
        )
        return state

    def _boundary_base_dt(self, trainer) -> Optional[float]:
        """The straggler-dilation base for gated boundaries: the agreed
        (slowest-host) step time when calibrated, else the trainer's own
        measured step cadence."""
        if self._agreed_dt is not None:
            return self._agreed_dt
        fn = getattr(trainer, "measured_step_dt", None)
        return fn() if callable(fn) else None

    @staticmethod
    def _note_collective_phase(trainer, seconds: float) -> None:
        """Attribute a host-visible synchronization wait (negotiate gather,
        catch-up average) to the anomaly detector's ``collective`` phase —
        these boundaries are where a slow peer gates this rank."""
        note = getattr(trainer, "note_phase_duration", None)
        if callable(note):
            note("collective", seconds)

    def _gated_straggle(self, trainer, sync_point: str) -> None:
        """Injected straggler stall at a gated boundary, reported back to
        the trainer's cadence tracker: an unreported boundary sleep lands
        in the next ``measured_step_dt`` sample and becomes the base of the
        next stall — the compounding that method promises to prevent."""
        slept = _inject.maybe_straggle(
            sync_point, base_dt=self._boundary_base_dt(trainer)
        )
        if slept:
            note = getattr(trainer, "note_injected_stall", None)
            if callable(note):
                note(slept)

    def _calibrate(self, trainer, state, step: int, watchdog=None) -> None:
        """Agree a launch period from the slowest host's measured step time
        (replaces the reference's per-host wall-clock gate, :170-177).

        Both window edges are FENCED with a scalar readback of the step
        counter: the host dispatch loop runs far ahead of the device, so an
        unfenced wall-clock window measures dispatch cadence, not step time
        (observed to mis-calibrate the period by 5x either way).  The
        averaging/combine/snapshot jits are also compiled HERE — at the
        first boundary they would land inside the user's steady-state
        window (several seconds of remote compile on tunneled devices).

        Restartable: periodic re-calibration (``recalibrate_rounds``) resets
        the window state and re-enters here, so a sustained step-time change
        (recompile, rebucketing) re-derives the period deterministically on
        all processes."""
        if self._calib_skip > 0:
            # skip step(s) right after warmup / a recalibration trigger:
            # they may include trace/compile time
            self._calib_skip -= 1
            return
        if self._calib_start is None:
            self._warm_compiles(trainer, state.params)
            np.asarray(state.step)  # fence: start from a drained pipeline
            self._calib_t0 = time.monotonic()
            self._calib_start = step
        elif step >= self._calib_start + self.calibration_steps:
            np.asarray(state.step)  # fence: include the full device work
            window = step - self._calib_start
            local_dt = (time.monotonic() - self._calib_t0) / window
            agreed_dt = _agree_max(local_dt, watchdog, "async-calibrate")
            self._agreed_dt = agreed_dt
            self._period = max(
                1, int(round(self.sync_interval_ms / (agreed_dt * 1000.0)))
            )
            self._anchor = step
            self._rounds = 0
            logger.info(
                "async model average: agreed step time %.4fs (local %.4fs) "
                "-> averaging every %d step(s)",
                agreed_dt, local_dt, self._period,
            )

    def host_pre_step(self, trainer, state):
        """Between-steps swap point (the reference's weight lock boundary)."""
        from ..communication import is_aborted

        if is_aborted():
            # the global abort flag (watchdog or user) stops the averaging
            # control loop exactly like a local abort() call — no new
            # rounds are launched, pending results are dropped; this process
            # is about to exit for gang restart, so cross-rank agreement is
            # moot here
            with self._lock:
                if self._pending is not None:
                    # abort stops every rank's control loop — fleet-wide,
                    # not a this-rank fault
                    self._drop_pending("comm abort flag raised",
                                       health_event=False)
            return state
        step = trainer._step_counter
        if step <= self.warmup_steps:
            return state
        if trainer._comm.nranks() == 1:
            # the averaging collective is an identity on a 1-rank comm world:
            # skip snapshot/avg/combine entirely (the reference's async CI
            # floor is the HIGHEST of all families — async must never cost;
            # round 4 measured ~10% single-chip overhead from these hops)
            return state
        watchdog = getattr(trainer, "_watchdog", None)
        with self._lock:
            if self._period is None:
                if self.period_steps is not None:
                    # pinned cadence: no wall-clock dependence at all
                    self._warm_compiles(trainer, state.params)
                    self._period = max(1, int(self.period_steps))
                    self._anchor = step
                    self._rounds = 0
                else:
                    self._calibrate(trainer, state, step, watchdog)
                return state
            if (step - self._anchor) % self._period != 0:
                return state
            # ---- scheduled boundary: negotiate, drain, launch ------------
            # every process reaches this branch at the same step, so the
            # control allgather and the collectives below line up globally.
            # A slow peer gates this boundary (the gather blocks on it);
            # the intervening steps ran free on stale local weights.
            self._gated_straggle(trainer, "async.negotiate")
            # the shared veto decides the apply BEFORE the gather so the
            # negotiated applied_after reflects the drop
            will_drop, drop_reason = self._pending_veto(trainer)
            # Requests are edge-triggered: the atomic read-then-clear under
            # _req_lock means an abort()/resume() issued from another thread
            # while the gather below is in flight stays pending for the next
            # boundary instead of being wiped.
            with self._req_lock:
                my_req, self._request = self._request, _REQ_NONE
            applied_after = self._rounds_applied + (
                1 if (self._pending is not None and not will_drop) else 0
            )
            # span: the negotiation gather is where a slow peer gates every
            # rank — its duration IS the straggler wait
            _t0 = time.monotonic()
            with trace_span("async/negotiate", step=step,
                            launched=self._rounds_launched,
                            applied=self._rounds_applied):
                gathered = _negotiate(
                    [float(my_req), float(applied_after)], watchdog
                )
            self._note_collective_phase(trainer, time.monotonic() - _t0)
            req = float(np.max(gathered[:, 0]))
            min_applied = int(np.min(gathered[:, 1]))
            if req >= _REQ_ABORT:
                new_status = _ABORTED
            elif req >= _REQ_RESUME:
                new_status = _RUNNING
            else:
                new_status = self._status
            if new_status != self._status:
                counters.incr(
                    "async/aborts_negotiated" if new_status == _ABORTED
                    else "async/resumes_negotiated"
                )
                logger.info(
                    "async model average: negotiated %s at step %d",
                    "ABORT" if new_status == _ABORTED else "RESUME", step,
                )
            self._status = new_status
            # ---- bounded staleness: rounds the worst rank will still be
            # missing after this boundary's apply/drop decisions (the
            # in-flight round counts as applied when it is about to be).
            # Deterministic on every process: a pure function of the
            # gathered counters and the (negotiated, hence uniform)
            # launched count.
            # the trigger is >= (not >): this boundary may launch a fresh
            # round the lagging rank misses too, so waiting for lag > cap
            # would let the observed lag transiently hit cap+1 — catching
            # up AT the cap is what makes "applied never lags launched by
            # more than max_staleness_rounds" a true invariant
            lag = self._rounds_launched - min_applied
            if (
                self._status == _RUNNING
                and self.max_staleness_rounds
                and lag >= self.max_staleness_rounds
            ):
                return self._catchup_sync(trainer, state, watchdog, step,
                                          "staleness")
            counters.set_gauge("async/staleness_max", lag)
            if self._pending is not None:
                if will_drop:
                    self._drop_pending(drop_reason)
                else:
                    # the previous round was launched by all processes;
                    # drain it deterministically whether we stay running or
                    # just aborted
                    state = self._apply_pending(state, watchdog)
            if self._status != _RUNNING:
                return state
            # ---- RUNNING-only sequence: count the round, maybe
            # recalibrate, else launch.  Aborted windows run none of this —
            # recalibration firing there would repeatedly drain the
            # pipeline and stall a pending resume behind a fresh
            # calibration window.
            self._rounds += 1
            if (
                self.period_steps is None
                and self.recalibrate_rounds is not None
                and self._rounds >= self.recalibrate_rounds
            ):
                # periodic re-calibration: reset the window state machine so
                # the period re-derives from CURRENT step time.  Step-count
                # driven, hence simultaneous on every process.
                self._period = None
                self._calib_start = None
                self._calib_skip = 1
                logger.info(
                    "async model average: recalibrating period at step %d "
                    "after %d rounds", step, self._rounds,
                )
                return state
            self._ensure_avg_fn(trainer)
            # snapshot = explicit copy (the reference op copies weights on
            # the torch stream first, rs:50-60): the train step donates
            # state.params, so the retained snapshot needs its own buffers
            snapshot = self._snap_fn(state.params)
            # the round launched HERE is the one a partition costs the
            # rank — its apply happens one boundary later.  The fire is
            # consumed at launch, not at negotiation, so a boundary that
            # launches nothing (catch-up, abort, recalibration) cannot
            # silently spend a count-limited spec with no round to drop.
            self._drop_next = _inject.maybe_drop_negotiation_round()
            # dispatch is async: train steps keep running while the
            # averaging collective is in flight
            self._pending = (self._avg_fn(snapshot), snapshot)
            self._rounds_launched += 1
            self._rewinds_at_launch = getattr(
                trainer, "_guard_rewinds_total", 0
            )
            counters.incr("async/rounds_launched")
        return state

    # ---- control (reference :203-233) -----------------------------------

    def abort(self):
        """Request a stop of background averaging (e.g. before evaluation).

        Takes effect at the next scheduled boundary on ALL processes
        simultaneously (the reference's negotiated ABORT, :203-218); may be
        called from any single rank — and cleared by a ``resume()`` from any
        rank, not just the one that aborted."""
        with self._req_lock:
            self._request = _REQ_ABORT
        logger.info("async model average abort requested")

    def resume(self):
        """Request that background averaging resumes (negotiated RESUME)."""
        with self._req_lock:
            self._request = _REQ_RESUME
        logger.info("async model average resume requested")

    def barrier(self, trainer, state):
        """Drain any in-flight averaging and apply it (the reference's
        post-abort synchronization), under the boundary's grad-guard /
        partition veto.  Collective: call on every process."""
        with self._lock:
            state = self._drain_pending(
                trainer, state, getattr(trainer, "_watchdog", None),
                block=True,
            )
        return state

    def sync_for_checkpoint(self, trainer, state):
        """Blocking synchronous model average that leaves every rank's
        replica bit-identical — run right before saving a checkpoint that
        must survive an elastic WORLD RESIZE: stacked per-rank rows restore
        across world sizes only when the rows agree
        (``trainer.restore_checkpoint`` verifies row identity and re-tiles
        row 0 onto the new world).  Drains and applies any in-flight round
        first.  Collective: call on every process."""
        if trainer._comm.nranks() == 1:
            return state
        watchdog = getattr(trainer, "_watchdog", None)
        with self._lock:
            state = self._drain_pending(trainer, state, watchdog, block=True)
            return self._catchup_sync(
                trainer, state, watchdog, trainer._step_counter, "checkpoint"
            )

    def reset_schedule(self) -> None:
        """Forget the negotiated schedule and any in-flight round: the next
        post-warmup step re-enters a FRESH calibration window (or re-pins
        ``period_steps``) and the round counters restart from zero.

        Called through :meth:`on_restore` after a checkpoint restore —
        elastic world resizes included: the restored run must not apply a
        round launched against pre-restore weights (a stale ``_pending``),
        nor keep a launch anchor/agreed period negotiated by a world that
        no longer exists."""
        with self._lock:
            if self._pending is not None:
                self._pending = None
                counters.incr("async/rounds_dropped")
            self._period = None
            self._anchor = None
            self._calib_t0 = None
            self._calib_start = None
            self._calib_skip = 1
            self._agreed_dt = None
            self._rounds = 0
            self._rounds_launched = 0
            self._rounds_applied = 0
            self._rounds_dropped = 0
            self._drop_next = False
            self._rewinds_at_launch = 0
            self._status = _RUNNING
            with self._req_lock:
                self._request = _REQ_NONE
        logger.info("async model average: schedule reset — next post-warmup "
                    "step opens a fresh calibration window")

    def on_restore(self, trainer) -> None:
        self.reset_schedule()
