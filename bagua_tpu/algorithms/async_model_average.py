"""Asynchronous model averaging.

Counterpart of /root/reference/bagua/torch_api/algorithms/async_model_average.py
(:156-233) + comm_ops/decentralized_full_precision_asynchronous.rs: a
background loop continuously allreduce-averages the weights while compute
proceeds, with a lock so weights are swapped only between steps, and
``abort``/``resume`` control.

TPU-native mechanism: the reference needs a worker thread + CUDA stream +
weight mutex because torch executes eagerly.  JAX's async dispatch already
gives us a "background stream": the averaging is its own tiny jitted
collective, dispatched without blocking the Python loop; train steps keep
executing on stale local weights while it's in flight (same staleness
semantics as the reference), and the result is swapped into the train state
between steps — the functional equivalent of the reference's weight lock held
during forward/backward (:156-168).  ``warmup_steps`` of synchronous gradient
allreduce match the reference (:60, :125-131).
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..communication import ReduceOp
from .base import Algorithm, AlgorithmContext

logger = logging.getLogger(__name__)

_RUNNING = "running"
_ABORTED = "aborted"


class AsyncModelAverageAlgorithm(Algorithm):
    replicated_params = False

    def __init__(
        self,
        peer_selection_mode: str = "all",
        sync_interval_ms: int = 500,
        warmup_steps: int = 0,
    ):
        """
        Args:
            peer_selection_mode: Only ``"all"`` is supported (as in the
                reference async op).
            sync_interval_ms: Minimum milliseconds between launching two
                averaging rounds (reference sync_interval_ms).
            warmup_steps: Initial steps of synchronous gradient allreduce
                before going asynchronous (reference :60).
        """
        assert peer_selection_mode == "all"
        self.peer_selection_mode = peer_selection_mode
        self.sync_interval_ms = sync_interval_ms
        self.warmup_steps = warmup_steps
        self._status = _RUNNING
        self._pending: Optional[Any] = None
        self._avg_fn = None
        self._last_launch = 0.0
        self._lock = threading.Lock()

    # ---- traced stages ---------------------------------------------------

    def process_grads(self, ctx: AlgorithmContext, grads, params, algo_state, step):
        # warmup: plain synchronous allreduce of gradients (reference
        # :125-131 registers a centralized op during warmup)
        if self.warmup_steps > 0:
            flats = ctx.plan.flatten_tree(grads)

            def sync(fs):
                return [ctx.comm.allreduce(f, ReduceOp.AVG) for f in fs]

            flats = jax.lax.cond(step < self.warmup_steps, sync, lambda fs: fs, flats)
            grads = ctx.plan.unflatten_tree(flats, grads)
        return grads, algo_state

    # ---- host-side async loop -------------------------------------------

    def _ensure_avg_fn(self, trainer):
        if self._avg_fn is not None:
            return
        mesh = trainer.mesh
        comm = trainer._comm
        spec = P(comm.axis_name if len(comm.axes) == 1 else comm.axes)

        def avg(params_stacked):
            p = jax.tree.map(lambda x: x[0], params_stacked)
            p = jax.tree.map(lambda x: comm.allreduce(x, ReduceOp.AVG), p)
            return jax.tree.map(lambda x: x[None], p)

        self._avg_fn = jax.jit(
            jax.shard_map(avg, mesh=mesh, in_specs=spec, out_specs=spec,
                          check_vma=False)
        )
        # apply the averaging as a DELTA onto the current weights, exactly the
        # reference kernel's `x += reduced/n - copy` under the weight lock
        # (decentralized_full_precision_asynchronous.rs:121-126): local
        # progress made while the collective was in flight is preserved.
        self._combine_fn = jax.jit(
            lambda cur, avg_, snap: jax.tree.map(
                lambda c, a, s: c + a - s, cur, avg_, snap
            )
        )
        self._snap_fn = jax.jit(lambda t: jax.tree.map(jnp.copy, t))

    def host_pre_step(self, trainer, state):
        """Between-steps swap point (the reference's weight lock boundary)."""
        import time

        from ..communication import is_aborted

        if is_aborted():
            # the global abort flag (watchdog or user) stops the averaging
            # control loop exactly like a local abort() call — no new
            # rounds are launched, pending results are dropped
            with self._lock:
                self._pending = None
            return state
        if self._status != _RUNNING or trainer._step_counter <= self.warmup_steps:
            return state
        self._ensure_avg_fn(trainer)
        with self._lock:
            if self._pending is not None:
                avg_result, snapshot = self._pending
                if all(l.is_ready() for l in jax.tree.leaves(avg_result)):
                    state = state._replace(
                        params=self._combine_fn(state.params, avg_result, snapshot)
                    )
                    self._pending = None
            now = time.monotonic()
            if (
                self._pending is None
                and (now - self._last_launch) * 1000.0 >= self.sync_interval_ms
            ):
                # snapshot = explicit copy (the reference op copies weights on
                # the torch stream first, rs:50-60): the train step donates
                # state.params, so the retained snapshot needs its own buffers
                snapshot = self._snap_fn(state.params)
                # dispatch is async: train steps keep running while the
                # averaging collective is in flight
                self._pending = (self._avg_fn(snapshot), snapshot)
                self._last_launch = now
        return state

    # ---- control (reference :203-233) -----------------------------------

    def abort(self):
        """Stop background averaging (e.g. before evaluation)."""
        with self._lock:
            self._status = _ABORTED
            self._pending = None
        logger.info("async model average aborted")

    def resume(self):
        """Resume background averaging."""
        with self._lock:
            self._status = _RUNNING
        logger.info("async model average resumed")

    def barrier(self, trainer, state):
        """Drain any in-flight averaging and apply it (the reference's
        post-abort synchronization)."""
        with self._lock:
            if self._pending is not None:
                avg_result, snapshot = self._pending
                jax.block_until_ready(avg_result)
                state = state._replace(
                    params=self._combine_fn(state.params, avg_result, snapshot)
                )
                self._pending = None
        return state
