"""Autotune sidecar service (reference ``bagua/service/``)."""

from .autotune_service import AutotuneClient, AutotuneService, run_autotune_server  # noqa: F401
from .bayesian_optimizer import BayesianOptimizer, BoolParam, FloatParam, IntParam  # noqa: F401
