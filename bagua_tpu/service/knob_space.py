"""Declarative, capability-gated knob space for autotune v2.

The legacy search (PR 2) optimized ``bucket_size × is_hierarchical_reduce``
on raw step time.  This module widens the space to every runtime knob the
trainer can actually flip at a check-in — overlap + per-tier chunk bytes,
the per-link codec ladder (incl. the stateful 1-bit/top-k rungs), the
flat-resident layout, and algorithm-family switching — and gates each knob
on the TASK's capabilities, which the trainer reports once at tensor
registration (mesh shape, error-feedback availability, flat-layout safety,
legal switch targets).  A knob the trainer would refuse is simply never in
the space, so no sample is burned discovering a refusal.

Point-dependent legality rides the optimizer's conditional sampling
(:mod:`.bayesian_optimizer`): chunk-byte knobs are inactive while
``overlap == "off"``, the DCN-tier knobs while the mesh has one tier, the
flat-resident knob while the sampled family cannot hold flat state.
Inactive coordinates collapse to canonical values, so the optimizer never
emits two points that differ only on a dead knob.

See docs/autotune.md for the full knob table and gating rules.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .bayesian_optimizer import (
    BoolParam,
    CatParam,
    Condition,
    IntParam,
    Param,
)

# bucket_size = 2**p; mirrors the reference's 10..31 exponent range
MIN_BUCKET_SIZE_EXP = 10
MAX_BUCKET_SIZE_EXP = 31

# per-tier ring chunk target = 2**p bytes; 64 KiB .. 64 MiB covers the
# useful range on both link classes (docs/hierarchical.md)
MIN_CHUNK_BYTES_EXP = 16
MAX_CHUNK_BYTES_EXP = 26

#: codec rungs per tier knob.  "auto" defers to the algorithm family's own
#: wire codec (the constructor default), "off" forces full precision; the
#: stateful error-feedback rungs are appended only when the task's mesh can
#: carry the per-bucket residual (``ef_ok``).
BASE_CODEC_CHOICES = ("auto", "off", "minmax_uint8", "fp8_e4m3")
EF_CODEC_CHOICES = ("onebit_ef", "topk")


def evaluate_active(
    params: List[Param], conditions: Dict[str, Condition], point: Dict
) -> Dict[str, bool]:
    """Which coordinates of ``point`` are active (mirror of
    ``BayesianOptimizer.active`` for callers that hold only the space)."""
    from .bayesian_optimizer import _inactive_value

    out: Dict[str, bool] = {}
    prefix: Dict = {}
    for p in params:
        cond = conditions.get(p.name)
        is_active = True if cond is None else bool(cond(prefix))
        out[p.name] = is_active
        prefix[p.name] = (
            point.get(p.name, _inactive_value(p))
            if is_active else _inactive_value(p)
        )
    return out


@dataclass
class KnobSpace:
    """A built search space plus the point<->hyperparameter translation.

    ``params``/``conditions`` feed the optimizer; :meth:`point_to_updates`
    renders an asked point as ``BaguaHyperparameter`` field updates (the
    wire schema the trainer already consumes), and :meth:`point_from_hp`
    inverts a reported hyperparameter set back into a point so the
    optimizer can be told the score of what actually ran.
    """

    params: List[Param]
    conditions: Dict[str, Condition]
    capabilities: Dict = field(default_factory=dict)

    def names(self) -> List[str]:
        return [p.name for p in self.params]

    def has(self, name: str) -> bool:
        return any(p.name == name for p in self.params)

    def active(self, point: Dict) -> Dict[str, bool]:
        return evaluate_active(self.params, self.conditions, point)

    # -- point -> BaguaHyperparameter field updates -----------------------

    def point_to_updates(self, point: Dict) -> Dict:
        """Field updates for ``BaguaHyperparameter.update()``.  Inactive
        knobs emit their keep-current sentinel (0 / "") — the trainer
        leaves the live value untouched, and the step-cache key zeroes
        them anyway (chunk bytes while overlap is off)."""
        act = self.active(point)
        updates: Dict = {}
        if "bucket_size_2p" in point:
            updates["bucket_size"] = 2 ** int(point["bucket_size_2p"])
        if self.has("is_hierarchical_reduce"):
            updates["is_hierarchical_reduce"] = bool(
                point.get("is_hierarchical_reduce", False)
            )
        if self.has("algorithm"):
            updates["algorithm"] = str(point.get("algorithm", ""))
        if self.has("overlap"):
            updates["overlap"] = str(point.get("overlap", "off"))
        for knob, fld in (
            ("overlap_chunk_bytes_intra_2p", "overlap_chunk_bytes_intra"),
            ("overlap_chunk_bytes_inter_2p", "overlap_chunk_bytes_inter"),
        ):
            if self.has(knob):
                updates[fld] = (
                    2 ** int(point[knob]) if act.get(knob) else 0
                )
        for knob in ("compress_intra", "compress_inter"):
            if self.has(knob):
                updates[knob] = (
                    str(point.get(knob, "auto")) if act.get(knob) else ""
                )
        if self.has("flat_resident"):
            updates["flat_resident"] = (
                str(point.get("flat_resident", "off"))
                if act.get("flat_resident") else ""
            )
        return updates

    # -- BaguaHyperparameter -> point -------------------------------------

    def point_from_hp(self, hp) -> Dict:
        """Reconstruct the search point that produced ``hp`` (the reported
        hyperparameters of the window being scored).  Unknown / keep-current
        values fall back to canonical defaults; the optimizer canonicalizes
        the result, so inactive coordinates collapse regardless."""
        point: Dict = {}
        for p in self.params:
            name = p.name
            if name == "bucket_size_2p":
                exp = max(1, int(getattr(hp, "bucket_size", 0) or 1)).bit_length() - 1
                point[name] = max(MIN_BUCKET_SIZE_EXP,
                                  min(MAX_BUCKET_SIZE_EXP, exp))
            elif name == "is_hierarchical_reduce":
                point[name] = bool(getattr(hp, "is_hierarchical_reduce", False))
            elif name == "algorithm":
                v = getattr(hp, "algorithm", "") or \
                    self.capabilities.get("current_algorithm", "")
                point[name] = v if v in p.choices else p.choices[0]
            elif name == "overlap":
                v = getattr(hp, "overlap", "")
                point[name] = v if v in p.choices else "off"
            elif name in ("overlap_chunk_bytes_intra_2p",
                          "overlap_chunk_bytes_inter_2p"):
                fld = name[: -len("_2p")]
                b = int(getattr(hp, fld, 0) or 0)
                exp = b.bit_length() - 1 if b > 0 else MIN_CHUNK_BYTES_EXP
                point[name] = max(MIN_CHUNK_BYTES_EXP,
                                  min(MAX_CHUNK_BYTES_EXP, exp))
            elif name in ("compress_intra", "compress_inter"):
                v = getattr(hp, name, "")
                point[name] = v if v in p.choices else "auto"
            elif name == "flat_resident":
                v = getattr(hp, name, "")
                point[name] = v if v in p.choices else "off"
        return point


def build_knob_space(
    capabilities: Optional[Dict],
    tune_algorithm: bool = False,
) -> Optional[KnobSpace]:
    """Build the v2 space from a task's check-in capabilities, or return
    ``None`` for the legacy two-knob space (no capabilities reported —
    an old trainer, or ``BAGUA_AUTOTUNE_SPACE=legacy``).

    Capability keys (all optional, conservative defaults):

    * ``two_tier`` — both tier communicators exist; unlocks
      ``is_hierarchical_reduce``, the DCN chunk knob, ``compress_inter``.
    * ``ef_ok`` — the mesh/trainer can hold the per-bucket error-feedback
      residual; unlocks the ``onebit_ef``/``topk`` codec rungs.
    * ``flat_ok`` — live flat<->leaf relayout is safe for the current
      optimizer/algorithm; unlocks the ``flat_resident`` knob.
    * ``families`` — legal algorithm switch targets (incl. the current
      family); with ``tune_algorithm`` and >1 entries, unlocks the
      ``algorithm`` categorical.
    * ``flat_families`` — the subset of ``families`` that can hold flat
      state; the flat knob is conditionally inactive outside it.
    * ``current_algorithm`` — fallback for hyperparameter inversion.
    """
    if not capabilities or capabilities.get("space") != "v2":
        return None

    two_tier = bool(capabilities.get("two_tier", False))
    ef_ok = bool(capabilities.get("ef_ok", False))
    flat_ok = bool(capabilities.get("flat_ok", False))
    families = [str(f) for f in capabilities.get("families") or []]
    flat_families = [str(f) for f in capabilities.get("flat_families") or []]
    current = str(capabilities.get("current_algorithm", "") or "")
    if current and current not in families:
        families = [current] + families

    codec_choices = BASE_CODEC_CHOICES + (EF_CODEC_CHOICES if ef_ok else ())

    params: List[Param] = []
    conditions: Dict[str, Condition] = {}

    # declaration order matters: conditions read earlier coordinates only
    if tune_algorithm and len(families) > 1:
        params.append(CatParam("algorithm", tuple(families)))
    params.append(IntParam("bucket_size_2p",
                           MIN_BUCKET_SIZE_EXP, MAX_BUCKET_SIZE_EXP))
    if two_tier:
        params.append(BoolParam("is_hierarchical_reduce"))
    params.append(CatParam("overlap", ("off", "on")))
    params.append(IntParam("overlap_chunk_bytes_intra_2p",
                           MIN_CHUNK_BYTES_EXP, MAX_CHUNK_BYTES_EXP))
    conditions["overlap_chunk_bytes_intra_2p"] = (
        lambda pt: pt.get("overlap") == "on"
    )
    if two_tier:
        params.append(IntParam("overlap_chunk_bytes_inter_2p",
                               MIN_CHUNK_BYTES_EXP, MAX_CHUNK_BYTES_EXP))
        conditions["overlap_chunk_bytes_inter_2p"] = (
            lambda pt: pt.get("overlap") == "on"
            and pt.get("is_hierarchical_reduce", False)
        )
    params.append(CatParam("compress_intra", codec_choices))
    if two_tier:
        # with the two-level decomposition off, the flat comm world spans
        # both mesh axes and the compressed ring disengages (LOUDLY — see
        # AlgorithmContext.flat_ring_codec), so BOTH tier codecs are dead
        # knobs; the DCN tier itself only exists under hierarchical reduce
        conditions["compress_intra"] = (
            lambda pt: pt.get("is_hierarchical_reduce", False)
        )
        params.append(CatParam("compress_inter", codec_choices))
        conditions["compress_inter"] = (
            lambda pt: pt.get("is_hierarchical_reduce", False)
        )
    if flat_ok:
        params.append(CatParam("flat_resident", ("off", "on")))
        if tune_algorithm and len(families) > 1 and flat_families:
            conditions["flat_resident"] = (
                lambda pt: pt.get("algorithm") in flat_families
            )
    return KnobSpace(params=params, conditions=conditions,
                     capabilities=dict(capabilities))
