"""Sequential model-based optimizer for the autotune search.

Counterpart of the reference's thin skopt wrapper
(/root/reference/bagua/service/bayesian_optimizer.py:7-79: IntParam/BoolParam/
FloatParam over a skopt ``Optimizer`` with Halton init, maximizing by telling
negated scores).  scikit-optimize is not in this image, so the same interface
is backed by a self-contained strategy: low-discrepancy (Halton) exploration
for the first ``n_initial_points`` asks, then surrogate-guided
exploit/explore — perturb the best known point along one coordinate, with an
ε-greedy random restart.  The search spaces here are small (tens to a few
thousand discrete points), so this converges at least as fast as a GP would.

Autotune-v2 extensions (docs/autotune.md):

* ``CatParam`` — categorical coordinates (codec names, algorithm families,
  ``overlap`` on/off) alongside the int/float/bool axes.
* **Conditional (hierarchical) sampling** — ``conditions`` maps a param name
  to a predicate over the earlier coordinates; when the predicate is false
  the coordinate is INACTIVE and canonicalized to a fixed value (its
  ``low`` / ``False`` / first choice).  Two points differing only on
  inactive coordinates are therefore the SAME point: samples are never
  burned exploring chunk sizes while overlap is off, and :meth:`_perturb`
  only moves coordinates that are active at the base point.
* **Running-mean ``tell``** — repeated observations of the same
  (canonical) point fold into a running mean instead of piling up
  last-writer-wins duplicates, so one lucky sample of a noisy window
  cannot dominate :meth:`best`.
* **Warm-start priors** — :meth:`prime` queues suggested points (autopilot
  hints, historian trends) that :meth:`ask` serves before resuming its own
  schedule: a hint biases WHERE the search looks next without pinning the
  outcome.
* **Coordinate weighting** — :meth:`weight` biases which coordinate the
  exploit step perturbs (e.g. weight ``compress_inter`` up while the DCN
  share of the step is high).
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple, Union


@dataclass(frozen=True)
class IntParam:
    name: str
    low: int
    high: int  # inclusive


@dataclass(frozen=True)
class FloatParam:
    name: str
    low: float
    high: float


@dataclass(frozen=True)
class BoolParam:
    name: str


@dataclass(frozen=True)
class CatParam:
    """Categorical coordinate: an unordered finite choice set (codec names,
    algorithm families).  ``choices`` must be hashable and non-empty; the
    first choice is the canonical value while the coordinate is inactive."""

    name: str
    choices: Tuple


Param = Union[IntParam, FloatParam, BoolParam, CatParam]

#: predicate over the (canonicalized) earlier coordinates deciding whether a
#: param is active; params are canonicalized in declaration order, so a
#: condition may only read coordinates declared BEFORE its param
Condition = Callable[[Dict], bool]


def _halton(index: int, base: int) -> float:
    f, r = 1.0, 0.0
    i = index + 1
    while i > 0:
        f /= base
        r += f * (i % base)
        i //= base
    return r


_PRIMES = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]


def _inactive_value(p: Param):
    """Canonical value an inactive coordinate collapses to."""
    if isinstance(p, IntParam):
        return p.low
    if isinstance(p, FloatParam):
        return p.low
    if isinstance(p, CatParam):
        return p.choices[0]
    return False


class BayesianOptimizer:
    """tell/ask loop maximizing a noisy score over a small mixed space."""

    def __init__(
        self,
        params: List[Param],
        n_initial_points: int = 10,
        explore_prob: float = 0.25,
        seed: int = 0,
        conditions: Optional[Dict[str, Condition]] = None,
    ):
        self.params = list(params)
        self.n_initial_points = n_initial_points
        self.explore_prob = explore_prob
        self.conditions = dict(conditions or {})
        self._rng = random.Random(seed)
        # canonical point key -> [point, running mean score, n observations]
        self._observations: Dict[Tuple, List] = {}
        self._ask_count = 0
        # warm-start priors (FIFO) and exploit coordinate weights
        self._primed: List[Dict] = []
        self._coord_weights: Dict[str, float] = {}

    # -- space helpers ----------------------------------------------------

    def active(self, point: Dict) -> Dict[str, bool]:
        """Which coordinates are active at ``point`` (declaration order;
        conditions read the canonicalized prefix)."""
        out: Dict[str, bool] = {}
        prefix: Dict = {}
        for p in self.params:
            cond = self.conditions.get(p.name)
            is_active = True if cond is None else bool(cond(prefix))
            out[p.name] = is_active
            prefix[p.name] = (
                point.get(p.name, _inactive_value(p))
                if is_active else _inactive_value(p)
            )
        return out

    def _canonicalize(self, point: Dict) -> Dict:
        """Collapse inactive coordinates to their canonical values (and fill
        missing ones), in declaration order — the identity under which
        observations fold and perturbations never vary dead knobs."""
        out: Dict = {}
        for p in self.params:
            cond = self.conditions.get(p.name)
            is_active = True if cond is None else bool(cond(out))
            if not is_active:
                out[p.name] = _inactive_value(p)
            else:
                out[p.name] = point.get(p.name, _inactive_value(p))
        return out

    def _from_unit(self, u: List[float]) -> Dict:
        point = {}
        for p, x in zip(self.params, u):
            if isinstance(p, IntParam):
                point[p.name] = min(p.high, p.low + int(x * (p.high - p.low + 1)))
            elif isinstance(p, FloatParam):
                point[p.name] = p.low + x * (p.high - p.low)
            elif isinstance(p, CatParam):
                point[p.name] = p.choices[
                    min(len(p.choices) - 1, int(x * len(p.choices)))
                ]
            else:
                point[p.name] = x >= 0.5
        return self._canonicalize(point)

    def _random_point(self) -> Dict:
        return self._from_unit([self._rng.random() for _ in self.params])

    def _perturb(self, point: Dict) -> Dict:
        """Move one ACTIVE coordinate a small step — local search around the
        best.  Coordinate choice is weighted (:meth:`weight`), and inactive
        coordinates are never varied (their canonical values are restored by
        canonicalization anyway — moving them would re-sample the same
        point)."""
        out = dict(point)
        act = self.active(point)
        candidates = [p for p in self.params if act[p.name]]
        if not candidates:
            candidates = list(self.params)
        weights = [max(1e-9, self._coord_weights.get(p.name, 1.0))
                   for p in candidates]
        p = self._rng.choices(candidates, weights=weights, k=1)[0]
        if isinstance(p, IntParam):
            span = max(1, (p.high - p.low) // 8)
            out[p.name] = min(
                p.high, max(p.low, point[p.name] + self._rng.choice([-span, span]))
            )
        elif isinstance(p, FloatParam):
            span = (p.high - p.low) / 8
            v = point[p.name] + self._rng.uniform(-span, span)
            out[p.name] = min(p.high, max(p.low, v))
        elif isinstance(p, CatParam):
            others = [c for c in p.choices if c != point[p.name]]
            if others:
                out[p.name] = self._rng.choice(others)
        else:
            out[p.name] = not point[p.name]
        return self._canonicalize(out)

    def _key(self, canonical: Dict) -> Tuple:
        return tuple(canonical[p.name] for p in self.params)

    # -- priors / weighting ----------------------------------------------

    def prime(self, updates: Dict) -> None:
        """Queue a warm-start point for the next :meth:`ask`.  ``updates``
        may be partial — missing coordinates come from the best known point
        (or the canonical defaults before any observation).  A prior is a
        suggestion, not a pin: it is scored like any other sample and only
        survives if it wins."""
        base = self.best()
        point = dict(base[0]) if base is not None else {}
        point.update(updates)
        self._primed.append(self._canonicalize(point))

    def weight(self, name: str, w: float) -> None:
        """Bias the exploit step toward perturbing coordinate ``name`` by
        multiplicative weight ``w`` (1.0 = neutral)."""
        if any(p.name == name for p in self.params):
            self._coord_weights[name] = float(w)

    # -- tell/ask ---------------------------------------------------------

    def tell(self, point: Dict, score: float) -> None:
        if not (isinstance(score, (int, float)) and math.isfinite(score)):
            return
        canonical = self._canonicalize(point)
        key = self._key(canonical)
        obs = self._observations.get(key)
        if obs is None:
            self._observations[key] = [canonical, float(score), 1]
        else:
            # fold into a running mean: noisy windows of the same config
            # average out instead of the single luckiest one winning best()
            obs[2] += 1
            obs[1] += (float(score) - obs[1]) / obs[2]

    def best(self) -> Optional[Tuple[Dict, float]]:
        if not self._observations:
            return None
        point, mean, _ = max(self._observations.values(), key=lambda o: o[1])
        return dict(point), mean

    def ask(self) -> Dict:
        if self._primed:
            return self._primed.pop(0)
        self._ask_count += 1
        if self._ask_count <= self.n_initial_points or not self._observations:
            u = [
                _halton(self._ask_count - 1, _PRIMES[i % len(_PRIMES)])
                for i in range(len(self.params))
            ]
            return self._from_unit(u)
        if self._rng.random() < self.explore_prob:
            return self._random_point()
        best_point, _ = self.best()
        return self._perturb(best_point)
