"""Sequential model-based optimizer for the autotune search.

Counterpart of the reference's thin skopt wrapper
(/root/reference/bagua/service/bayesian_optimizer.py:7-79: IntParam/BoolParam/
FloatParam over a skopt ``Optimizer`` with Halton init, maximizing by telling
negated scores).  scikit-optimize is not in this image, so the same interface
is backed by a self-contained strategy: low-discrepancy (Halton) exploration
for the first ``n_initial_points`` asks, then surrogate-guided
exploit/explore — perturb the best known point along one coordinate, with an
ε-greedy random restart.  The search spaces here are tiny (≤ ~44 discrete
points: 22 bucket-size exponents × 2 hierarchical flags), so this converges
at least as fast as a GP would.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union


@dataclass(frozen=True)
class IntParam:
    name: str
    low: int
    high: int  # inclusive


@dataclass(frozen=True)
class FloatParam:
    name: str
    low: float
    high: float


@dataclass(frozen=True)
class BoolParam:
    name: str


Param = Union[IntParam, FloatParam, BoolParam]


def _halton(index: int, base: int) -> float:
    f, r = 1.0, 0.0
    i = index + 1
    while i > 0:
        f /= base
        r += f * (i % base)
        i //= base
    return r


_PRIMES = [2, 3, 5, 7, 11, 13, 17, 19, 23, 29]


class BayesianOptimizer:
    """tell/ask loop maximizing a noisy score over a small mixed space."""

    def __init__(
        self,
        params: List[Param],
        n_initial_points: int = 10,
        explore_prob: float = 0.25,
        seed: int = 0,
    ):
        self.params = list(params)
        self.n_initial_points = n_initial_points
        self.explore_prob = explore_prob
        self._rng = random.Random(seed)
        self._observations: List[Tuple[Dict, float]] = []
        self._ask_count = 0

    # -- space helpers ----------------------------------------------------

    def _from_unit(self, u: List[float]) -> Dict:
        point = {}
        for p, x in zip(self.params, u):
            if isinstance(p, IntParam):
                point[p.name] = min(p.high, p.low + int(x * (p.high - p.low + 1)))
            elif isinstance(p, FloatParam):
                point[p.name] = p.low + x * (p.high - p.low)
            else:
                point[p.name] = x >= 0.5
        return point

    def _random_point(self) -> Dict:
        return self._from_unit([self._rng.random() for _ in self.params])

    def _perturb(self, point: Dict) -> Dict:
        """Move one coordinate a small step — local search around the best."""
        out = dict(point)
        p = self._rng.choice(self.params)
        if isinstance(p, IntParam):
            span = max(1, (p.high - p.low) // 8)
            out[p.name] = min(
                p.high, max(p.low, point[p.name] + self._rng.choice([-span, span]))
            )
        elif isinstance(p, FloatParam):
            span = (p.high - p.low) / 8
            v = point[p.name] + self._rng.uniform(-span, span)
            out[p.name] = min(p.high, max(p.low, v))
        else:
            out[p.name] = not point[p.name]
        return out

    # -- tell/ask ---------------------------------------------------------

    def tell(self, point: Dict, score: float) -> None:
        if not (isinstance(score, (int, float)) and math.isfinite(score)):
            return
        self._observations.append((dict(point), float(score)))

    def best(self) -> Optional[Tuple[Dict, float]]:
        if not self._observations:
            return None
        return max(self._observations, key=lambda o: o[1])

    def ask(self) -> Dict:
        self._ask_count += 1
        if self._ask_count <= self.n_initial_points or not self._observations:
            u = [
                _halton(self._ask_count - 1, _PRIMES[i % len(_PRIMES)])
                for i in range(len(self.params))
            ]
            return self._from_unit(u)
        if self._rng.random() < self.explore_prob:
            return self._random_point()
        best_point, _ = self.best()
        return self._perturb(best_point)
