"""Autotune sidecar: HTTP service + client.

Counterpart of /root/reference/bagua/service/autotune_service.py (Flask app
with 4 routes :155-294, warmup/sampling state machine :78-152, AutotuneClient
:302-384).  Flask is not in this image; the service is a stdlib
``ThreadingHTTPServer`` speaking the same JSON protocol on the same paths, so
reference-style clients port over.

State machine per model (as in the reference):
  warmup  — serve the default recommendation, ignore scores, until
            ``warmup_time_s`` after the first ask;
  sampling — every ``sampling_confidence_time_s`` (and only once every rank
            has checked in at the sampled iteration) record the aggregate
            speed as the current point's score, then ask the optimizer for
            the next (bucket_size, hierarchical) point;
  completed — after ``max_samples`` points, pin the best point forever.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib import error, request

from ..define import BaguaHyperparameter, TensorDeclaration
from .autotune_task_manager import AutotuneTaskManager

logger = logging.getLogger(__name__)

API = "/api/v1"


class _TaskState:
    def __init__(self, model_name: str, service: "AutotuneService"):
        self.model_name = model_name
        self.lock = threading.Lock()
        self.manager = AutotuneTaskManager(
            model_name, service.is_output_autotune_log,
            tune_algorithm=service.tune_algorithm,
        )
        self.tensor_list: List[TensorDeclaration] = []
        self.recommended = BaguaHyperparameter(
            bucket_size=service.default_bucket_size
        )
        #: trainer-reported capabilities (mesh tiers, EF/flat legality,
        #: switchable families) — presence selects the v2 knob space
        self.capabilities: Optional[dict] = None
        #: per-rank efficiency observations riding the check-in (windowed
        #: goodput_fraction / mfu / dcn share / hbm headroom; replace
        #: semantics like ``speed_by_rank``) — the v2 scoring input
        self.obs_by_rank: Dict[int, dict] = {}
        #: last HBM headroom per rank, for the shrinking-headroom trend
        #: weighting; one flat-residency prior max per search
        self.hbm_prev: Dict[int, float] = {}
        self.flat_primed = False
        self.first_ask_time: Optional[float] = None
        self.sample_start_time: Optional[float] = None
        self.sample_start_iter = 0
        self.speed_by_rank: Dict[int, float] = {}
        self.iter_by_rank: Dict[int, int] = {}
        self.n_samples = 0
        self.completed = False
        # perf hints from the workers' anomaly detectors (newest last,
        # bounded): environmental slowness context for the scorer — a
        # sample whose window carried a straggler_suspect hint scores the
        # environment, not the hyperparameter point.  The watermark below
        # is the MONOTONIC received count, never len(perf_hints): once the
        # bounded list saturates, its length stops moving exactly when
        # hints are most frequent
        self.perf_hints: List[dict] = []
        self.perf_hints_total = 0
        self.sample_hint_mark = 0
        self.sample_retried = False
        #: scoring mode the task's FIRST scored window established (True =
        #: fleet-min goodput, False = summed speed).  Goodput lives in
        #: [0, 1]; speed is steps/s-scaled — one sample scored on the
        #: other scale would dominate (or vanish under) every honest one
        #: in the optimizer's best(), so a window whose mode disagrees is
        #: re-measured once and then discarded from the tell
        self.goodput_mode: Optional[bool] = None
        # fleet-autopilot controller state (docs/autopilot.md): a pinned
        # algorithm family overrides every recommendation until cleared
        # (the ladder's switch rung must survive later BO points), and
        # extra_samples re-opens a completed search for a bounded retune
        self.pinned_algorithm: Optional[str] = None
        self.extra_samples = 0
        # per-round decision cache: every rank asking at the same train_iter
        # must receive the SAME recommendation, or the ranks' compiled SPMD
        # programs diverge and their collectives deadlock (trainers check in
        # at deterministic iterations, so train_iter identifies the round)
        self.decisions: Dict[int, dict] = {}


class AutotuneService:
    def __init__(
        self,
        world_size: int,
        autotune_level: int = 1,
        max_samples: int = 60,
        sampling_confidence_time_s: float = 5.0,
        warmup_time_s: float = 30.0,
        is_output_autotune_log: bool = False,
        default_bucket_size: int = 10 * 1024 ** 2,
        tune_algorithm: bool = False,
    ):
        self.world_size = world_size
        self.autotune_level = autotune_level
        self.max_samples = max_samples
        self.sampling_confidence_time_s = sampling_confidence_time_s
        self.warmup_time_s = warmup_time_s
        self.is_output_autotune_log = is_output_autotune_log
        self.default_bucket_size = default_bucket_size
        self.tune_algorithm = tune_algorithm
        self._tasks: Dict[str, _TaskState] = {}
        self._tasks_lock = threading.Lock()

    def _task(self, model_name: str) -> _TaskState:
        with self._tasks_lock:
            if model_name not in self._tasks:
                self._tasks[model_name] = _TaskState(model_name, self)
            return self._tasks[model_name]

    # ---- route handlers --------------------------------------------------

    def register_tensors(self, req: dict) -> dict:
        task = self._task(req["model_name"])
        decls = [TensorDeclaration(**t) for t in req["tensor_list"]]
        with task.lock:
            if not task.tensor_list:
                task.tensor_list = decls
                caps = req.get("capabilities")
                if isinstance(caps, dict):
                    task.capabilities = caps
                    # capability-gated v2 knob space: the trainer's mesh /
                    # family / layout legality decides which knobs exist
                    task.manager.configure_space(caps)
                from ..bucket import split_bucket_by_bucket_size

                task.recommended = BaguaHyperparameter(
                    buckets=split_bucket_by_bucket_size(
                        decls, self.default_bucket_size
                    ),
                    bucket_size=self.default_bucket_size,
                )
            return {
                "recommended_hyperparameters": task.recommended.model_dump(),
            }

    def report_metrics(self, req: dict) -> dict:
        task = self._task(req["model_name"])
        rank = int(req["rank"])
        with task.lock:
            if rank >= 0:
                task.speed_by_rank[rank] = float(req["speed"])
                obs = req.get("obs")
                if isinstance(obs, dict):
                    task.obs_by_rank[rank] = obs
                    self._ingest_trends(task, rank, obs)
            # a NEGATIVE rank is a controller (the fleet autopilot, rank
            # -1): its report carries hints only — recording its zero
            # "speed" would poison the ranks' summed score
            for hint in req.get("perf_hints") or []:
                if isinstance(hint, dict):
                    # codec names are validated ONCE here at ingest
                    # (invalid -> stripped with a warning); everything
                    # downstream — the pin path, the prior builder, every
                    # tell iteration — trusts the normalized value
                    hint = self._normalize_hint(task, hint)
                    task.perf_hints.append({**hint, "reported_by": rank})
                    task.perf_hints_total += 1
                    self._apply_controller_hint(task, hint)
            del task.perf_hints[:-64]  # bounded: hints are context, not log
        return {"message": "ok"}

    def _normalize_hint(self, task: _TaskState, hint: dict) -> dict:
        """Validate a hint's codec name exactly once at ingest.  An
        unknown codec is replaced by the empty string — downstream
        consumers skip actuation/priming on it but still honor the hint's
        other semantics (re-measure re-grant)."""
        codec = hint.get("codec")
        if codec is None:
            return hint
        from ..compression.codecs import validate_codec_policy

        try:
            return {**hint, "codec": validate_codec_policy(
                str(codec), "compress_inter")}
        except ValueError as e:
            logger.warning(
                "autotune[%s]: hint %r carried an unknown codec, "
                "stripped at ingest: %s",
                task.model_name, hint.get("kind"), e,
            )
            return {**hint, "codec": ""}

    def _ingest_trends(self, task: _TaskState, rank: int, obs: dict) -> None:
        """Historian-style trend signals riding the check-in become
        COORDINATE WEIGHTS and (once) a warm-start prior for a live v2
        search — never recommendation pins (caller holds ``task.lock``).

        * sustained DCN share of the step -> bias the exploit step toward
          the DCN-tier knobs (``compress_inter``, the inter chunk size);
        * shrinking HBM headroom -> bias toward ``flat_resident`` and
          prime one flat-layout point (the resident layout drops the
          per-step flatten temporaries).
        """
        mgr = task.manager
        if mgr.space is None or task.completed:
            return
        dcn_share = obs.get("dcn_share")
        if isinstance(dcn_share, (int, float)) and dcn_share > 0.15:
            boost = 1.0 + 4.0 * min(1.0, float(dcn_share))
            if mgr.space.has("compress_inter"):
                mgr.weight_coordinate("compress_inter", boost)
            if mgr.space.has("overlap_chunk_bytes_inter_2p"):
                mgr.weight_coordinate(
                    "overlap_chunk_bytes_inter_2p", 1.0 + 2.0 * boost / 5.0
                )
        hbm = obs.get("hbm_headroom_bytes")
        if isinstance(hbm, (int, float)):
            prev = task.hbm_prev.get(rank)
            task.hbm_prev[rank] = float(hbm)
            if (prev is not None and float(hbm) < prev * 0.95
                    and mgr.space.has("flat_resident")):
                mgr.weight_coordinate("flat_resident", 4.0)
                if not task.flat_primed:
                    task.flat_primed = True
                    mgr.prime({"flat_resident": "on"})

    def _apply_controller_hint(self, task: _TaskState, hint: dict) -> None:
        """Fleet-autopilot command hints (caller holds ``task.lock``).
        Ordinary hints (``autopilot_retune_hint``, the anomaly detector's
        ``step_time_anomaly``) need nothing here — arriving inside a
        sampling window already makes the state machine re-measure it.

        * ``autopilot_retune`` — a COMMANDED retune outranks the
          once-per-point re-measure budget (``sample_retried`` resets),
          and re-opens a completed search for a bounded number of extra
          samples: the escalation ladder's "retune" rung must still mean
          something after the BO loop pinned its best point.
        * ``autopilot_switch_family`` — pin the recommended algorithm
          family; every rank applies it at its next check-in through the
          NORMAL recommendation path (``_maybe_switch_algorithm`` — a
          re-jit plus a queued state migration, never a restart), and the
          per-train_iter decision cache keeps the switch SPMD-uniform.
        * ``autopilot_compress_dcn`` — the DCN-dominance trend hint:
          ACTUATE the wire-byte reduction by setting the recommended
          ``compress_inter`` codec policy — every rank applies it at its
          next check-in through the normal recommendation path (a re-jit
          with compressed cross-slice ring hops, kept SPMD-uniform by the
          per-train_iter decision cache) — and re-grant the once-per-point
          re-measure (the dominance evidence taints the current window's
          score).  The FAMILY named by the hint stays a suggestion (the
          BO loop keeps the last word on a family switch), but the codec
          flip is live: hierarchical collectives of the current family
          start carrying compressed DCN bytes without one.
        """
        kind = hint.get("kind")
        # a LIVE v2 search treats autopilot commands as priors, not pins:
        # the hint decides where the optimizer looks next (a primed point
        # plus coordinate weighting), the measured goodput decides whether
        # it sticks.  Legacy tasks and completed searches keep the direct
        # actuation — there is no live loop to absorb a prior.
        v2_live = task.manager.space is not None and not task.completed
        if kind == "autopilot_compress_dcn":
            task.sample_retried = False
            # codec was validated at ingest ("" = stripped as unknown)
            codec = str(hint.get("codec", "minmax_uint8"))
            if not codec:
                logger.warning(
                    "autotune[%s]: compress_dcn hint had no valid codec, "
                    "NOT actuated (re-measure still re-granted)",
                    task.model_name,
                )
            elif v2_live and task.manager.space.has("compress_inter"):
                task.manager.prime({
                    "compress_inter": codec,
                    "is_hierarchical_reduce": True,
                })
                share = hint.get("dcn_share")
                boost = (
                    2.0 + 6.0 * min(1.0, float(share))
                    if isinstance(share, (int, float)) else 4.0
                )
                task.manager.weight_coordinate("compress_inter", boost)
                logger.info(
                    "autotune[%s]: autopilot reports sustained DCN "
                    "dominance; primed DCN codec %r as a search prior "
                    "(goodput keeps the last word; re-measure re-granted)",
                    task.model_name, codec,
                )
            else:
                task.recommended.compress_inter = codec
                logger.info(
                    "autotune[%s]: autopilot reports sustained DCN "
                    "dominance; actuating DCN codec %r (suggested "
                    "compression family %r, re-measure re-granted)",
                    task.model_name, codec, hint.get("family"),
                )
        elif kind == "autopilot_retune":
            task.sample_retried = False
            if task.completed and task.extra_samples < 16:
                task.extra_samples += 4
                task.completed = False
                logger.info(
                    "autotune[%s]: autopilot retune re-opened the search "
                    "(+4 samples, %d extra total)", task.model_name,
                    task.extra_samples,
                )
        elif kind == "autopilot_switch_family":
            family = hint.get("family")
            if family and v2_live and task.manager.space.has("algorithm"):
                task.manager.prime({"algorithm": str(family)})
                task.manager.weight_coordinate("algorithm", 4.0)
                logger.info(
                    "autotune[%s]: autopilot suggested family %r; primed "
                    "as a search prior (goodput keeps the last word)",
                    task.model_name, family,
                )
            elif family:
                task.pinned_algorithm = str(family)
                task.recommended.algorithm = str(family)
                logger.info(
                    "autotune[%s]: autopilot pinned algorithm family %r",
                    task.model_name, family,
                )

    def report_tensor_execution_order(self, req: dict) -> dict:
        spans = req.get("spans", [])
        ordered = [
            s["tensor_name"]
            for s in sorted(spans, key=lambda s: s.get("start_time", 0))
            if s.get("tensor_name")
        ]
        task = self._task(req["model_name"]) if "model_name" in req else None
        if task is None:
            # reference route carries no model name; apply to every task
            with self._tasks_lock:
                tasks = list(self._tasks.values())
        else:
            tasks = [task]
        for t in tasks:
            with t.lock:
                t.manager.report_tensor_execution_order(ordered)
        return {"message": "ok"}

    def ask_hyperparameters(self, req: dict) -> dict:
        task = self._task(req["model_name"])
        rank = int(req["rank"])
        train_iter = int(req["train_iter"])
        now = time.time()
        with task.lock:
            task.iter_by_rank[rank] = train_iter
            if train_iter in task.decisions:
                return task.decisions[train_iter]
            reply = self._decide(task, train_iter, now)
            task.decisions[train_iter] = reply
            for it in sorted(task.decisions)[:-8]:  # bound the cache
                del task.decisions[it]
            return reply

    def _decide(self, task: _TaskState, train_iter: int, now: float) -> dict:
        """Compute the round's reply; caller holds ``task.lock``."""
        if task.first_ask_time is None:
            task.first_ask_time = now
            task.sample_start_time = now
        if self.autotune_level < 1 or task.completed:
            return self._reply(task)
        if now - task.first_ask_time < self.warmup_time_s:
            # hints landing during warmup describe windows that were never
            # going to be scored — absorb them, or the first real sampling
            # window would always burn its one re-measure on stale noise
            task.sample_hint_mark = task.perf_hints_total
            return self._reply(task)
        # confidence gate: the current point must have run long enough AND
        # every rank must have checked in past the point's start iteration,
        # so the summed speed reflects only the current config
        long_enough = (
            now - task.sample_start_time >= self.sampling_confidence_time_s
        )
        all_ranks_in = len(task.iter_by_rank) >= self.world_size and all(
            it > task.sample_start_iter for it in task.iter_by_rank.values()
        )
        if not (all_ranks_in and long_enough):
            return self._reply(task)
        # an anomaly-flagged window (rank-local detector flag riding the
        # obs payload) is discarded like a hint-tainted one: re-measure
        # once before scoring, then score honestly
        anomaly_flagged = any(
            bool(o.get("anomaly")) for o in task.obs_by_rank.values()
        )
        if (task.perf_hints_total > task.sample_hint_mark or anomaly_flagged) \
                and not task.sample_retried:
            # the window carried anomaly hints (a straggler, an injected
            # stall): its speed measures the environment, not the point —
            # re-measure once before scoring.  One retry only, so a
            # chronically noisy fleet still makes progress (the score is
            # then honest about its environment).
            logger.info(
                "autotune[%s]: %d perf hint(s) during the sample window — "
                "re-measuring this point before scoring",
                task.model_name,
                task.perf_hints_total - task.sample_hint_mark,
            )
            task.sample_hint_mark = task.perf_hints_total
            task.sample_retried = True
            task.sample_start_time = now
            task.sample_start_iter = train_iter
            return self._reply(task)
        score, scored_on_goodput = self._score(task)
        if task.goodput_mode is None:
            task.goodput_mode = scored_on_goodput
        usable = scored_on_goodput == task.goodput_mode
        if not usable and not task.sample_retried:
            # scale guard: the window's scoring mode disagrees with the
            # task's established one (obs coverage appeared or vanished
            # mid-search) — re-measure once before giving up on it
            logger.info(
                "autotune[%s]: window scored on %s but the search runs on "
                "%s — re-measuring before scoring",
                task.model_name,
                "goodput" if scored_on_goodput else "speed",
                "goodput" if task.goodput_mode else "speed",
            )
            task.sample_retried = True
            task.sample_start_time = now
            task.sample_start_iter = train_iter
            return self._reply(task)
        if usable:
            task.manager.record_sample(train_iter, task.recommended, score)
        else:
            logger.warning(
                "autotune[%s]: window still scored on the wrong scale "
                "after a re-measure — sample spent, observation discarded",
                task.model_name,
            )
        next_hp = task.manager.ask_hyperparameters(
            train_iter, task.tensor_list, task.recommended,
            score if usable else None,
        )
        task.n_samples += 1
        if task.n_samples >= self.max_samples + task.extra_samples:
            best = task.manager.best_hyperparameters(task.tensor_list)
            task.recommended = best if best is not None else task.recommended
            task.completed = True
            task.manager.close()
            logger.info(
                "autotune[%s] completed after %d samples (scored on %s): "
                "bucket=%d hier=%s algo=%s",
                task.model_name, task.n_samples,
                "fleet-min goodput" if scored_on_goodput else "summed speed",
                task.recommended.bucket_size,
                task.recommended.is_hierarchical_reduce,
                task.recommended.algorithm or "-",
            )
        else:
            task.recommended = next_hp
        task.sample_start_time = now
        task.sample_start_iter = train_iter
        task.sample_hint_mark = task.perf_hints_total
        task.sample_retried = False
        return self._reply(task)

    def _score(self, task: _TaskState) -> "tuple[float, bool]":
        """The sampling window's score (caller holds ``task.lock``).

        With every reporting rank carrying a goodput observation, the
        score is FLEET-MIN GOODPUT — the fleet is only as productive as
        its least productive rank (a config that compiles fast on seven
        ranks and churns on the eighth is a bad config) — with summed
        speed as a bounded tiebreak (< 1e-4, so it can never outvote a
        real goodput difference).  Compile churn is charged naturally:
        every re-jit the config causes lands in its own window's badput.
        Without full goodput coverage (obs plane off, old trainers) the
        legacy summed-speed score stands.
        """
        speed_sum = sum(task.speed_by_rank.values())
        goodputs = [
            o.get("goodput_fraction") for o in task.obs_by_rank.values()
        ]
        if (
            goodputs
            and len(task.obs_by_rank) >= len(task.speed_by_rank)
            and len(task.speed_by_rank) >= self.world_size
            and all(isinstance(g, (int, float)) for g in goodputs)
        ):
            tiebreak = 1e-4 * speed_sum / (1.0 + speed_sum)
            return min(float(g) for g in goodputs) + tiebreak, True
        return speed_sum, False

    def _reply(self, task: _TaskState) -> dict:
        if task.pinned_algorithm:
            # the autopilot's pin survives BO points and completion: every
            # reply carries it until a new pin replaces it
            task.recommended.algorithm = task.pinned_algorithm
        return {
            "recommended_hyperparameters": task.recommended.model_dump(),
            "is_autotune_completed": task.completed,
        }

    def health(self, req: dict) -> dict:
        return {"status": "ok"}


class _Handler(BaseHTTPRequestHandler):
    service: AutotuneService = None  # set by run_autotune_server

    ROUTES = {
        f"{API}/register_tensors": "register_tensors",
        f"{API}/report_metrics": "report_metrics",
        f"{API}/ask_hyperparameters": "ask_hyperparameters",
        f"{API}/report_tensor_execution_order": "report_tensor_execution_order",
        f"{API}/health": "health",
    }

    def log_message(self, fmt, *args):  # quiet
        logger.debug("autotune http: " + fmt, *args)

    def _respond(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == f"{API}/health":
            return self._respond(200, {"status": "ok"})
        self._respond(404, {"error": "not found"})

    def do_POST(self):
        handler_name = self.ROUTES.get(self.path)
        if handler_name is None:
            return self._respond(404, {"error": "not found"})
        length = int(self.headers.get("Content-Length", 0))
        try:
            req = json.loads(self.rfile.read(length) or b"{}")
            rsp = getattr(self.service, handler_name)(req)
            self._respond(200, rsp)
        except Exception as e:  # noqa: BLE001
            logger.exception("autotune route %s failed", self.path)
            self._respond(500, {"error": str(e)})


def make_server(port: int, service: AutotuneService) -> ThreadingHTTPServer:
    handler = type("BoundHandler", (_Handler,), {"service": service})
    return ThreadingHTTPServer(("0.0.0.0", port), handler)


def run_autotune_server(
    port: int,
    world_size: int,
    autotune_level: int = 1,
    max_samples: int = 60,
    sampling_confidence_time_s: float = 5.0,
    warmup_time_s: float = 30.0,
    is_output_autotune_log: bool = False,
    default_bucket_size: int = 10 * 1024 ** 2,
    tune_algorithm: bool = False,
) -> None:
    """Blocking server entry (run in a daemon process by
    :func:`bagua_tpu.communication.start_autotune_server`)."""
    service = AutotuneService(
        world_size=world_size,
        autotune_level=autotune_level,
        max_samples=max_samples,
        sampling_confidence_time_s=sampling_confidence_time_s,
        warmup_time_s=warmup_time_s,
        is_output_autotune_log=is_output_autotune_log,
        default_bucket_size=default_bucket_size,
        tune_algorithm=tune_algorithm,
    )
    server = make_server(port, service)
    logger.info("autotune service listening on :%d", port)
    server.serve_forever()


class AutotuneClient:
    """HTTP client (reference autotune_service.py:302-384) on stdlib urllib."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 retries: int = 3):
        self.base = f"http://{host}:{port}{API}"
        self.timeout_s = timeout_s
        self.retries = retries

    def _post(self, route: str, payload: dict) -> dict:
        data = json.dumps(payload).encode()
        last_err = None
        for attempt in range(self.retries):
            try:
                req = request.Request(
                    f"{self.base}/{route}", data=data,
                    headers={"Content-Type": "application/json"},
                )
                with request.urlopen(req, timeout=self.timeout_s) as rsp:
                    return json.loads(rsp.read())
            except (error.URLError, OSError) as e:
                last_err = e
                time.sleep(0.2 * (attempt + 1))
        raise ConnectionError(f"autotune service unreachable: {last_err}")

    def health(self) -> bool:
        try:
            with request.urlopen(f"{self.base}/health", timeout=self.timeout_s):
                return True
        except (error.URLError, OSError):
            return False

    def wait_until_ready(self, timeout_s: float = 30.0) -> None:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.health():
                return
            time.sleep(0.1)
        raise TimeoutError("autotune service did not come up")

    def register_tensors(
        self, model_name: str, tensor_list: List[dict],
        capabilities: Optional[dict] = None,
    ) -> dict:
        payload = {"model_name": model_name, "tensor_list": tensor_list}
        if capabilities:
            # v2: what the trainer's mesh/family/layout makes legal —
            # selects the capability-gated knob space service-side
            payload["capabilities"] = capabilities
        return self._post("register_tensors", payload)

    def report_metrics(
        self, model_name: str, rank: int, train_iter: int,
        hyperparameters: dict, speed: float,
        perf_hints: Optional[List[dict]] = None,
        obs: Optional[dict] = None,
    ) -> dict:
        payload = {
            "model_name": model_name, "rank": rank,
            "train_iter": train_iter,
            "hyperparameters": hyperparameters, "speed": speed,
        }
        if perf_hints:
            # anomaly-detector hints (bagua_tpu.obs.anomaly): the sampling
            # state machine re-measures a window these taint
            payload["perf_hints"] = perf_hints
        if obs:
            # windowed efficiency observations (goodput_fraction, mfu,
            # dcn share, hbm headroom): the v2 scoring + trend input
            payload["obs"] = obs
        return self._post("report_metrics", payload)

    def ask_hyperparameters(self, model_name: str, rank: int, train_iter: int) -> dict:
        return self._post(
            "ask_hyperparameters",
            {"model_name": model_name, "rank": rank, "train_iter": train_iter},
        )

    def report_tensor_execution_order(
        self, spans: List[dict], model_name: Optional[str] = None
    ) -> dict:
        payload = {"spans": spans}
        if model_name is not None:
            payload["model_name"] = model_name
        return self._post("report_tensor_execution_order", payload)
