"""Per-model autotune task manager.

Counterpart of /root/reference/bagua/service/autotune_task_manager.py:21-185:
keeps the (train_iter, hyperparameters, speed) sample history, re-orders the
tensor list by the observed execution partial order, asks the optimizer for
the next (bucket_size, is_hierarchical_reduce) point, and materializes it into
concrete buckets via :func:`split_bucket_by_bucket_size`.

The search dimension gains one TPU-specific axis over the reference: the
algorithm *family* is part of the tunable space when ``tune_algorithm`` is on
(BASELINE.json requires the centralized / decentralized / low-precision
families to be selectable by the autotuner).

Autotune v2 (ISSUE 19): when the trainer reports capabilities at tensor
registration, :meth:`AutotuneTaskManager.configure_space` swaps the legacy
two-knob space for the full capability-gated knob space
(:mod:`.knob_space`) — overlap + per-tier chunk bytes, the codec ladder,
flat residency, and family switching — with conditional sampling so
inactive knobs never burn samples.  Tasks without capabilities keep the
legacy space and materialization byte-for-byte.
"""

from __future__ import annotations

import csv
import logging
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..bucket import split_bucket_by_bucket_size
from ..define import BaguaHyperparameter, TensorDeclaration
from .bayesian_optimizer import BayesianOptimizer, BoolParam, IntParam
from .knob_space import KnobSpace, build_knob_space

logger = logging.getLogger(__name__)

MIN_BUCKET_SIZE_EXP = 10   # 1 KiB
MAX_BUCKET_SIZE_EXP = 31   # 2 GiB   (reference: 2^10 .. 2^31)

# Only families the trainer can hot-swap mid-training — the stateless
# replicated pair plus QAdam, whose param-shaped momenta ride the trainer's
# state-migration adapter (see algorithms.SWITCHABLE_ALGORITHMS).  Gossip and
# sharded-opt-state families change the TrainState layout irreversibly, so
# recommending them would record scores against configs the trainer silently
# cannot apply.
ALGORITHM_FAMILIES = ["gradient_allreduce", "bytegrad", "qadam"]


class AutotuneTaskManager:
    def __init__(
        self,
        task_name: str,
        is_output_autotune_log: bool,
        tune_algorithm: bool = False,
        log_path: Optional[str] = None,
    ):
        self.task_name = task_name
        params = [
            IntParam("bucket_size_2p", MIN_BUCKET_SIZE_EXP, MAX_BUCKET_SIZE_EXP),
            BoolParam("is_hierarchical_reduce"),
        ]
        if tune_algorithm:
            params.append(IntParam("algorithm_index", 0, len(ALGORITHM_FAMILIES) - 1))
        self.tune_algorithm = tune_algorithm
        self.optimizer = BayesianOptimizer(params)
        #: v2 knob space (None = legacy two-knob space); set once via
        #: :meth:`configure_space` from the task's registration capabilities
        self.space: Optional[KnobSpace] = None
        # sample history: (train_iter, hyperparameters, score)
        self.records: Deque[Tuple[int, BaguaHyperparameter, float]] = deque(maxlen=100)
        self.tensor_partial_order: Dict[str, int] = {}
        self._log_writer = None
        if is_output_autotune_log:
            path = log_path or f"/tmp/bagua_autotune_{task_name}_{int(time.time())}.csv"
            f = open(path, "a", newline="")
            self._log_writer = csv.writer(f)
            self._log_writer.writerow(
                ["train_iter", "bucket_size", "is_hierarchical_reduce", "score"]
            )
            self._log_file = f
            logger.info("autotune log -> %s", path)

    def configure_space(self, capabilities: Optional[Dict]) -> None:
        """Swap in the capability-gated v2 knob space (idempotent; no-op
        for legacy/absent capabilities or once sampling has begun — a
        mid-search space change would orphan every observation)."""
        if self.space is not None or self.records:
            return
        space = build_knob_space(capabilities, self.tune_algorithm)
        if space is None:
            return
        self.space = space
        self.optimizer = BayesianOptimizer(
            space.params, conditions=space.conditions
        )
        logger.info(
            "autotune[%s]: v2 knob space active (%s)",
            self.task_name, ", ".join(space.names()),
        )

    def prime(self, updates: Dict) -> None:
        """Warm-start prior from an autopilot hint / historian trend:
        queue a point near the current best with ``updates`` applied
        (hyperparameter-field names == v2 param names)."""
        self.optimizer.prime(updates)

    def weight_coordinate(self, name: str, w: float) -> None:
        """Bias the exploit step toward one coordinate (trend weighting)."""
        self.optimizer.weight(name, w)

    def record_sample(
        self, train_iter: int, hp: BaguaHyperparameter, score: float
    ) -> None:
        self.records.append((train_iter, hp, score))
        if self._log_writer:
            self._log_writer.writerow(
                [train_iter, hp.bucket_size, hp.is_hierarchical_reduce, score]
            )
            self._log_file.flush()

    def report_tensor_execution_order(self, ordered_names: List[str]) -> None:
        """Record the observed grad-ready order; buckets are rebuilt in this
        order so the head-of-ring fills first (reference
        autotune_task_manager.py:167-172 re-sorts by telemetry)."""
        for i, name in enumerate(ordered_names):
            self.tensor_partial_order[name] = i

    def _order_tensors(
        self, tensor_list: List[TensorDeclaration]
    ) -> List[TensorDeclaration]:
        if not self.tensor_partial_order:
            return list(tensor_list)
        n = len(self.tensor_partial_order)
        return sorted(
            tensor_list,
            key=lambda t: self.tensor_partial_order.get(t.name, n),
        )

    def ask_hyperparameters(
        self,
        train_iter: int,
        tensor_list: List[TensorDeclaration],
        last_hp: BaguaHyperparameter,
        last_score: Optional[float],
    ) -> BaguaHyperparameter:
        """tell the last sample's score, ask the next point, materialize it."""
        if self.space is not None:
            if last_score is not None:
                self.optimizer.tell(
                    self.space.point_from_hp(last_hp), last_score
                )
            return self._materialize(self.optimizer.ask(), tensor_list, last_hp)
        if last_score is not None:
            point = {
                "bucket_size_2p": max(last_hp.bucket_size, 1).bit_length() - 1,
                "is_hierarchical_reduce": bool(last_hp.is_hierarchical_reduce),
            }
            if self.tune_algorithm:
                algo = last_hp.algorithm or ALGORITHM_FAMILIES[0]
                point["algorithm_index"] = (
                    ALGORITHM_FAMILIES.index(algo)
                    if algo in ALGORITHM_FAMILIES else 0
                )
            self.optimizer.tell(point, last_score)
        nxt = self.optimizer.ask()
        return self._materialize(nxt, tensor_list, last_hp)

    def _materialize(
        self, point: Dict, tensor_list: List[TensorDeclaration],
        last_hp: Optional[BaguaHyperparameter] = None,
    ) -> BaguaHyperparameter:
        bucket_size = 2 ** point["bucket_size_2p"]
        ordered = self._order_tensors(tensor_list)
        if self.space is not None:
            # v2: searched knobs come from the point (inactive ones emit
            # their keep-current sentinel), unsearched knobs carry through
            hp = BaguaHyperparameter(
                buckets=split_bucket_by_bucket_size(ordered, bucket_size),
                bucket_size=bucket_size,
                overlap_chunk_bytes=(
                    last_hp.overlap_chunk_bytes if last_hp is not None else 0
                ),
            )
            if last_hp is not None:
                for fld in ("is_hierarchical_reduce", "overlap",
                            "overlap_chunk_bytes_intra",
                            "overlap_chunk_bytes_inter",
                            "compress_intra", "compress_inter",
                            "flat_resident"):
                    setattr(hp, fld, getattr(last_hp, fld))
            hp.update(self.space.point_to_updates(point))
            return hp
        return BaguaHyperparameter(
            buckets=split_bucket_by_bucket_size(ordered, bucket_size),
            bucket_size=bucket_size,
            is_hierarchical_reduce=bool(point["is_hierarchical_reduce"]),
            algorithm=(
                ALGORITHM_FAMILIES[point["algorithm_index"]]
                if self.tune_algorithm else ""
            ),
            # overlap knobs are carried through, not searched: the trainer's
            # reported values survive re-bucketing recommendations ("" / 0
            # means "keep current" on the trainer side either way)
            overlap=(last_hp.overlap if last_hp is not None else ""),
            overlap_chunk_bytes=(
                last_hp.overlap_chunk_bytes if last_hp is not None else 0
            ),
            overlap_chunk_bytes_intra=(
                last_hp.overlap_chunk_bytes_intra if last_hp is not None else 0
            ),
            overlap_chunk_bytes_inter=(
                last_hp.overlap_chunk_bytes_inter if last_hp is not None else 0
            ),
            # the codec policy is carried through like the overlap knobs —
            # the autopilot's actuated compress_inter must survive every
            # later re-bucketing recommendation
            compress_intra=(
                last_hp.compress_intra if last_hp is not None else ""
            ),
            compress_inter=(
                last_hp.compress_inter if last_hp is not None else ""
            ),
        )

    def best_hyperparameters(
        self, tensor_list: List[TensorDeclaration]
    ) -> Optional[BaguaHyperparameter]:
        best = self.optimizer.best()
        if best is None:
            return None
        point, _ = best
        return self._materialize(point, tensor_list)

    def close(self) -> None:
        if self._log_writer is not None:
            self._log_file.close()
            self._log_writer = None
