"""Cluster-level system probe: run a perf microbenchmark on every host.

Counterpart of /root/reference/bagua/service/autotune_system.py:16+
(``sysperf``: parallel-ssh to all hosts, each running the ``bagua_sys_perf``
VGG16 probe, collecting per-host throughput to spot slow nodes before a
training run).  Here the probe is the collective microbenchmark
(benchmarks/collective_bench.py) or ``bench.py``, over plain ssh
subprocesses (``--ssh_cmd`` shim-able, as in ``baguarun``).

    bagua-tpu-sysperf --host_list 10.0.0.1,10.0.0.2
    -> one JSON line per host: {"host", "ok", "records" | "error"}
    exit code 1 when any host underperforms the fleet median by
    ``--straggler_pct`` or fails.
"""

from __future__ import annotations

import argparse
import json
import logging
import shlex
import statistics
import subprocess
import sys
from typing import Dict, List

logger = logging.getLogger("bagua_tpu.sysperf")

PROBES = {
    "collective": "benchmarks/collective_bench.py --sizes-mb 4",
    "train": "bench.py",
}


def parse_args(argv=None):
    p = argparse.ArgumentParser("bagua-tpu-sysperf")
    p.add_argument("--host_list", type=str, required=True)
    p.add_argument("--ssh_port", type=int, default=22)
    p.add_argument("--ssh_cmd", type=str, default="ssh -p {port} {host}")
    p.add_argument("--probe", choices=sorted(PROBES), default="collective")
    p.add_argument("--python", type=str, default="python")
    p.add_argument("--cwd", type=str, default=None)
    p.add_argument("--timeout_s", type=float, default=1800)
    p.add_argument("--straggler_pct", type=float, default=20.0,
                   help="flag hosts slower than median by this percent")
    return p.parse_args(argv)


def probe_host(args, host: str) -> Dict:
    ssh = shlex.split(args.ssh_cmd.format(port=args.ssh_port, host=host))
    cmd = f"{args.python} {PROBES[args.probe]}"
    if args.cwd:
        cmd = f"cd {shlex.quote(args.cwd)} && {cmd}"
    try:
        out = subprocess.run(
            ssh + [cmd], capture_output=True, text=True,
            timeout=args.timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {"host": host, "ok": False, "error": "timeout"}
    if out.returncode != 0:
        return {"host": host, "ok": False,
                "error": (out.stderr or out.stdout)[-500:]}
    records = []
    for line in out.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return {"host": host, "ok": bool(records), "records": records}


def _score(result: Dict) -> float:
    """One comparable throughput number per host."""
    vals = [
        r.get("busbw_GBps") or r.get("value") or 0.0
        for r in result.get("records", [])
    ]
    return float(max(vals)) if vals else 0.0


def sysperf(args) -> int:
    from concurrent.futures import ThreadPoolExecutor

    hosts = [h.strip() for h in args.host_list.split(",") if h.strip()]
    if not hosts:
        return 0
    # probe all hosts concurrently (the reference fans out with parallel-ssh;
    # serial probing would serialize per-host timeouts on a hung fleet)
    with ThreadPoolExecutor(max_workers=min(len(hosts), 64)) as pool:
        results = list(pool.map(lambda h: probe_host(args, h), hosts))
    scores = {r["host"]: _score(r) for r in results if r["ok"]}
    median = statistics.median(scores.values()) if scores else 0.0
    rc = 0
    for r in results:
        if not r["ok"]:
            r["straggler"] = True
            rc = 1
        else:
            s = scores[r["host"]]
            r["score"] = s
            r["straggler"] = (
                median > 0 and s < median * (1 - args.straggler_pct / 100.0)
            )
            if r["straggler"]:
                rc = 1
        print(json.dumps(r), flush=True)
    if rc:
        logger.error("stragglers or failures detected (median score %.2f)",
                     median)
    return rc


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO)
    return sysperf(parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
