"""jax version-compatibility shims.

The package is written against the modern surface (``jax.shard_map`` with
``check_vma=``); older runtimes only ship ``jax.experimental.shard_map``
whose flag is ``check_rep=``.  Importing through here keeps every call site
on the modern spelling.
"""

from __future__ import annotations

try:  # jax >= 0.6: top-level export, check_vma flag
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental home, check_rep flag
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
