"""Profiler integration — the TPU-native tracing subsystem.

SURVEY.md §5.1: the reference's OTel span pipeline exists to recover the
tensor execution order for the autotuner (covered here by
:mod:`bagua_tpu.telemetry`); its *profiling* role — seeing where step time
goes — maps to ``jax.profiler`` traces, which capture XLA op timelines,
collective costs on ICI, and host callstacks viewable in TensorBoard /
Perfetto.

Two entry points:

- :func:`trace`: context manager around any region.
- trainer auto-capture: set ``BAGUA_PROFILE_DIR=/path`` (and optionally
  ``BAGUA_PROFILE_STEPS=start:stop``, default ``2:5`` — skip compile
  steps, keep the trace small).  ``BaguaTrainer.train_step`` starts/stops
  the trace at those step numbers; no code changes in the training script.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Optional, Tuple

logger = logging.getLogger(__name__)


def profile_dir() -> Optional[str]:
    return os.environ.get("BAGUA_PROFILE_DIR") or None


def profile_steps() -> Tuple[int, int]:
    """[start, stop) step window for trainer auto-capture."""
    raw = os.environ.get("BAGUA_PROFILE_STEPS", "2:5")
    try:
        start, stop = raw.split(":")
        return int(start), int(stop)
    except ValueError:
        logger.warning("BAGUA_PROFILE_STEPS=%r is not start:stop; using 2:5",
                       raw)
        return 2, 5


# jax allows only one profile at a time; track the owner (a StepProfiler or
# the trace() context manager) so the other entry point skips its turn
# instead of crashing
_TRACE_OWNER: Optional[object] = None


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a ``jax.profiler`` trace of the enclosed region.

    If a trace is already running (e.g. trainer auto-capture via
    ``BAGUA_PROFILE_DIR`` has its step window open), the region runs
    untraced with a warning — jax allows only one profile at a time."""
    global _TRACE_OWNER
    import jax

    if _TRACE_OWNER is not None:
        logger.warning(
            "profiling.trace(%s): another trace is active; running untraced",
            log_dir,
        )
        yield
        return
    token = object()
    jax.profiler.start_trace(log_dir)
    _TRACE_OWNER = token  # only own it once start_trace succeeded
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            if _TRACE_OWNER is token:
                _TRACE_OWNER = None


class StepProfiler:
    """Start/stop a trace across a step-number window (trainer hook).

    Registered with ``atexit`` so a run that ends before the stop step
    still flushes its trace instead of silently losing it.
    """

    def __init__(self, log_dir: str, start: int, stop: int):
        self.log_dir = log_dir
        self.start = start
        self.stop = stop
        self._active = False
        self._done = False

    @classmethod
    def from_env(cls) -> Optional["StepProfiler"]:
        d = profile_dir()
        if not d:
            return None
        start, stop = profile_steps()
        prof = cls(d, start, stop)
        import atexit

        atexit.register(prof.close)
        return prof

    def on_step(self, step: int) -> None:
        """Call once per train step BEFORE dispatching it."""
        global _TRACE_OWNER
        import jax

        if self._done:
            return
        if not self._active and step >= self.start:
            if _TRACE_OWNER is not None:
                # another trainer's window is still open — skip rather
                # than crash on jax's one-profile-at-a-time limit
                return
            jax.profiler.start_trace(self.log_dir)
            _TRACE_OWNER = self
            self._active = True
            logger.info("profiler: tracing steps [%d, %d) -> %s",
                        self.start, self.stop, self.log_dir)
        elif self._active and step >= self.stop:
            self.close()

    def close(self) -> None:
        global _TRACE_OWNER
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            if _TRACE_OWNER is self:
                _TRACE_OWNER = None
            logger.info("profiler: trace written to %s", self.log_dir)
