"""Profiler integration — the TPU-native tracing subsystem.

SURVEY.md §5.1: the reference's OTel span pipeline exists to recover the
tensor execution order for the autotuner (covered here by
:mod:`bagua_tpu.telemetry`); its *profiling* role — seeing where step time
goes — maps to ``jax.profiler`` traces, which capture XLA op timelines,
collective costs on ICI, and host callstacks viewable in TensorBoard /
Perfetto.

Two entry points:

- :func:`trace`: context manager around any region.
- trainer auto-capture: set ``BAGUA_PROFILE_DIR=/path`` (and optionally
  ``BAGUA_PROFILE_STEPS=start:stop``, default ``2:5`` — skip compile
  steps, keep the trace small).  ``BaguaTrainer.train_step`` starts/stops
  the trace at those step numbers; no code changes in the training script.
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Optional, Tuple

logger = logging.getLogger(__name__)


def profile_dir() -> Optional[str]:
    from . import env

    return env.get_profile_dir()


def profile_steps() -> Tuple[int, int]:
    """[start, stop) step window for trainer auto-capture."""
    from . import env

    raw = env.get_profile_steps_raw()
    try:
        start, stop = raw.split(":")
        return int(start), int(stop)
    except ValueError:
        logger.warning("BAGUA_PROFILE_STEPS=%r is not start:stop; using 2:5",
                       raw)
        return 2, 5


# jax allows only one profile at a time; track the owner (a StepProfiler or
# the trace() context manager) so the other entry point skips its turn
# instead of crashing
_TRACE_OWNER: Optional[object] = None


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a ``jax.profiler`` trace of the enclosed region.

    If a trace is already running (e.g. trainer auto-capture via
    ``BAGUA_PROFILE_DIR`` has its step window open), the region runs
    untraced with a warning — jax allows only one profile at a time."""
    global _TRACE_OWNER
    import jax

    if _TRACE_OWNER is not None:
        logger.warning(
            "profiling.trace(%s): another trace is active; running untraced",
            log_dir,
        )
        yield
        return
    token = object()
    jax.profiler.start_trace(log_dir)
    _TRACE_OWNER = token  # only own it once start_trace succeeded
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            if _TRACE_OWNER is token:
                _TRACE_OWNER = None


class StepProfiler:
    """Start/stop a trace across a step-number window (trainer hook).

    Registered with ``atexit`` so a run that ends before the stop step
    still flushes its trace instead of silently losing it.
    """

    def __init__(self, log_dir: str, start: int, stop: int):
        self.log_dir = log_dir
        self.start = start
        self.stop = stop
        self._active = False
        self._done = False
        self._closed_dir: Optional[str] = None

    @classmethod
    def from_env(cls) -> Optional["StepProfiler"]:
        d = profile_dir()
        if not d:
            return None
        start, stop = profile_steps()
        prof = cls(d, start, stop)
        import atexit

        atexit.register(prof.close)
        return prof

    def on_step(self, step: int) -> None:
        """Call once per train step BEFORE dispatching it."""
        global _TRACE_OWNER
        import jax

        if self._done:
            return
        if not self._active and step >= self.start:
            if _TRACE_OWNER is not None:
                # another trainer's window is still open — skip rather
                # than crash on jax's one-profile-at-a-time limit
                return
            jax.profiler.start_trace(self.log_dir)
            _TRACE_OWNER = self
            self._active = True
            logger.info("profiler: tracing steps [%d, %d) -> %s",
                        self.start, self.stop, self.log_dir)
        elif self._active and step >= self.stop:
            self.close()

    def close(self) -> None:
        global _TRACE_OWNER
        if self._active:
            import jax

            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            self._closed_dir = self.log_dir
            if _TRACE_OWNER is self:
                _TRACE_OWNER = None
            logger.info("profiler: trace written to %s", self.log_dir)

    def consume_closed_dir(self) -> Optional[str]:
        """The log dir of a JUST-closed trace window, once (None after the
        first read, and until another window closes) — the trainer's hook
        for post-trace analysis like device-time attribution."""
        d, self._closed_dir = self._closed_dir, None
        return d


# ---------------------------------------------------------------------------
# Measured memory traffic (the reference measures GB/s with paired CUDA
# events, distributed.py:340-358; on TPU the ground truth is the profiler's
# per-op memory_access_breakdown, which separates HBM from on-chip VMEM/CMEM
# traffic — XLA's cost model "bytes accessed" conflates them, which is why
# cost-model hbm_util can read >1.0)
# ---------------------------------------------------------------------------

def _newest_xplane(log_dir: str) -> Optional[str]:
    """The most recently WRITTEN ``*.xplane.pb`` under ``log_dir``.

    jax names trace files by host+timestamp; a plain ``sorted(...)[-1]``
    picks the lexicographically last one, which is not the newest once a
    directory holds traces from more than one capture (different hosts, or
    timestamp formats that don't sort) — order by mtime instead."""
    import glob

    files = glob.glob(log_dir + "/**/*.xplane.pb", recursive=True)
    if not files:
        return None
    return max(files, key=lambda p: (os.path.getmtime(p), p))


def _load_xspace(xplane_path: str):
    """Parse one serialized ``XSpace`` proto — the load boilerplate every
    xplane parser shares."""
    from tensorflow.tsl.profiler.protobuf import xplane_pb2  # noqa: PLC0415

    xs = xplane_pb2.XSpace()
    with open(xplane_path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _first_tpu_plane(xs):
    return next(
        (p for p in xs.planes if p.name.startswith("/device:TPU")), None
    )

def trace_memory_traffic(run_step, steps: int = 5, log_dir=None,
                         finalize=None) -> dict:
    """Run ``run_step()`` ``steps`` times under a ``jax.profiler`` trace and
    parse the TPU xplane for MEASURED per-memory-space traffic.

    Returns ``{}`` off-TPU or when the trace lacks a device plane; otherwise::

        {"step_s": mean device step seconds (trace Steps line),
         "hbm_gb_per_step": ..., "vmem_gb_per_step": ..., "cmem_gb_per_step": ...,
         "hbm_gbps_measured": hbm_gb_per_step / step_s}

    ``run_step`` should only ENQUEUE its step (no per-step host readback —
    that would serialize dispatch over the transport and inflate the traced
    step time); ``finalize`` runs once inside the trace to fence everything
    (e.g. a final-loss readback).
    """
    import shutil
    import tempfile

    import jax

    owned = log_dir is None
    d = log_dir or tempfile.mkdtemp(prefix="bagua_trace_")
    try:
        with jax.profiler.trace(d):
            for _ in range(steps):
                run_step()
            if finalize is not None:
                finalize()
        newest = _newest_xplane(d)
        if newest is None:
            return {}
        try:
            return parse_xplane_memory_traffic(newest)
        except Exception as e:  # pragma: no cover - proto availability varies
            logger.info("xplane parse unavailable: %s", e)
            return {}
    finally:
        if owned:  # don't leak tens-of-MB traces to /tmp per bench record
            shutil.rmtree(d, ignore_errors=True)


def trace_op_profile(run, log_dir=None, finalize=None) -> dict:
    """Like :func:`trace_memory_traffic` but returns the PER-OP kernel
    profile (:func:`parse_xplane_op_profile`) — the tool for measuring one
    kernel's on-device time and HBM traffic in isolation, where wall-clock
    timing would measure the host dispatch round-trip instead (on tunneled
    transports that is milliseconds against a microsecond kernel)."""
    import shutil
    import tempfile

    import jax

    owned = log_dir is None
    d = log_dir or tempfile.mkdtemp(prefix="bagua_optrace_")
    try:
        with jax.profiler.trace(d):
            run()
            if finalize is not None:
                finalize()
        newest = _newest_xplane(d)
        if newest is None:
            return {}
        try:
            return parse_xplane_op_profile(newest)
        except Exception as e:  # pragma: no cover - proto availability varies
            logger.info("xplane parse unavailable: %s", e)
            return {}
    finally:
        if owned:
            shutil.rmtree(d, ignore_errors=True)


def parse_xplane_op_profile(xplane_path: str) -> dict:
    """Per-op kernel time + measured memory traffic from the first TPU
    plane's ``XLA Ops`` line (per-chip scope, like
    :func:`parse_xplane_memory_traffic`).

    Returns ``{"ops": {name: {"time_s", "count", "hbm_gb", "vmem_gb",
    "cmem_gb"}}, "total_time_s", "total_hbm_gb", "total_vmem_gb"}`` —
    ``time_s`` is the op's on-device duration summed over occurrences, so
    the totals over a trace window containing ONLY the kernel under test
    are that kernel's true device time/traffic, independent of host
    dispatch latency."""
    from xprof.protobuf import op_metrics_pb2  # noqa: PLC0415

    plane = _first_tpu_plane(_load_xspace(xplane_path))
    if plane is None:
        return {}
    smd = plane.stat_metadata
    emd = plane.event_metadata
    ops: dict = {}
    for line in plane.lines:
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            name = emd[ev.metadata_id].name
            rec = ops.setdefault(
                name, {"time_s": 0.0, "count": 0,
                       "hbm_gb": 0.0, "cmem_gb": 0.0, "vmem_gb": 0.0}
            )
            rec["time_s"] += ev.duration_ps / 1e12
            rec["count"] += 1
            for s in emd[ev.metadata_id].stats:
                if smd[s.metadata_id].name == "memory_access_breakdown":
                    mab = op_metrics_pb2.MemoryAccessBreakdown()
                    mab.ParseFromString(s.bytes_value)
                    for acc in mab.memory_accessed:
                        key = {1: "hbm_gb", 2: "cmem_gb", 3: "vmem_gb"}.get(
                            acc.memory_space
                        )
                        if key:
                            rec[key] += acc.bytes_accessed / 1e9
    if not ops:
        return {}
    return {
        "ops": ops,
        "total_time_s": sum(r["time_s"] for r in ops.values()),
        "total_hbm_gb": sum(r["hbm_gb"] for r in ops.values()),
        "total_vmem_gb": sum(r["vmem_gb"] for r in ops.values()),
    }


#: HLO instruction-name prefixes that put an op on the wire (ICI/DCN) —
#: async collectives appear as ``<name>-start``/``-done``, which the
#: prefix match also covers
_COMM_OP_PREFIXES = (
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "ragged-all-to-all", "collective-broadcast",
)


def is_comm_op(name: str) -> bool:
    return name.startswith(_COMM_OP_PREFIXES)


def parse_xplane_overlap(xplane_path: str) -> dict:
    """Profiler-derived comm-hidden ratio for the overlap scheduler's bench
    record (ISSUE 2): from the first TPU plane's ``XLA Ops`` line, sum
    on-device time of communication ops (:func:`is_comm_op`) vs everything
    else, against the device step wall (``Steps`` line).

    If comm and compute ran strictly serialized, ``step ≈ comm + compute``;
    every second below that is a second of communication the scheduler hid
    under compute::

        overlap_fraction = clamp((comm + compute - step) / comm, 0, 1)

    Returns ``{}`` off-TPU or when the trace lacks the needed lines —
    callers record ``overlap_fraction: null`` honestly instead of guessing.
    """
    plane = _first_tpu_plane(_load_xspace(xplane_path))
    if plane is None:
        return {}
    emd = plane.event_metadata
    comm_ps = 0
    compute_ps = 0
    n_steps = 0
    step_ps = 0
    for line in plane.lines:
        if line.name == "Steps":
            n_steps = len(line.events)
            step_ps = sum(e.duration_ps for e in line.events)
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            if is_comm_op(emd[ev.metadata_id].name):
                comm_ps += ev.duration_ps
            else:
                compute_ps += ev.duration_ps
    if not n_steps or not step_ps or not comm_ps:
        return {}
    step_s = step_ps / n_steps / 1e12
    comm_s = comm_ps / n_steps / 1e12
    compute_s = compute_ps / n_steps / 1e12
    hidden = max(0.0, min(1.0, (comm_s + compute_s - step_s) / comm_s))
    return {
        "step_s": round(step_s, 6),
        "comm_s_per_step": round(comm_s, 6),
        "compute_s_per_step": round(compute_s, 6),
        "overlap_fraction": round(hidden, 3),
    }


def trace_overlap(run_step, steps: int = 5, finalize=None) -> dict:
    """Run ``run_step()`` under a trace and return
    :func:`parse_xplane_overlap`'s fields ({} off-TPU).  Same enqueue-only
    contract as :func:`trace_memory_traffic`."""
    import shutil
    import tempfile

    import jax

    d = tempfile.mkdtemp(prefix="bagua_overlap_trace_")
    try:
        with jax.profiler.trace(d):
            for _ in range(steps):
                run_step()
            if finalize is not None:
                finalize()
        newest = _newest_xplane(d)
        if newest is None:
            return {}
        try:
            return parse_xplane_overlap(newest)
        except Exception as e:  # pragma: no cover - proto availability varies
            logger.info("xplane parse unavailable: %s", e)
            return {}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def parse_xplane_comm_events(xplane_path: str) -> dict:
    """Per-occurrence communication events from the first TPU plane, in
    device-time order — the device half of per-bucket comm attribution
    (``bagua_tpu.obs.attribution`` matches these against the host's
    ``trace/bucket_collective`` launch schedule).

    Returns ``{}`` when the trace has no TPU plane or no comm ops;
    otherwise::

        {"events": [{"name", "t0_s", "dur_s"}, ...],   # sorted by t0_s
         "n_steps": ..., "step_s": mean device step seconds}

    ``-start``/``-done`` halves of one async collective both match
    :func:`is_comm_op`; the ``-start`` op carries the wire time, the
    ``-done`` is the wait — callers see both, named."""
    plane = _first_tpu_plane(_load_xspace(xplane_path))
    if plane is None:
        return {}
    emd = plane.event_metadata
    events = []
    n_steps = 0
    step_ps = 0
    for line in plane.lines:
        if line.name == "Steps":
            n_steps = len(line.events)
            step_ps = sum(e.duration_ps for e in line.events)
        if line.name != "XLA Ops":
            continue
        for ev in line.events:
            name = emd[ev.metadata_id].name
            if is_comm_op(name):
                events.append({
                    "name": name,
                    "t0_s": ev.offset_ps / 1e12,
                    "dur_s": ev.duration_ps / 1e12,
                })
    if not events:
        return {}
    events.sort(key=lambda e: e["t0_s"])
    out = {"events": events}
    if n_steps and step_ps:
        out["n_steps"] = n_steps
        out["step_s"] = step_ps / n_steps / 1e12
    return out


def parse_xplane_memory_traffic(xplane_path: str) -> dict:
    """Aggregate per-op ``memory_access_breakdown`` over every executed op
    occurrence in the TPU device plane.  Memory spaces (op_metrics.proto
    ``PerformanceInfo.MemoryAccessed.MemorySpace``): 1=HBM, 2=CMEM, 3=VMEM.

    Scope: the FIRST ``/device:TPU*`` plane only — on a multi-chip trace the
    returned ``hbm_gb_per_step`` / ``hbm_gbps_measured`` are therefore
    **per-chip** figures (one chip's traffic), not totals.  That is the
    convention every bench record uses (``*_per_chip``); do not multiply by
    chip count without checking the sharding actually balances traffic."""
    from xprof.protobuf import op_metrics_pb2  # noqa: PLC0415

    plane = _first_tpu_plane(_load_xspace(xplane_path))
    if plane is None:
        return {}
    smd = plane.stat_metadata
    emd = plane.event_metadata
    by_space = {1: 0, 2: 0, 3: 0}
    n_steps = 0
    step_ps = 0
    for line in plane.lines:
        if line.name == "Steps":
            n_steps = len(line.events)
            step_ps = sum(e.duration_ps for e in line.events)
        if line.name != "XLA Ops":
            continue
        for ev in line.events:  # per OCCURRENCE: metadata stats are static
            for s in emd[ev.metadata_id].stats:
                if smd[s.metadata_id].name == "memory_access_breakdown":
                    mab = op_metrics_pb2.MemoryAccessBreakdown()
                    mab.ParseFromString(s.bytes_value)
                    for acc in mab.memory_accessed:
                        by_space[acc.memory_space] = (
                            by_space.get(acc.memory_space, 0)
                            + acc.bytes_accessed
                        )
    if not n_steps or not step_ps:
        return {}
    step_s = step_ps / n_steps / 1e12
    out = {
        "step_s": round(step_s, 6),
        "hbm_gb_per_step": round(by_space.get(1, 0) / 1e9 / n_steps, 3),
        "cmem_gb_per_step": round(by_space.get(2, 0) / 1e9 / n_steps, 3),
        "vmem_gb_per_step": round(by_space.get(3, 0) / 1e9 / n_steps, 3),
    }
    out["hbm_gbps_measured"] = round(out["hbm_gb_per_step"] / step_s)
    return out
