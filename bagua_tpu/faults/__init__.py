"""Deterministic fault injection + the in-band defenses it proves out.

The reference Bagua survives faults with one blunt instrument — panic after
a 300 s comm timeout and gang-restart (bagua-core-internal/src/lib.rs:255-265).
This package makes every recovery path in bagua_tpu *exercisable on demand*:
a seeded injection registry (:mod:`bagua_tpu.faults.inject`) arms named
fault points inside the real store/heartbeat/checkpoint/watchdog/step code,
and ``scripts/chaos_drill.py`` / ``tests/test_faults.py`` drive the full
matrix in-process on the cpu-sim mesh.  See docs/robustness.md for the
failure-mode catalog (fault point → detector → recovery → drill).
"""

from .inject import (  # noqa: F401
    FAULT_POINTS,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    InjectedStoreError,
    clear_plan,
    fault_scope,
    get_plan,
    set_plan,
)
