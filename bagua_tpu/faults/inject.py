"""Seeded fault-injection registry: named points inside the real code paths.

Every defense in bagua_tpu (store retry, lease expiry, checkpoint fallback
restore, hang watchdog, gradient guard) is reachable from a *named injection
point* armed via ``BAGUA_FAULT_PLAN`` (a JSON list of specs) or
programmatically (:func:`fault_scope` / :func:`set_plan`).  Injection is
deterministic — triggers are step numbers or op counts, corruption offsets
come from each spec's seed — so a chaos drill is exactly repeatable, unlike
the process-killing elastic drill.

Points and what firing them does:

======================  =====================================================
``store.op``            the next ``_RestartStore`` op raises a (retryable)
                        :class:`InjectedStoreError` — exercises the
                        reconnect-and-retry path (distributed/run.py)
``elastic.heartbeat``   :class:`~bagua_tpu.elastic.membership.LeaseHeartbeat`
                        drops beats — the coordinator's lease expires and the
                        world shrinks
``ckpt.write``          deterministically corrupts (or tears) the just-saved
                        checkpoint's largest data file — restore must fall
                        back to the previous verified step
``ckpt.sidecar``        corrupts/truncates the layout sidecar JSON
``collective.hang``     wedges the watchdog waiter's readback inside a
                        watched section — the monitor must fire, abort, and
                        recover via ``reset_abort``
``grad.poison``         traced: injects NaN/Inf into a chosen bucket's
                        gradient at a chosen step inside the compiled train
                        step — the gradient-health sentinel must detect and
                        (policy permitting) skip it
``step.straggle``       dilates a chosen rank's step by ``factor``× its base
                        step time.  The straggler's own process always pays
                        the dilation; every OTHER process pays it only at a
                        *gated* synchronization point — a per-step gradient
                        collective (synchronous families), an async
                        negotiation boundary, a catch-up sync — which is
                        exactly where a slow peer binds in a real fleet
``async.partition``     drops a rank from one async-model-average
                        negotiation round: the round launched at the fired
                        boundary is never applied by that rank — the
                        bounded-staleness tracker must detect the lag and
                        force a synchronous catch-up average
``podsim.link``         the pod simulator's shaped loopback links
                        (:mod:`bagua_tpu.podsim.shaping`): ``drop`` eats one
                        shaped hop's payload (a ``ConnectionError`` to the
                        transport); ``partition`` severs the DCN links of the
                        slice named by ``rank`` for ``duration_s`` seconds —
                        intra-slice traffic keeps flowing, like a real
                        inter-slice network cut
``store.failover``      the failover store client treats its *current*
                        endpoint as dead before the next op — forces an
                        endpoint failover (+ standby promotion) without
                        killing the server process, so drills can prove the
                        multi-endpoint client path deterministically
======================  =====================================================

Every armed/fired/recovered event lands in
:data:`bagua_tpu.telemetry.counters` under ``faults/<point>/{armed,fired,
recovered}``.  The hooks are cheap no-ops while no plan is armed (one
``None`` check), so production code keeps them unconditionally.

This module must stay import-light (no jax): the launcher and the watchdog
waiter thread consume it.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from .. import env as _env
from ..telemetry import counters

logger = logging.getLogger(__name__)

FAULT_POINTS = (
    "store.op",
    "elastic.heartbeat",
    "ckpt.write",
    "ckpt.sidecar",
    "collective.hang",
    "grad.poison",
    "step.straggle",
    "async.partition",
    "podsim.link",
    "store.failover",
)

#: default fault kind per point (the only kind most points support)
_DEFAULT_KINDS = {
    "store.op": "error",
    "elastic.heartbeat": "drop",
    "ckpt.write": "corrupt",
    "ckpt.sidecar": "truncate",
    "collective.hang": "hang",
    "grad.poison": "nan",
    "step.straggle": "dilate",
    "async.partition": "drop",
    "podsim.link": "drop",
    "store.failover": "error",
}

_VALID_KINDS = {
    "store.op": ("error",),
    "elastic.heartbeat": ("drop",),
    "ckpt.write": ("corrupt", "torn"),
    "ckpt.sidecar": ("truncate", "corrupt"),
    "collective.hang": ("hang",),
    "grad.poison": ("nan", "inf"),
    "step.straggle": ("dilate",),
    "async.partition": ("drop",),
    "podsim.link": ("drop", "partition"),
    "store.failover": ("error",),
}


def _note_fire_to_recorder(spec: "FaultSpec") -> None:
    """Every fire refreshes a flight-recorder dump naming the firing point
    (lazy import: the obs package is import-light, but this module must
    stay loadable even if obs grows heavier; a recorder failure never
    blocks an injection)."""
    try:
        from ..obs import recorder as _obs_recorder

        _obs_recorder.note_fault_fire(spec.point, spec.kind)
    except Exception as e:  # noqa: BLE001 - injection must not die on obs
        logger.debug("fault-fire flight dump skipped: %s", e)


class InjectedFault(Exception):
    """Marker base for every injected failure, so defense code can tell an
    injected fault from a real one when recording recoveries."""


class InjectedStoreError(InjectedFault, ConnectionError):
    """Injected store flake — a ``ConnectionError`` subclass so the
    production retry path (``_STORE_RETRY_ERRORS``) catches it exactly like
    a real transient socket failure."""


@dataclass(frozen=True)
class FaultSpec:
    """One armed fault.  ``step`` triggers step-keyed points (``grad.poison``
    fires inside the step whose traced counter equals it; ``ckpt.*`` fire on
    the checkpoint saved at that step; None = any), ``op`` triggers op-count
    points (the op-index at which firing starts, 0 = the first op seen).
    ``count`` bounds total fires (-1 = unlimited); ``seed`` drives every
    random choice (corruption offsets) so reruns are identical."""

    point: str
    kind: str = ""
    step: Optional[int] = None
    op: int = 0
    count: int = 1
    seed: int = 0
    bucket: int = 0          # grad.poison: target bucket index
    duration_s: float = 30.0  # collective.hang: how long to wedge
    rank: int = 0            # step.straggle: which process rank is slow
    factor: float = 10.0     # step.straggle: dilation multiple of base time
    base_ms: float = 0.0     # step.straggle: straggler base step time; 0 =
    #                          use the caller-measured step time instead

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {self.point!r}; valid: {FAULT_POINTS}"
            )
        kind = self.kind or _DEFAULT_KINDS[self.point]
        object.__setattr__(self, "kind", kind)
        if kind not in _VALID_KINDS[self.point]:
            raise ValueError(
                f"fault kind {kind!r} invalid for {self.point!r}; valid: "
                f"{_VALID_KINDS[self.point]}"
            )
        if self.point == "step.straggle" and self.factor < 1.0:
            raise ValueError(
                f"step.straggle factor must be >= 1.0, got {self.factor}"
            )

    def signature(self) -> tuple:
        """Hashable identity of the TRACED behavior this spec compiles into
        (part of the trainer's step-cache key for ``grad.poison``).
        ``count`` is included because a step=None spec compiles it in as
        the fire window."""
        return (self.point, self.kind, self.step, self.bucket, self.count)


class FaultPlan:
    """A set of armed :class:`FaultSpec` with per-spec runtime state (op
    counters, fire counts).  Thread-safe — the heartbeat and watchdog
    waiter threads query it concurrently with the main thread."""

    def __init__(self, specs):
        self.specs: Tuple[FaultSpec, ...] = tuple(
            s if isinstance(s, FaultSpec) else FaultSpec(**s) for s in specs
        )
        self._lock = threading.Lock()
        self._ops: Dict[int, int] = {i: 0 for i in range(len(self.specs))}
        self._fires: Dict[int, int] = {i: 0 for i in range(len(self.specs))}

    @classmethod
    def from_json(cls, raw: str) -> "FaultPlan":
        data = json.loads(raw)
        if isinstance(data, dict):
            data = [data]
        if not isinstance(data, list):
            raise ValueError(
                "BAGUA_FAULT_PLAN must be a JSON list of fault specs"
            )
        return cls(data)

    def arm(self) -> None:
        armed: Dict[str, int] = {}
        for s in self.specs:
            key = f"faults/{s.point}/armed"
            armed[key] = armed.get(key, 0) + 1
        counters.incr_many(armed)
        if self.specs:
            logger.warning(
                "fault injection ARMED (%d specs): %s — drills/tests only",
                len(self.specs),
                ", ".join(f"{s.point}:{s.kind}" for s in self.specs),
            )

    def should_fire(self, point: str,
                    step: Optional[int] = None) -> Optional[FaultSpec]:
        """Query-and-advance: returns the spec that fires at this call (and
        records the fire), else None.  Step-keyed specs fire when ``step``
        matches; op-keyed specs count queries and fire from op-index
        ``spec.op`` for ``spec.count`` consecutive queries."""
        fired: Optional[FaultSpec] = None
        fire_no = 0
        with self._lock:
            for i, s in enumerate(self.specs):
                if s.point != point:
                    continue
                if s.count >= 0 and self._fires[i] >= s.count:
                    continue
                if s.step is not None:
                    if step is None or int(step) != int(s.step):
                        continue
                else:
                    idx = self._ops[i]
                    self._ops[i] = idx + 1
                    if idx < s.op:
                        continue
                self._fires[i] += 1
                fired, fire_no = s, self._fires[i]
                break
        if fired is None:
            return None
        # accounting and the flight-recorder hook run OUTSIDE the plan
        # lock (like note_traced_fire): the recorder dump does JSON + disk
        # I/O, and concurrent fault-point queries (heartbeat thread,
        # watchdog waiter) must not block on it
        counters.incr(f"faults/{point}/fired")
        logger.warning(
            "fault injection: %s fired (kind=%s, fire %d/%s)",
            point, fired.kind, fire_no,
            "inf" if fired.count < 0 else fired.count,
        )
        _note_fire_to_recorder(fired)
        return fired

    def note_traced_fire(self, spec: FaultSpec) -> None:
        """Host-side accounting for TRACED faults (``grad.poison`` fires
        inside the compiled program; the trainer calls this when the host
        step counter crosses the armed step)."""
        with self._lock:
            for i, s in enumerate(self.specs):
                if s is spec:
                    self._fires[i] += 1
        counters.incr(f"faults/{spec.point}/fired")
        logger.warning("fault injection: %s fired in-step (kind=%s)",
                       spec.point, spec.kind)
        _note_fire_to_recorder(spec)

    def fired(self, point: str) -> bool:
        with self._lock:
            return any(
                self._fires[i] > 0
                for i, s in enumerate(self.specs) if s.point == point
            )

    def armed_specs(self, point: str) -> Tuple[FaultSpec, ...]:
        return tuple(s for s in self.specs if s.point == point)


# ---- global plan ----------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_CHECKED = False
_GLOBAL_LOCK = threading.Lock()


def get_plan() -> Optional[FaultPlan]:
    """The active plan: the programmatically installed one, else the
    ``BAGUA_FAULT_PLAN`` env plan (parsed and armed once), else None."""
    global _PLAN, _ENV_CHECKED
    if _PLAN is not None:
        return _PLAN
    if _ENV_CHECKED:
        return None
    with _GLOBAL_LOCK:
        if not _ENV_CHECKED:
            _ENV_CHECKED = True
            raw = _env.get_fault_plan_raw()
            if raw:
                try:
                    plan = FaultPlan.from_json(raw)
                except (ValueError, TypeError, json.JSONDecodeError) as e:
                    raise ValueError(
                        f"BAGUA_FAULT_PLAN is not a valid fault plan: {e}"
                    ) from e
                plan.arm()
                _PLAN = plan
    return _PLAN


def set_plan(plan: Optional[FaultPlan]) -> None:
    """Install (and arm) a plan programmatically; ``None`` disarms."""
    global _PLAN, _ENV_CHECKED
    with _GLOBAL_LOCK:
        _ENV_CHECKED = True  # programmatic control; never fall back to env
        _PLAN = plan
    if plan is not None:
        plan.arm()


def clear_plan() -> None:
    """Disarm everything and forget the env plan was ever parsed (the next
    :func:`get_plan` re-reads ``BAGUA_FAULT_PLAN`` — test isolation)."""
    global _PLAN, _ENV_CHECKED
    with _GLOBAL_LOCK:
        _PLAN = None
        _ENV_CHECKED = False


@contextmanager
def fault_scope(*specs):
    """Arm the given specs (or one :class:`FaultPlan`) for the duration of
    the block, restoring the previous plan after::

        with fault_scope(FaultSpec("store.op", op=2)):
            ...   # the third store op flakes, once
    """
    if len(specs) == 1 and isinstance(specs[0], FaultPlan):
        plan = specs[0]
    else:
        plan = FaultPlan(specs)
    global _PLAN, _ENV_CHECKED
    with _GLOBAL_LOCK:
        prev, prev_checked = _PLAN, _ENV_CHECKED
        _PLAN = plan
        _ENV_CHECKED = True
    plan.arm()
    try:
        yield plan
    finally:
        with _GLOBAL_LOCK:
            _PLAN, _ENV_CHECKED = prev, prev_checked


# ---- hooks called by production code (no-ops while nothing is armed) ------


def should_fire(point: str, step: Optional[int] = None) -> Optional[FaultSpec]:
    plan = get_plan()
    return plan.should_fire(point, step=step) if plan is not None else None


def record_recovery(point: str) -> None:
    """Defense paths call this after recovering from a failure they know
    (or a drill knows) was injected; no-op unless the point has fired."""
    plan = _PLAN
    if plan is not None and plan.fired(point):
        counters.incr(f"faults/{point}/recovered")


def armed_traced_specs(point: str) -> Tuple[FaultSpec, ...]:
    """Specs the trainer compiles INTO the traced step (``grad.poison``);
    queried at trace time, so their signature is part of the step cache
    key."""
    plan = get_plan()
    return plan.armed_specs(point) if plan is not None else ()


def note_traced_fire(spec: FaultSpec) -> None:
    plan = _PLAN
    if plan is not None:
        plan.note_traced_fire(spec)


def maybe_raise_store_error(opname: str, point: str = "store.op") -> None:
    """``store.op`` / ``store.failover`` hook (the failover store client):
    raise a retryable injected flake before the op runs.  ``store.failover``
    is queried on the op *after* reconnect too, so arming it with
    ``count > 1`` walks the client down the endpoint list."""
    spec = should_fire(point)
    if spec is not None:
        raise InjectedStoreError(
            f"injected {point} fault on {opname} (seed={spec.seed})"
        )


def should_drop_heartbeat() -> bool:
    """``elastic.heartbeat`` hook (``LeaseHeartbeat._run``): True = skip
    this tick's beat (``count`` consecutive drops starve the lease)."""
    return should_fire("elastic.heartbeat") is not None


def maybe_hang(stop_event: Optional[threading.Event] = None) -> float:
    """``collective.hang`` hook (watchdog waiter): wedge the caller for the
    spec's duration (bounded; a stop event cuts it short so test teardown
    never waits the full window).  Returns seconds requested (0 = no
    fault)."""
    spec = should_fire("collective.hang")
    if spec is None:
        return 0.0
    if stop_event is not None:
        stop_event.wait(spec.duration_s)
    else:  # pragma: no cover - all in-repo callers pass their stop event
        time.sleep(spec.duration_s)
    return spec.duration_s


def maybe_corrupt_checkpoint(directory, step: int) -> bool:
    """``ckpt.write`` hook: after the checkpoint for ``step`` became
    durable, deterministically corrupt its largest data file (``corrupt``
    flips seeded bytes; ``torn`` truncates to half — the mid-write crash).
    Returns True when a corruption was applied."""
    plan = get_plan()
    if plan is None or not plan.armed_specs("ckpt.write"):
        return False
    # enumerate BEFORE consuming the fire: recording a fired count for a
    # step whose files are gone (retention pruned, empty dir) would exhaust
    # a single-shot spec and let a drill validate a fault that never
    # actually landed on disk
    root = os.path.join(str(directory), str(int(step)))
    candidates = []
    for dirpath, _, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            try:
                size = os.path.getsize(p)
            except OSError:
                continue
            if size > 0:
                candidates.append((size, p))
    if not candidates:
        logger.warning("ckpt.write injection: no files under %s — "
                       "fire not consumed", root)
        return False
    spec = should_fire("ckpt.write", step=int(step))
    if spec is None:
        return False
    # the largest file holds the array payload: corrupting it guarantees
    # either an unreadable checkpoint or a digest mismatch at restore
    candidates.sort(key=lambda t: (-t[0], t[1]))
    size, target = candidates[0]
    if spec.kind == "torn":
        with open(target, "r+b") as f:
            f.truncate(max(1, size // 2))
        logger.warning("ckpt.write injection: tore %s to %d bytes",
                       target, max(1, size // 2))
        return True
    rng = random.Random(spec.seed)
    with open(target, "r+b") as f:
        data = bytearray(f.read())
        n = min(64, len(data))
        for _ in range(n):
            data[rng.randrange(len(data))] ^= 0xFF
        f.seek(0)
        f.write(bytes(data))
    logger.warning("ckpt.write injection: flipped %d bytes in %s", n, target)
    return True


def straggle_targets_self() -> bool:
    """Whether an armed ``step.straggle`` spec names THIS process's rank —
    i.e. injected stalls here model a locally slow host (the straggler
    itself), not a wait on a slow peer.  The anomaly detector's phase
    attribution reads this to file the stall under ``dispatch`` vs
    ``collective``."""
    plan = get_plan()
    if plan is None:
        return False
    this_rank = _env.get_rank()
    return any(s.rank == this_rank
               for s in plan.armed_specs("step.straggle"))


def maybe_straggle(sync_point: str, base_dt: Optional[float] = None,
                   gated: bool = True) -> float:
    """``step.straggle`` hook: stall the caller by ``(factor - 1)``× the
    straggler's base step time, simulating a slow host in the fleet.

    ``gated`` names whether the calling code path actually synchronizes
    with the straggler: a per-step gradient collective (synchronous
    families) or an async negotiation/catch-up boundary is gated; an async
    train step running on stale local weights is not.  The straggler's OWN
    process (``spec.rank == env.get_rank()``) always pays the dilation —
    its host really is slow — while peers pay only at gated points, which
    is where a slow peer binds in a real fleet.  Returns seconds slept
    (0 = no fault, or the straggler does not gate this point)."""
    plan = get_plan()
    if plan is None:
        return 0.0
    specs = plan.armed_specs("step.straggle")
    if not specs:
        return 0.0
    this_rank = _env.get_rank()
    if not any(s.rank == this_rank or gated for s in specs):
        return 0.0
    if not base_dt and not any(s.base_ms > 0 for s in specs):
        # no dilation base exists yet (the caller has not measured a step
        # cadence and no spec pins base_ms): a fire here would be spent on
        # a zero-length sleep while still counting as "fired" — skip the
        # query so a count-limited spec waits for a base instead
        logger.warning("step.straggle: no base step time at %s — "
                       "fire not consumed", sync_point)
        return 0.0
    spec = plan.should_fire("step.straggle")
    if spec is None:
        return 0.0
    base = spec.base_ms / 1000.0 if spec.base_ms > 0 else float(base_dt or 0)
    delay = max(0.0, (spec.factor - 1.0) * base)
    if delay > 0.0:
        logger.debug("step.straggle: stalling %s for %.4fs (factor %.1f)",
                     sync_point, delay, spec.factor)
        time.sleep(delay)
    return delay


def maybe_drop_negotiation_round() -> bool:
    """``async.partition`` hook (async model average's negotiated
    boundary): True = this rank is partitioned out of the round launched
    at this boundary — it still participates in the negotiation gather and
    the averaging collective (the SPMD dispatch schedule must stay aligned
    on every process), but it never APPLIES the round's delta, so its
    applied-round counter stalls and the bounded-staleness tracker must
    catch it."""
    return should_fire("async.partition") is not None


def maybe_corrupt_sidecar(path, step: int) -> bool:
    """``ckpt.sidecar`` hook: corrupt the just-written layout sidecar
    (``truncate`` leaves torn JSON; ``corrupt`` replaces it with garbage)."""
    spec = should_fire("ckpt.sidecar", step=int(step))
    if spec is None:
        return False
    try:
        text = path.read_text()
    except OSError:  # pragma: no cover - fs-backend dependent
        return False
    if spec.kind == "truncate":
        path.write_text(text[: max(1, len(text) // 2)])
    else:
        path.write_text("\x00not json\x00")
    logger.warning("ckpt.sidecar injection: %s %s", spec.kind, path)
    return True
