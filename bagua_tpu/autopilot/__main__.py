"""Operator CLI: replay a fleet snapshot stream against the current
policy matrix.

    python -m bagua_tpu.autopilot --replay SNAPSHOTS.jsonl
        [--out DECISIONS.json] [--expect PLAN.json]
        [--slo-goodput F] [--sustain N] [--cooldown-s S] [--budget N]
        [--staleness-s S] [--straggler-ratio F] [--ckpt-failures N]
        [--family NAME] [--historian] [--trend-window-s S]
        [--dcn-share F] [--hbm-horizon-s S] [--compress-family NAME]

``SNAPSHOTS.jsonl``: one ``bagua-obs-fleet-v1`` record per line (the
stream a coordinator's ``BAGUA_OBS_FLEET_OUT`` writer produced — tail the
file into a log, or synthesize one).  Replay is a pure rehearsal: each
snapshot is evaluated at its OWN ``time_unix`` (deterministic regardless
of when the operator runs it) and nothing actuates.  Prints the decision
log as JSON; ``--expect`` compares the decided action plan (the
``(snapshot, kind, rule)`` sequence) against a committed expectation and
exits non-zero on mismatch — the CI smoke gate.

Policy knobs default to the ``BAGUA_AUTOPILOT_*`` env registry values;
flags override (so an operator can ask "what WOULD a tighter SLO have
done to yesterday's fleet?").

``--historian`` replays the stream through a fresh telemetry historian
(:mod:`bagua_tpu.obs.historian`) first — each snapshot is ingested and
trend-augmented exactly as the live coordinator would, so the trend
rules (pre-OOM resize on shrinking HBM headroom, DCN-dominance
compression hint) can fire.  Deterministic: historian samples are
timestamped by the records' own ``time_unix``.  Also on when
``BAGUA_OBS_HISTORIAN=on``; raw (un-augmented) replays of streams whose
snapshots already carry ``trends`` behave identically either way.
"""

from __future__ import annotations

import argparse
import json
import sys
from dataclasses import replace
from typing import List

from .. import env as _env
from .engine import replay
from .policy import config_from_env


def _load_snapshots(path: str) -> List[dict]:
    snaps = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                snaps.append(json.loads(line))
            except ValueError as e:
                sys.exit(f"{path}:{i + 1}: unparseable snapshot: {e}")
    if not snaps:
        sys.exit(f"{path}: no snapshots")
    return snaps


def _plan(log: List[dict]) -> List[dict]:
    """The comparable skeleton of a decision log: which action kinds which
    rules decided at which snapshot (targets/reasons carry wall-clock and
    host specifics that must not fail a replay gate)."""
    return [
        {"snapshot": entry["snapshot"], "kind": a["kind"], "rule": a["rule"]}
        for entry in log for a in entry["actions"]
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "python -m bagua_tpu.autopilot",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("--replay", required=True, metavar="SNAPSHOTS.jsonl",
                    help="fleet snapshot stream (one JSON record per line)")
    ap.add_argument("--out", default=None,
                    help="write the full decision log here (default: stdout)")
    ap.add_argument("--expect", default=None, metavar="PLAN.json",
                    help="committed expected action plan; exit 1 on "
                         "mismatch (the CI smoke gate)")
    ap.add_argument("--slo-goodput", type=float, default=None)
    ap.add_argument("--sustain", type=int, default=None)
    ap.add_argument("--cooldown-s", type=float, default=None)
    ap.add_argument("--budget", type=int, default=None)
    ap.add_argument("--staleness-s", type=float, default=None)
    ap.add_argument("--straggler-ratio", type=float, default=None)
    ap.add_argument("--suspect-ttl-s", type=float, default=None)
    ap.add_argument("--ckpt-failures", type=int, default=None)
    ap.add_argument("--family", default=None)
    ap.add_argument("--dcn-share", type=float, default=None)
    ap.add_argument("--hbm-horizon-s", type=float, default=None)
    ap.add_argument("--compress-family", default=None)
    ap.add_argument("--compress-codec", default=None)
    ap.add_argument("--historian", action="store_true",
                    help="ingest the stream through a fresh telemetry "
                         "historian first (trend-augmented snapshots, as "
                         "the live coordinator would see them) — required "
                         "for the hbm_exhaustion/dcn_dominance rules; "
                         "also on when BAGUA_OBS_HISTORIAN=on")
    ap.add_argument("--trend-window-s", type=float, default=None,
                    help="historian trend window override "
                         "(default BAGUA_OBS_HISTORIAN_WINDOW_S)")
    args = ap.parse_args(argv)

    config = config_from_env()
    overrides = {
        "slo_goodput": args.slo_goodput, "sustain": args.sustain,
        "cooldown_s": args.cooldown_s, "budget": args.budget,
        "staleness_s": args.staleness_s,
        "straggler_ratio": args.straggler_ratio,
        "suspect_ttl_s": args.suspect_ttl_s,
        "ckpt_failures": args.ckpt_failures, "switch_family": args.family,
        "dcn_share": args.dcn_share, "hbm_horizon_s": args.hbm_horizon_s,
        "compress_family": args.compress_family,
        "compress_codec": args.compress_codec,
    }
    config = replace(config, mode="observe",
                     **{k: v for k, v in overrides.items() if v is not None})

    historian = None
    if args.historian or _env.is_obs_historian_on():
        from ..obs.historian import Historian

        historian = Historian(window_s=args.trend_window_s)

    log = replay(_load_snapshots(args.replay), config, historian=historian)
    record = {
        "mode": "replay",
        "historian": historian is not None,
        "config": {k: getattr(config, k)
                   for k in config.__dataclass_fields__},
        "decisions": log,
        "plan": _plan(log),
    }
    text = json.dumps(record, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out} ({len(record['plan'])} action(s) over "
              f"{len(log)} snapshot(s))")
    else:
        print(text)

    if args.expect:
        expected = json.load(open(args.expect))
        if isinstance(expected, dict):
            expected = expected.get("plan", expected)
        if record["plan"] != expected:
            print("autopilot replay: action plan DIVERGED from expectation",
                  file=sys.stderr)
            print(f"  expected: {json.dumps(expected)}", file=sys.stderr)
            print(f"  got:      {json.dumps(record['plan'])}",
                  file=sys.stderr)
            return 1
        print(f"autopilot replay: plan matches {args.expect} "
              f"({len(expected)} action(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
