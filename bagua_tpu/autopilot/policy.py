"""The autopilot's pure decision core: (fleet_snapshot, policy_state) ->
(actions, policy_state).

PRs 6-12 built a complete sense layer — per-rank ``straggler_suspect`` with
phase blame, health beacons and fencing, the goodput/badput ledger and
fleet efficiency rollup, checkpoint-integrity fallback counters, a crash
flight recorder — but the only automated actuation was the unhealthy-rank
fence.  For unattended multi-day runs on preemptible capacity (the
MegaScale operations story, arXiv 2402.15627, whose goodput lens the
ledger already uses) the coordinator must close the loop itself: Bagua's
thesis of system relaxations (arXiv 2107.01499) only pays off at fleet
scale when degradation triggers a cheap adaptation instead of a human
page.

Policy matrix (evidence -> action, every actuation through machinery that
already exists — no new control paths into the step):

=====================  ==========================================  =======
rule                   evidence (``bagua-obs-fleet-v1`` snapshot)  action
=====================  ==========================================  =======
``chronic_straggler``  dispatch-dominant ``straggler_suspect``     fence
                       (ratio >= straggler_ratio, fresh within     (world
                       suspect_ttl_s) sustained ``sustain``        resizes
                       snapshots                                   down)
``collective_victim``  collective-dominant suspect sustained       retune
                       (a rank WAITING on someone — the knobs,     hint
                       not the host, may be wrong)
``slo_breach``         fleet min goodput fraction < slo_goodput    ladder:
                       sustained ``sustain`` snapshots; each rung  hint ->
                       requires a fresh sustained window           retune ->
                                                                   switch ->
                                                                   resize
``ckpt_integrity``     a rank's integrity_failures +               storage
                       fallback_restores >= ckpt_failures          quarantine
``hbm_exhaustion``     historian trend: negative HBM-headroom      pre-OOM
                       slope projecting exhaustion within          resize
                       hbm_horizon_s (trends.hbm_headroom_eta_s)   (node
                       sustained ``sustain`` snapshots             removed)
``dcn_dominance``      historian trend: DCN device seconds >=      compress
                       dcn_share of the step wall                  hint
                       (trends.dcn_comm_share) sustained           (slow
                       ``sustain`` snapshots                       tier)
=====================  ==========================================  =======

The two trend rules consume the ``trends`` sub-dicts the telemetry
historian (:mod:`bagua_tpu.obs.historian`) publishes into each rank's
obs summary — windowed least-squares derivatives, not point-in-time
readings.  Without the historian (``BAGUA_OBS_HISTORIAN=off``, the
default) no snapshot carries trends and neither rule can fire: the
rules are provably inert until the operator turns the memory on.

Every rule carries hysteresis: ``sustain`` consecutive snapshots to
trigger, per-action-kind cooldowns, and a global action budget.
Precedence: a fence beats a retune for the same rank — a host being
removed must not also be "fixed" by a knob change.  The core is a pure
function of (snapshot, state, config, now): no I/O, no clocks, no
telemetry — the engine (:mod:`bagua_tpu.autopilot.engine`) supplies the
wall clock, publishes the counter deltas recorded in ``state.counters``,
and actuates.  Import-light (no jax): the coordinator's launcher hosts it.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from .. import env as _env
from ..obs.anomaly import fleet_straggler_suspects

__all__ = [
    "Action", "PolicyConfig", "PolicyState", "decide",
    "ACTION_KINDS", "LADDER", "config_from_env",
]

#: every action kind the matrix can emit (cooldowns are tracked per kind)
ACTION_KINDS = ("fence", "retune_hint", "retune", "switch_family",
                "resize", "quarantine_storage", "compress_dcn")

#: the SLO escalation ladder, cheapest adaptation first: rung N's action
#: fires only after rung N-1 fired AND the breach sustained through a
#: fresh hysteresis window
LADDER = ("retune_hint", "retune", "switch_family", "resize")


@dataclass(frozen=True)
class Action:
    """One decided adaptation: what to do, to whom, and the evidence that
    condemned them (flight-recorded verbatim)."""

    kind: str          # one of ACTION_KINDS
    rule: str          # which matrix row fired
    target: Any        # node id list / rank / storage path / family name
    reason: str
    evidence: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class PolicyConfig:
    """The matrix's knobs — built from the env registry by
    :func:`config_from_env`, or passed explicitly (tests, replays)."""

    mode: str = "off"                 # off | observe | act
    sustain: int = 3                  # consecutive snapshots to trigger
    cooldown_s: float = 300.0         # per-action-kind cooldown
    budget: int = 8                   # global action budget per run
    staleness_s: float = 60.0         # snapshot freshness bound
    slo_goodput: float = 0.0          # 0 disables the SLO ladder
    straggler_ratio: float = 3.0      # min suspect ratio counted
    suspect_ttl_s: float = 120.0      # suspect evidence freshness
    ckpt_failures: int = 3            # integrity events before quarantine
    switch_family: str = "async"      # the ladder's switch rung target
    dcn_share: float = 0.5            # trend rule: DCN share of the step
    compress_family: str = "bytegrad"  # the compression hint's family
    compress_codec: str = "minmax_uint8"  # DCN wire codec the hint actuates
    hbm_horizon_s: float = 600.0      # trend rule: pre-OOM projection


def config_from_env() -> PolicyConfig:
    return PolicyConfig(
        mode=_env.get_autopilot_mode(),
        sustain=max(1, _env.get_autopilot_sustain()),
        cooldown_s=_env.get_autopilot_cooldown_s(),
        budget=_env.get_autopilot_budget(),
        staleness_s=_env.get_autopilot_staleness_s(),
        slo_goodput=_env.get_autopilot_slo_goodput(),
        straggler_ratio=_env.get_autopilot_straggler_ratio(),
        suspect_ttl_s=_env.get_autopilot_suspect_ttl_s(),
        ckpt_failures=_env.get_autopilot_ckpt_failures(),
        switch_family=_env.get_autopilot_family(),
        dcn_share=_env.get_autopilot_dcn_share(),
        compress_family=_env.get_autopilot_compress_family(),
        compress_codec=_env.get_autopilot_compress_codec(),
        hbm_horizon_s=_env.get_autopilot_hbm_horizon_s(),
    )


@dataclass
class PolicyState:
    """Everything the matrix remembers between snapshots — JSON-round-trip
    serializable so a relaunched coordinator resumes with its cooldowns,
    escalation rung, and quarantined paths intact (persisted through the
    restart TCPStore by the engine)."""

    #: rule/target -> consecutive qualifying snapshots
    streaks: Dict[str, int] = field(default_factory=dict)
    #: action kind -> wall time (unix) it last fired (cooldowns compare
    #: wall clock, never monotonic: the state crosses process restarts)
    last_action_unix: Dict[str, float] = field(default_factory=dict)
    actions_taken: int = 0
    #: SLO ladder rung reached (0 = healthy; index into LADDER is rung-1)
    rung: int = 0
    #: codec-ladder rung for the compress_dcn hint (0 = the configured
    #: start codec): each sustained RE-breach of DCN dominance after an
    #: actuated hint escalates one rung along
    #: ``bagua_tpu.compression.codecs.CODEC_LADDER`` (uint8 -> fp8 ->
    #: onebit_ef -> topk) — more aggressive wire formats until the DCN
    #: share drops below the threshold; unwinds when dominance clears
    codec_rung: int = 0
    #: consecutive healthy (non-breaching) snapshots — de-escalation timer
    slo_clear_streak: int = 0
    #: storage paths already quarantined (idempotence)
    quarantined: List[str] = field(default_factory=list)
    #: time_unix of the last snapshot evaluated (duplicate-write guard:
    #: re-reading one snapshot must not advance any sustain streak)
    last_snapshot_unix: Optional[float] = None
    #: cumulative bookkeeping the engine diffs into telemetry counters
    counters: Dict[str, int] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, raw) -> "PolicyState":
        d = json.loads(raw)
        known = {f for f in cls.__dataclass_fields__}  # noqa: C401
        return cls(**{k: v for k, v in d.items() if k in known})

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n


def _fresh_suspects(snapshot: dict, config: PolicyConfig,
                    now: float) -> Tuple[List[dict], List[dict]]:
    """Straggler/victim suspects that are strong (ratio) and fresh (ttl)
    enough to count as live evidence.  Reuses the coordinator-side
    analysis the fleet snapshot was built for."""
    named = fleet_straggler_suspects(snapshot)

    def live(items):
        out = []
        for it in items:
            s = it.get("suspect") or {}
            ratio = s.get("ratio") or 0.0
            detected = s.get("detected_at_unix")
            if ratio < config.straggler_ratio:
                continue
            if detected is not None and now - float(detected) \
                    > config.suspect_ttl_s:
                continue
            out.append(it)
        return out

    return live(named["stragglers"]), live(named["victims"])


def _goodput_min(snapshot: dict) -> Optional[float]:
    eff = snapshot.get("efficiency") or {}
    v = eff.get("goodput_fraction_min")
    return float(v) if v is not None else None


def _ckpt_evidence(snapshot: dict, config: PolicyConfig) -> List[dict]:
    """Ranks whose checkpoint-integrity event count crossed the quarantine
    threshold, with the storage path their manager reported."""
    out = []
    for node_id, entry in (snapshot.get("ranks") or {}).items():
        for rank_id, summary in (entry.get("obs") or {}).items():
            if not isinstance(summary, dict):
                continue
            events = int(summary.get("ckpt_integrity_failures", 0) or 0) + \
                int(summary.get("ckpt_fallback_restores", 0) or 0)
            path = summary.get("ckpt_directory")
            if events >= config.ckpt_failures and path:
                out.append({"node": int(node_id), "rank": rank_id,
                            "path": str(path), "events": events})
    return out


def _trend_evidence(snapshot: dict) -> List[dict]:
    """Per-rank historian trends from the snapshot ((node, rank, trends)
    records).  Present only when the telemetry historian augmented the
    record — a point-in-time snapshot carries no trends and the trend
    rules stay inert."""
    out = []
    for node_id, entry in (snapshot.get("ranks") or {}).items():
        for rank_id, summary in (entry.get("obs") or {}).items():
            if not isinstance(summary, dict):
                continue
            trends = summary.get("trends")
            if isinstance(trends, dict) and trends:
                out.append({"node": int(node_id), "rank": str(rank_id),
                            "trends": trends})
    return out


def _bump_streak(state: PolicyState, key: str, active: bool) -> int:
    """Advance (or reset) one sustain streak; returns the new count."""
    if active:
        state.streaks[key] = state.streaks.get(key, 0) + 1
    else:
        state.streaks.pop(key, None)
    return state.streaks.get(key, 0)


def _gate(state: PolicyState, config: PolicyConfig, kind: str,
          now: float) -> Optional[str]:
    """Why an action of ``kind`` may NOT fire right now (None = clear):
    the cooldown/budget half of the hysteresis contract."""
    if config.budget <= 0 or state.actions_taken >= config.budget:
        state._count("suppressed_budget")
        return "budget_exhausted"
    last = state.last_action_unix.get(kind)
    if last is not None and now - last < config.cooldown_s:
        state._count("suppressed_cooldown")
        return "cooldown"
    return None


def _emit(state: PolicyState, actions: List[Action], action: Action,
          now: float) -> None:
    state.last_action_unix[action.kind] = now
    state.actions_taken += 1
    state._count("decisions")
    actions.append(action)


def _worst_goodput_node(snapshot: dict) -> Optional[Tuple[int, str, float]]:
    """(node_id, rank_id, goodput) of the fleet's worst-goodput rank — the
    ladder's resize rung removes its node."""
    worst = None
    for node_id, entry in (snapshot.get("ranks") or {}).items():
        for rank_id, summary in (entry.get("obs") or {}).items():
            if not isinstance(summary, dict):
                continue
            gf = summary.get("goodput_fraction")
            if gf is None:
                continue
            if worst is None or float(gf) < worst[2]:
                worst = (int(node_id), str(rank_id), float(gf))
    return worst


def decide(snapshot: dict, state: PolicyState, config: PolicyConfig,
           now: float) -> Tuple[List[Action], PolicyState]:
    """Run the policy matrix over one fleet snapshot.

    Pure: consumes the snapshot dict, the previous :class:`PolicyState`,
    the config, and the caller's wall clock; returns the decided actions
    and the NEW state (the input state is never mutated).  ``mode`` is not
    consulted here — observe vs act is the engine's actuation gate; the
    decision log must be identical in both so a dry-run rehearses the real
    policy.
    """
    state = replace(
        state,
        streaks=dict(state.streaks),
        last_action_unix=dict(state.last_action_unix),
        quarantined=list(state.quarantined),
        counters=dict(state.counters),
    )
    actions: List[Action] = []
    state._count("snapshots")

    # ---- staleness guard: refuse to act on old evidence -----------------
    snap_time = snapshot.get("time_unix")
    if snap_time is None or now - float(snap_time) > config.staleness_s:
        state._count("stale_snapshots")
        return [], state
    # duplicate-write guard: the monitor may re-read one snapshot faster
    # than the writer refreshes it; a re-read is not new evidence and must
    # not advance any sustain streak
    if state.last_snapshot_unix is not None \
            and float(snap_time) <= state.last_snapshot_unix:
        return [], state
    state.last_snapshot_unix = float(snap_time)

    stragglers, victims = _fresh_suspects(snapshot, config, now)

    # ---- rule 1: chronic dispatch-dominant straggler -> fence -----------
    straggler_nodes = {it["node"] for it in stragglers}
    fenced_nodes: set = set()
    for node in sorted(straggler_nodes):
        streak = _bump_streak(state, f"straggler/{node}", True)
        if streak < config.sustain:
            continue
        why = _gate(state, config, "fence", now)
        if why is not None:
            continue
        evidence = [it for it in stragglers if it["node"] == node]
        _emit(state, actions, Action(
            kind="fence", rule="chronic_straggler", target=[node],
            reason=(f"node {node}: dispatch-dominant straggler suspect "
                    f"sustained {streak} snapshots "
                    f"(ratio {evidence[0]['suspect'].get('ratio')})"),
            evidence={"suspects": evidence, "streak": streak},
        ), now)
        fenced_nodes.add(node)
        state.streaks.pop(f"straggler/{node}", None)
    # nodes no longer suspect: clear their streaks
    for key in [k for k in state.streaks
                if k.startswith("straggler/")
                and int(k.split("/", 1)[1]) not in straggler_nodes]:
        state.streaks.pop(key, None)

    trend_items = _trend_evidence(snapshot)

    # ---- rule 1b: shrinking HBM headroom -> pre-OOM resize-down ---------
    # historian evidence only: a rank whose windowed headroom slope is
    # negative and projects exhaustion within the horizon gets its node
    # removed at a restart boundary BEFORE the OOM kills the gang
    # mid-collective (an OOM is a crash-loop; a resize is one rendezvous)
    hbm_nodes: Dict[int, dict] = {}
    if config.hbm_horizon_s > 0:
        for item in trend_items:
            trends = item["trends"]
            eta = trends.get("hbm_headroom_eta_s")
            slope = trends.get("hbm_headroom_slope")
            if slope is None or slope >= 0 or eta is None:
                continue
            if eta <= config.hbm_horizon_s:
                prev = hbm_nodes.get(item["node"])
                if prev is None or eta < prev["trends"].get(
                        "hbm_headroom_eta_s", float("inf")):
                    hbm_nodes[item["node"]] = item
    for node in sorted(hbm_nodes):
        if node in fenced_nodes:
            # already being removed this round — and its pending streak
            # resets: "sustained" means CONSECUTIVE qualifying snapshots,
            # and a fence interruption breaks the run (a frozen streak
            # would let non-consecutive evidence satisfy the hysteresis)
            state.streaks.pop(f"hbm/{node}", None)
            continue
        streak = _bump_streak(state, f"hbm/{node}", True)
        if streak < config.sustain:
            continue
        why = _gate(state, config, "resize", now)
        if why is not None:
            continue
        item = hbm_nodes[node]
        eta = item["trends"].get("hbm_headroom_eta_s")
        _emit(state, actions, Action(
            kind="resize", rule="hbm_exhaustion", target=[node],
            reason=(f"node {node} (rank {item['rank']}): HBM headroom "
                    f"slope {item['trends'].get('hbm_headroom_slope'):.0f} "
                    f"B/s projects exhaustion in {eta:.0f}s <= horizon "
                    f"{config.hbm_horizon_s:.0f}s, sustained {streak} "
                    "snapshots; resizing down before the OOM"),
            evidence={"trend": item, "streak": streak},
        ), now)
        fenced_nodes.add(node)
        state.streaks.pop(f"hbm/{node}", None)
    # nodes whose headroom recovered: clear their streaks
    for key in [k for k in state.streaks
                if k.startswith("hbm/")
                and int(k.split("/", 1)[1]) not in hbm_nodes]:
        state.streaks.pop(key, None)

    # ---- rule 2: collective-dominant victim -> retune hint --------------
    # precedence: a fence beats a retune for the same rank — removing the
    # straggler already fixes its victims' waits, and any victim living on
    # a node being fenced this round is evidence, not a patient
    victim_ranks = {it["rank"] for it in victims
                    if it["node"] not in fenced_nodes
                    and it["node"] not in straggler_nodes}
    victim_active = bool(victim_ranks)
    streak = _bump_streak(state, "victim", victim_active)
    if victim_active and streak >= config.sustain:
        why = _gate(state, config, "retune_hint", now)
        if why is None:
            evidence = [it for it in victims if it["rank"] in victim_ranks]
            _emit(state, actions, Action(
                kind="retune_hint", rule="collective_victim",
                target=sorted(victim_ranks),
                reason=(f"rank(s) {sorted(victim_ranks)} collective-"
                        f"dominant (waiting on peers) sustained {streak} "
                        "snapshots; autotune should re-measure"),
                evidence={"suspects": evidence, "streak": streak},
            ), now)
            state.streaks.pop("victim", None)

    # ---- rule 2b: sustained DCN dominance -> compression-family hint -----
    # historian evidence only: when the windowed DCN share of the step
    # wall sits at/above the threshold fleet-wide-anywhere, hint the
    # autotune service toward the compression family whose hierarchical
    # path compresses ONLY the slow cross-slice tier
    # (docs/hierarchical.md) — the Bagua relaxation applied where bytes
    # are most expensive.  A hint, never a forced switch: the service
    # re-measures and the BO loop keeps the last word.
    dcn_items = [
        it for it in trend_items
        if config.dcn_share > 0
        and it["node"] not in fenced_nodes
        and (it["trends"].get("dcn_comm_share") or 0.0) >= config.dcn_share
    ]
    streak = _bump_streak(state, "dcn", bool(dcn_items))
    if not dcn_items and state.codec_rung:
        # dominance cleared: the current codec relieved the slow tier —
        # unwind the ladder so a later breach re-climbs from the start
        state.codec_rung = 0
    if dcn_items and streak >= config.sustain:
        why = _gate(state, config, "compress_dcn", now)
        if why is None:
            # codec ladder: the FIRST hint actuates the configured start
            # codec; every sustained re-breach afterwards (the actuated
            # codec did not relieve the DCN share) escalates one rung —
            # uint8 -> fp8 -> onebit_ef -> topk, ~4x to 16-32x wire
            # reduction.  A start codec outside the ladder stays fixed
            # (the operator chose a specific format).
            from ..compression.codecs import CODEC_LADDER
            if config.compress_codec in CODEC_LADDER:
                base = CODEC_LADDER.index(config.compress_codec)
                idx = min(base + state.codec_rung, len(CODEC_LADDER) - 1)
                codec = CODEC_LADDER[idx]
                state.codec_rung = min(state.codec_rung + 1,
                                       len(CODEC_LADDER) - 1 - base)
            else:
                codec = config.compress_codec
            shares = {it["rank"]: round(
                it["trends"]["dcn_comm_share"], 3) for it in dcn_items}
            _emit(state, actions, Action(
                kind="compress_dcn", rule="dcn_dominance",
                target=config.compress_family,
                reason=(f"rank(s) {sorted(shares)} spend "
                        f">= {config.dcn_share:.0%} of the step on the "
                        f"DCN tier (shares {shares}) sustained {streak} "
                        f"snapshots; hinting compression family "
                        f"{config.compress_family!r} and actuating DCN "
                        f"codec {codec!r} for the slow tier "
                        f"(ladder rung {state.codec_rung})"),
                evidence={"trends": dcn_items, "streak": streak,
                          "codec": codec,
                          "codec_rung": state.codec_rung,
                          # worst observed share: the autotune v2 loop
                          # turns it into coordinate weighting (how hard
                          # to bias the search toward the DCN-tier knobs)
                          "dcn_share_max": max(shares.values())},
            ), now)
            state.streaks.pop("dcn", None)

    # ---- rule 3: goodput SLO breach -> escalation ladder -----------------
    gf_min = _goodput_min(snapshot)
    breaching = (
        config.slo_goodput > 0
        and gf_min is not None
        and gf_min < config.slo_goodput
    )
    streak = _bump_streak(state, "slo", breaching)
    if breaching:
        state.slo_clear_streak = 0
        if streak >= config.sustain and state.rung < len(LADDER):
            kind = LADDER[state.rung]
            why = _gate(state, config, kind, now)
            if why is None:
                target: Any = None
                if kind == "switch_family":
                    target = config.switch_family
                elif kind == "resize":
                    worst = _worst_goodput_node(snapshot)
                    target = [worst[0]] if worst else None
                if kind == "resize" and target is None:
                    # nothing attributable to remove; stay on this rung
                    pass
                else:
                    state.rung += 1
                    _emit(state, actions, Action(
                        kind=kind, rule="slo_breach", target=target,
                        reason=(f"fleet min goodput {gf_min:.3f} < SLO "
                                f"{config.slo_goodput:.3f} sustained "
                                f"{streak} snapshots; ladder rung "
                                f"{state.rung}/{len(LADDER)} ({kind})"),
                        evidence={"goodput_fraction_min": gf_min,
                                  "rung": state.rung, "streak": streak},
                    ), now)
                    # each rung needs a FRESH sustained breach window
                    state.streaks.pop("slo", None)
    elif config.slo_goodput > 0 and gf_min is not None:
        # healthy snapshot: de-escalate after a full sustain window of
        # health (the ladder unwinds completely — a later breach restarts
        # from the cheapest adaptation)
        if state.rung > 0:
            state.slo_clear_streak += 1
            if state.slo_clear_streak >= config.sustain:
                state.rung = 0
                state.slo_clear_streak = 0

    # ---- rule 4: repeated checkpoint-integrity fallbacks -> quarantine ---
    for item in _ckpt_evidence(snapshot, config):
        path = item["path"]
        if path in state.quarantined:
            continue
        why = _gate(state, config, "quarantine_storage", now)
        if why is not None:
            continue
        state.quarantined.append(path)
        _emit(state, actions, Action(
            kind="quarantine_storage", rule="ckpt_integrity", target=path,
            reason=(f"rank {item['rank']} (node {item['node']}): "
                    f"{item['events']} checkpoint integrity events >= "
                    f"{config.ckpt_failures}; quarantining {path}"),
            evidence=item,
        ), now)

    return actions, state
