"""The autopilot engine: hosts the pure decision core inside a monitor
loop, actuates through pre-existing machinery, and leaves evidence.

Division of labor:

* :func:`bagua_tpu.autopilot.policy.decide` is the brain — pure, clock-
  and I/O-free, unit-testable without a fleet.
* :class:`AutopilotEngine` is the body: it feeds each coordinator-side
  fleet snapshot to the core, publishes the core's bookkeeping as
  ``autopilot/*`` telemetry, flight-records every decided action with its
  triggering evidence (trigger ``autopilot_action``), persists the policy
  state through the restart TCPStore (a relaunched coordinator resumes
  with cooldowns/rung/quarantines intact instead of re-firing a
  cooled-down action), and — in ``act`` mode only — invokes the actuators
  the HOST wired in.
* The host (``distributed/run.py``'s elastic monitor, the chaos drills,
  the replay CLI) supplies actuators.  Fence/resize are control-flow
  entangled with the monitor loop (they must raise the gang-stop the
  epoch machinery rides), so the host actuates those from the returned
  action list itself; the engine actuates the side-channel kinds it CAN
  own: retune hints (autotune service delivery) and storage quarantine
  (:func:`bagua_tpu.checkpoint.quarantine_storage_path`).

``observe`` mode runs the identical decision path and identical evidence
trail without any actuation — the dry-run rollout contract
(docs/autopilot.md).  Import-light (no jax): the launcher hosts this.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from .. import env as _env
from ..telemetry import counters
from .policy import (
    Action,
    PolicyConfig,
    PolicyState,
    config_from_env,
    decide,
)

logger = logging.getLogger("bagua_tpu.autopilot")

__all__ = ["AutopilotEngine", "deliver_hints_via_service",
           "default_engine_actuators", "STATE_STORE_KEY", "replay"]

#: restart-store key the policy state persists under — deliberately
#: OUTSIDE the epoch-fenced ``elastic/<e>/`` keyspace: cooldowns and the
#: escalation rung must survive epoch bumps and coordinator relaunches
STATE_STORE_KEY = "autopilot/state"

#: restart-store key carrying the ACTUATED storage-quarantine verdicts
#: (newline-separated paths; written only by an act-mode engine).  Kept
#: separate from STATE_STORE_KEY on purpose: the policy state records
#: every quarantine DECISION (observe mode included, for the dry-run
#: log), but only act-mode verdicts may reach workers' checkpoint
#: managers — and EVERY launcher (not just the coordinator's) reads this
#: key at spawn time, so the verdict reaches the nodes actually writing
#: to the rotting storage
QUARANTINE_STORE_KEY = "autopilot/quarantined"


def read_actuated_quarantines(store) -> List[str]:
    """The launcher-side half of :data:`QUARANTINE_STORE_KEY`: the
    storage paths an act-mode engine has quarantined, for injection into
    respawned workers' ``BAGUA_CKPT_QUARANTINED_PATHS``.  Exception-free
    ([] on any store trouble) — callers are spawn paths."""
    try:
        raw = store.get(QUARANTINE_STORE_KEY)
    except Exception:  # noqa: BLE001 - store may be down mid-teardown
        return []
    if not raw:
        return []
    text = raw.decode() if isinstance(raw, bytes) else str(raw)
    return [p.strip() for p in text.splitlines() if p.strip()]

#: decided-action kind -> its telemetry counter
_KIND_COUNTERS = {
    "fence": "autopilot/fences",
    "retune_hint": "autopilot/retunes",
    "retune": "autopilot/retunes",
    "switch_family": "autopilot/family_switches",
    "resize": "autopilot/resizes",
    "quarantine_storage": "autopilot/quarantines",
    "compress_dcn": "autopilot/compress_hints",
}

#: core-bookkeeping key -> telemetry counter (diff-published per snapshot)
_STATE_COUNTERS = {
    "snapshots": "autopilot/snapshots",
    "stale_snapshots": "autopilot/stale_snapshots",
    "decisions": "autopilot/decisions",
    "suppressed_cooldown": "autopilot/suppressed_cooldown",
    "suppressed_budget": "autopilot/suppressed_budget",
}


def deliver_hints_via_service(model_name: str, hints: List[dict],
                              addr: Optional[str] = None) -> bool:
    """Deliver autopilot perf hints to the autotune sidecar through the
    EXISTING channel — ``AutotuneClient.report_metrics(perf_hints=)`` with
    the controller rank (-1), which the service excludes from speed
    scoring.  The trainers then receive any resulting recommendation at
    their normal check-ins: no new control path into the step."""
    from ..service.autotune_service import AutotuneClient

    addr = addr or _env.get_autotune_server_addr()
    if not addr:
        logger.warning("autopilot: no autotune service address; hint "
                       "dropped: %s", hints)
        return False
    host, port = addr.rsplit(":", 1)
    try:
        AutotuneClient(host, int(port)).report_metrics(
            model_name=model_name, rank=-1, train_iter=-1,
            hyperparameters={}, speed=0.0, perf_hints=hints,
        )
        return True
    except (ConnectionError, OSError) as e:
        logger.warning("autopilot: hint delivery failed: %s", e)
        return False


class AutopilotEngine:
    """One engine per coordinator process.  ``actuators`` maps action
    kinds to callables ``(Action) -> bool`` (actuated?); kinds without an
    actuator are returned to the caller (the monitor loop actuates
    fence/resize itself because they raise its gang-stop)."""

    def __init__(self, config: Optional[PolicyConfig] = None,
                 actuators: Optional[Dict[str, Callable]] = None,
                 store=None):
        self.config = config or config_from_env()
        self.actuators = dict(actuators or {})
        self._store = store
        self.state = PolicyState()
        self._published: Dict[str, int] = {}
        if store is not None:
            self._load_state(store)

    # ---- restart-idempotence: policy state on the restart store ---------

    def _load_state(self, store) -> None:
        try:
            raw = store.get(STATE_STORE_KEY)
        except Exception as e:  # noqa: BLE001 - store may be coming up
            logger.debug("autopilot state not loaded: %s", e)
            return
        if raw is None:
            return
        try:
            self.state = PolicyState.from_json(raw)
            # published watermark syncs to the loaded cumulative counts so
            # a relaunch does not re-publish the previous life's events
            self._published = dict(self.state.counters)
            logger.info(
                "autopilot: resumed policy state (rung %d, %d action(s) "
                "taken, %d quarantined path(s))", self.state.rung,
                self.state.actions_taken, len(self.state.quarantined),
            )
        except (ValueError, TypeError, KeyError) as e:
            logger.warning("autopilot: persisted state unreadable (%s); "
                           "starting fresh", e)
            return
        if self.config.mode == "act" and self.state.quarantined:
            # re-actuate persisted quarantine verdicts into this process's
            # registry: the decision fired once and is deduped by the
            # policy state, so a relaunched coordinator — or one whose
            # operator flipped observe -> act — must apply it here instead
            # of never again
            try:
                from ..checkpoint import quarantine_storage_path

                for path in self.state.quarantined:
                    quarantine_storage_path(path)
            except Exception as e:  # noqa: BLE001 - keep monitoring
                logger.warning("autopilot: quarantine re-apply failed: %s",
                               e)

    def _persist_state(self) -> None:
        if self._store is None:
            return
        try:
            self._store.set(STATE_STORE_KEY, self.state.to_json())
            if self.config.mode == "act":
                # actuated verdicts only: observe-mode decisions must stay
                # a log, not reach workers' checkpoint managers
                self._store.set(QUARANTINE_STORE_KEY,
                                "\n".join(self.state.quarantined))
            counters.incr("autopilot/state_persists")
        except Exception as e:  # noqa: BLE001 - monitoring must not die
            logger.debug("autopilot state not persisted: %s", e)

    # ---- the loop body ---------------------------------------------------

    def observe_snapshot(self, snapshot: dict,
                         now: Optional[float] = None) -> List[Action]:
        """Evaluate one fleet snapshot; returns the decided actions (after
        engine-side actuation of the kinds it owns).  The caller actuates
        any remaining control-flow kinds (fence/resize) and may consult
        :attr:`state` afterwards."""
        now = time.time() if now is None else float(now)
        actions, self.state = decide(snapshot, self.state, self.config, now)
        self._publish_counters()
        for action in actions:
            counters.incr(_KIND_COUNTERS[action.kind])
            self._flight_record(action, snapshot)
            logger.warning("autopilot decision [%s]: %s (%s)",
                           self.config.mode, action.kind, action.reason)
        if self.config.mode == "act":
            for action in actions:
                fn = self.actuators.get(action.kind)
                if fn is None:
                    continue  # caller-actuated kind (fence/resize)
                try:
                    if fn(action):
                        counters.incr("autopilot/actions_actuated")
                except Exception as e:  # noqa: BLE001 - keep monitoring
                    logger.warning("autopilot: actuation of %s failed: %s",
                                   action.kind, e)
        elif actions:
            counters.incr_many({"autopilot/observed_only": len(actions)})
        counters.set_gauge("autopilot/escalation_rung", self.state.rung)
        if actions:
            # persist at action time: cooldowns/rung/quarantines are what a
            # relaunched coordinator must not forget (between actions, a
            # lost streak merely re-earns its hysteresis — conservative)
            self._persist_state()
        return actions

    def note_actuated(self, action: Action) -> None:
        """Caller hook for host-actuated kinds (fence/resize): count the
        actuation and persist — the gang is about to stop, and the next
        coordinator life must see this action's cooldown."""
        counters.incr("autopilot/actions_actuated")
        self._persist_state()

    def _publish_counters(self) -> None:
        """Diff the core's cumulative bookkeeping into telemetry (the core
        is pure and cannot touch counters itself)."""
        deltas = {}
        for key, metric in _STATE_COUNTERS.items():
            have = self.state.counters.get(key, 0)
            seen = self._published.get(key, 0)
            if have > seen:
                deltas[metric] = have - seen
            self._published[key] = have
        if deltas:
            counters.incr_many(deltas)

    def _flight_record(self, action: Action, snapshot: dict) -> None:
        """Every decision leaves its post-mortem artifact: the action, its
        evidence, and the snapshot epoch it judged."""
        from ..obs.recorder import dump_flight_record

        dump_flight_record(
            "autopilot_action",
            reason=f"{action.rule}: {action.reason}",
            extra={
                "action": action.to_json(),
                "mode": self.config.mode,
                "snapshot_epoch": snapshot.get("epoch"),
                "snapshot_time_unix": snapshot.get("time_unix"),
                "rung": self.state.rung,
                "actions_taken": self.state.actions_taken,
            },
        )


def default_engine_actuators(model_name: Optional[str] = None,
                             autotune_addr: Optional[str] = None
                             ) -> Dict[str, Callable]:
    """The engine-owned actuators for production wiring: retune kinds
    deliver perf hints to the autotune service; quarantine marks the path
    in this process's checkpoint registry (the launcher additionally
    injects it into respawned workers' env — see distributed/run.py).
    Fence/resize are deliberately absent: the monitor loop owns them."""
    model = model_name or _env.get_autopilot_model()

    def _hint(action: Action) -> bool:
        kind_map = {
            "retune_hint": "autopilot_retune_hint",
            "retune": "autopilot_retune",
            "switch_family": "autopilot_switch_family",
            "compress_dcn": "autopilot_compress_dcn",
        }
        hint = {
            "kind": kind_map[action.kind],
            "rule": action.rule,
            "reason": action.reason,
        }
        if action.kind in ("switch_family", "compress_dcn"):
            hint["family"] = action.target
        if action.kind == "compress_dcn":
            # the codec the service actuates onto recommended.compress_inter
            # (every rank's next check-in re-jits the compressed DCN hops)
            hint["codec"] = (action.evidence or {}).get(
                "codec") or "minmax_uint8"
            # v2 search tasks turn the observed dominance into coordinate
            # weighting instead of a pin (priors, not pins)
            share = (action.evidence or {}).get("dcn_share_max")
            if share is not None:
                hint["dcn_share"] = share
        return deliver_hints_via_service(model, [hint], addr=autotune_addr)

    def _quarantine(action: Action) -> bool:
        from ..checkpoint import quarantine_storage_path

        quarantine_storage_path(action.target)
        return True

    return {
        "retune_hint": _hint,
        "retune": _hint,
        "switch_family": _hint,
        "compress_dcn": _hint,
        "quarantine_storage": _quarantine,
    }


def replay(snapshots: List[dict], config: PolicyConfig,
           state: Optional[PolicyState] = None,
           historian=None) -> List[dict]:
    """Replay a recorded fleet snapshot stream against the policy matrix
    (operator CLI + the CI smoke stage).  Each snapshot is evaluated with
    ``now`` = its own ``time_unix`` (so a recorded stream replays
    identically regardless of when the operator runs it) and NOTHING
    actuates — replay is a pure rehearsal.  With ``historian`` (a fresh
    :class:`bagua_tpu.obs.historian.Historian`), each snapshot is first
    ingested and trend-augmented exactly as the live coordinator would —
    the only way the trend rules (``hbm_exhaustion``/``dcn_dominance``)
    can fire in a replay, and deterministic because historian samples are
    timestamped by the records' own ``time_unix``.  Snapshots are
    deep-copied before augmentation; the caller's stream is never
    mutated.  Returns the decision log: one entry per snapshot with the
    decided actions."""
    import copy

    state = state or PolicyState()
    log: List[dict] = []
    for i, snap in enumerate(snapshots):
        if historian is not None:
            snap = historian.ingest(copy.deepcopy(snap))
        now = float(snap.get("time_unix") or 0.0)
        actions, state = decide(snap, state, config, now)
        log.append({
            "snapshot": i,
            "time_unix": snap.get("time_unix"),
            "epoch": snap.get("epoch"),
            "actions": [a.to_json() for a in actions],
            "rung": state.rung,
            "actions_taken": state.actions_taken,
        })
    return log
