"""Closed-loop fleet autopilot: the coordinator-side policy engine that
makes the observability plane act (docs/autopilot.md).

* :mod:`bagua_tpu.autopilot.policy` — the pure decision core
  ``(fleet_snapshot, policy_state) -> (actions, policy_state)``.
* :mod:`bagua_tpu.autopilot.engine` — the monitor-loop host: staleness
  guard, telemetry, flight recording, restart-store state persistence,
  actuation.
* ``python -m bagua_tpu.autopilot --replay`` — operator CLI replaying a
  recorded fleet snapshot stream against the current policy.
"""

from .engine import (  # noqa: F401
    AutopilotEngine,
    STATE_STORE_KEY,
    default_engine_actuators,
    deliver_hints_via_service,
    replay,
)
from .policy import (  # noqa: F401
    ACTION_KINDS,
    LADDER,
    Action,
    PolicyConfig,
    PolicyState,
    config_from_env,
    decide,
)
