"""Communicators and collective primitives — the TPU-native comm backend.

Replaces the reference's whole native comm stack: NCCL unique-id rendezvous +
``BaguaSingleCommunicator`` / ``BaguaHierarchicalCommunicator`` (Rust + Aluminum,
/root/reference/rust/bagua-core/bagua-core-internal/src/communicators/mod.rs)
and the 22 Python collective wrappers
(/root/reference/bagua/torch_api/communication.py:230-852).

Design: a :class:`BaguaCommunicator` names one or more mesh axes.  Its methods
come in one flavor only — *traced* — and must run inside ``shard_map`` over the
mesh; they lower straight to XLA collectives (``psum``/``all_gather``/
``all_to_all``/``ppermute``) that ride ICI.  The module-level functions
(:func:`allreduce`, :func:`allgather`, ...) are the eager, user-facing
primitives with reference semantics: input carries a leading *rank* axis and
the collective runs across it on the global mesh.  There is no NCCL-id
rendezvous: device bring-up is ``jax.distributed.initialize`` + mesh building
(:func:`init_process_group`).
"""

from __future__ import annotations

import logging
import os
import threading
from enum import IntEnum
from functools import lru_cache, partial
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map

from . import env
from .parallel.mesh import build_mesh, get_global_mesh, hierarchical_mesh, mesh_axis_size, set_global_mesh

logger = logging.getLogger(__name__)


# Numbering matches the reference (communication.py:25-36), which itself must
# match Aluminum's ReductionOperator — kept for wire/API compatibility.
class ReduceOp(IntEnum):
    """Available reduction operations: ``SUM``, ``PRODUCT``, ``MIN``, ``MAX``,
    ``BAND``, ``BOR``, ``BXOR`` and ``AVG``."""

    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    BOR = 7
    BAND = 8
    BXOR = 9
    AVG = 10


def _tree_map(f, tree):
    return jax.tree.map(f, tree)


# ---- abort flag (reference communicators/mod.rs:74-80, 456-471) -----------
#
# The reference exposes ``abort()``/``check_abort`` so a wedged collective
# can be cancelled cooperatively and tested
# (tests/comm/test_communicator.py:40-60).  XLA cannot cancel a compiled
# program mid-flight, so the TPU rendering is a process-wide flag: new work
# fails fast (the trainer checks it before every dispatch), background
# control loops (async model average) stop launching rounds, and the
# watchdog raises it before terminating a wedged process so cooperating
# threads wind down first.

_ABORT_EVENT = threading.Event()
_ABORT_REASON: Optional[str] = None


class BaguaAborted(RuntimeError):
    """Raised by :func:`check_abort` after :func:`abort` was called."""


def abort(reason: str = "user abort") -> None:
    """Flag every communicator as aborted; in-flight XLA programs finish
    (they cannot be cancelled) but no new communication is dispatched."""
    global _ABORT_REASON
    # lock-free by design: the Event is the sync point (reason is written
    # before set(), so a reader that saw the event sees the reason), the
    # store is a single GIL-atomic ref assignment, and check_abort
    # tolerates a torn read with its `or "aborted"` fallback
    _ABORT_REASON = reason  # bagua: lint-ignore[unguarded-shared-write] -- Event-published; GIL-atomic store; stale read falls back to "aborted"
    _ABORT_EVENT.set()
    from .telemetry import counters

    counters.incr("comm/aborts")
    logger.error("bagua_tpu: communication aborted: %s", reason)


def is_aborted() -> bool:
    return _ABORT_EVENT.is_set()


def check_abort() -> None:
    """Raise :class:`BaguaAborted` if :func:`abort` has been called
    (reference ``check_abort``, communicators/mod.rs:74-80)."""
    if _ABORT_EVENT.is_set():
        raise BaguaAborted(_ABORT_REASON or "aborted")


def reset_abort() -> None:
    """Clear the abort flag (recovery path after the cause was handled —
    the reference re-creates communicators after an abort)."""
    global _ABORT_REASON
    was_aborted = _ABORT_EVENT.is_set()
    _ABORT_REASON = None
    _ABORT_EVENT.clear()
    if was_aborted:
        from .faults import inject as _inject
        from .telemetry import counters

        counters.incr("comm/abort_resets")
        # an injected collective hang that reached abort and was then
        # reset is a completed recovery (chaos-drill accounting)
        _inject.record_recovery("collective.hang")


def collapse_trivial_axes(mesh: Mesh, axes) -> Tuple[str, ...]:
    """Drop size-1 axes (keeping at least one) so single-axis collectives
    (alltoall/ppermute) work whenever the topology is effectively 1-D."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    nontrivial = tuple(a for a in axes if mesh.shape[a] > 1)
    return nontrivial if nontrivial else axes[-1:]


class BaguaCommunicator:
    """A communicator spanning one or more mesh axes.

    Counterpart of ``BaguaSingleCommunicator`` (communicators/mod.rs:20-60);
    hierarchical execution is expressed by holding *two* of these (one over
    ``intra``, one over ``inter``) instead of Leader/Worker role objects.

    All methods must be called inside ``shard_map`` over a mesh containing
    ``axes``.
    """

    def __init__(self, axes, mesh: Optional[Mesh] = None):
        self.axes: Tuple[str, ...] = (axes,) if isinstance(axes, str) else tuple(axes)
        self._mesh = mesh

    @property
    def mesh(self) -> Mesh:
        return self._mesh if self._mesh is not None else get_global_mesh()

    @property
    def axis_name(self):
        return self.axes if len(self.axes) > 1 else self.axes[0]

    def nranks(self) -> int:
        return mesh_axis_size(self.mesh, self.axes)

    # -- traced ops (inside shard_map) ------------------------------------

    def rank(self):
        return lax.axis_index(self.axes)

    def allreduce(self, x, op: ReduceOp = ReduceOp.AVG):
        ax = self.axes
        if not ax:
            # zero-axis communicator (e.g. a tp-only mesh has no data axes):
            # every reduction is an identity over a single member
            return x
        if op == ReduceOp.SUM:
            return lax.psum(x, ax)
        if op == ReduceOp.AVG:
            return lax.pmean(x, ax)
        if op == ReduceOp.MAX:
            return lax.pmax(x, ax)
        if op == ReduceOp.MIN:
            return lax.pmin(x, ax)
        # rare ops: gather then reduce locally (still a single XLA all-gather)
        gathered = lax.all_gather(x, ax, axis=0)  # [nranks, ...]
        if op == ReduceOp.PRODUCT:
            return jnp.prod(gathered, axis=0)
        if op == ReduceOp.BOR:
            return jax.lax.reduce(gathered, jnp.zeros((), gathered.dtype), lax.bitwise_or, (0,))
        if op == ReduceOp.BAND:
            return jax.lax.reduce(gathered, ~jnp.zeros((), gathered.dtype), lax.bitwise_and, (0,))
        if op == ReduceOp.BXOR:
            return jax.lax.reduce(gathered, jnp.zeros((), gathered.dtype), lax.bitwise_xor, (0,))
        raise ValueError(f"unsupported ReduceOp {op}")

    def allgather(self, x, axis: int = 0, tiled: bool = True):
        return lax.all_gather(x, self.axes, axis=axis, tiled=tiled)

    def reduce_scatter(self, x, op: ReduceOp = ReduceOp.SUM, axis: int = 0):
        if op == ReduceOp.AVG:
            return lax.psum_scatter(x, self.axes, scatter_dimension=axis, tiled=True) / self.nranks()
        if op == ReduceOp.SUM:
            return lax.psum_scatter(x, self.axes, scatter_dimension=axis, tiled=True)
        raise ValueError(f"reduce_scatter supports SUM/AVG, got {op}")

    def alltoall(self, x, split_axis: int = 0, concat_axis: int = 0):
        # multiple axes are treated as one flattened axis (XLA supports
        # axis-name sequences), e.g. the ('dp','pp') bucket communicator
        ax = self.axes[0] if len(self.axes) == 1 else tuple(self.axes)
        return lax.all_to_all(x, ax, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=False)

    def alltoall_tiled(self, x, split_axis: int = 0, concat_axis: int = 0):
        ax = self.axes[0] if len(self.axes) == 1 else tuple(self.axes)
        return lax.all_to_all(x, ax, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)

    def alltoall_v(
        self, x, output, input_offsets, send_sizes, output_offsets, recv_sizes
    ):
        """Ragged all-to-all (reference ``alltoall_v``,
        communicators/mod.rs:632-676): rank r sends
        ``x[input_offsets[i] : input_offsets[i]+send_sizes[i]]`` to each rank
        i, which lands at ``output_offsets`` in that rank's ``output`` buffer
        (which supplies capacity, dtype, and the values of untouched slots).
        Lowers to XLA's native ragged-all-to-all over ICI.
        """
        if len(self.axes) != 1:
            raise ValueError("alltoall_v needs a single mesh axis")
        return lax.ragged_all_to_all(
            x, output, input_offsets, send_sizes, output_offsets, recv_sizes,
            axis_name=self.axes[0],
        )

    def ppermute(self, x, perm: Sequence[Tuple[int, int]]):
        if len(self.axes) != 1:
            raise ValueError("ppermute needs a single mesh axis")
        return lax.ppermute(x, self.axes[0], perm=list(perm))

    # -- chunked ring collectives (overlap scheduler, ISSUE 2) -------------
    #
    # ``psum``/``psum_scatter`` hand XLA ONE monolithic collective per
    # bucket: the latency-hiding scheduler can overlap it with unrelated
    # compute, but cannot start reducing a bucket's early bytes while its
    # late bytes are still being produced, nor interleave two phases of the
    # same bucket.  The ring forms below decompose a bucket into
    # ``num_chunks`` INDEPENDENT sub-collectives built from ``ppermute``
    # hops + local adds — double-buffered in the sense that chunk ``c+1``'s
    # local adds are free to run while chunk ``c``'s hop is on the wire.
    # Chunk layout matches the tiled ``psum_scatter``/``all_gather`` pair
    # exactly (rank r owns the r-th CONTIGUOUS slice), so ZeRO's
    # reduce-scatter → update → all-gather dance can swap primitives
    # without relayouting its optimizer-state chunks.
    #
    # ``codec=`` (ISSUE 15) fuses a compression codec INTO the hops: every
    # reduce-scatter ``ppermute`` carries the quantized partial sum
    # (payload + the codec's f32 sidecar), the receiver dequantizes and
    # adds its own block in fp32 (the accumulation-dtype contract —
    # quantization error enters per hop, never through the accumulator),
    # and the allgather phase quantizes each rank's finished chunk exactly
    # ONCE, forwarding the payload unchanged hop to hop.  Compressed bytes
    # are what cross the wire — a 4x payload reduction for the u8/int8/fp8
    # codecs minus the sidecar.  ``codec=None`` is byte-for-byte the
    # pre-codec construction (HLO-pinned).

    def _ring_valid(self) -> bool:
        """Ring forms need a single nontrivial mesh axis to permute over."""
        return len(self.axes) == 1 and self.nranks() > 1

    def _ring_blocks(self, x, n):
        """[n*m, ...] -> per-rank-block view [n, m, ...] plus a traced
        block selector (dynamic_slice: block index depends on the rank)."""
        assert x.shape[0] % n == 0, (x.shape, n)
        blocks = x.reshape((n, x.shape[0] // n) + x.shape[1:])

        def block(i):
            return jnp.squeeze(
                lax.dynamic_slice_in_dim(blocks, i % n, 1, axis=0), 0
            )

        return blocks, block

    def _ring_reduce_scatter_1(self, x, op: ReduceOp, codec=None):
        """One ring: rank r ends with the reduction of every rank's r-th
        block.  The partial sum for block b starts at rank ``(b+1) % n`` and
        travels +1 per hop, each rank adding its own contribution — n-1
        ``ppermute`` hops, each moving 1/n of the bytes (bandwidth-optimal,
        like NCCL's ring).  With ``codec``: quantize-on-send (every hop
        carries the codec payload + sidecar), dequantize and accumulate in
        fp32 on receive — the compressed output stays f32."""
        n = self.nranks()
        if op not in (ReduceOp.SUM, ReduceOp.AVG):
            raise ValueError(f"ring reduce_scatter supports SUM/AVG, got {op}")
        r = self.rank()
        _, block = self._ring_blocks(x, n)
        perm = [(i, (i + 1) % n) for i in range(n)]
        if codec is None:
            buf = block(r - 1)
            # unrolled: every hop is its own ppermute instruction, so the
            # scheduler may pipeline hop s+1's local add under hop s's wire
            # time
            for s in range(n - 1):
                buf = self.ppermute(buf, perm)
                buf = buf + block(r - 2 - s)
            if op == ReduceOp.AVG:
                buf = buf / n
            return buf
        buf = block(r - 1).astype(jnp.float32)
        m = buf.shape[0]
        for s in range(n - 1):
            parts = codec.encode(buf[None])
            parts = tuple(self.ppermute(p, perm) for p in parts)
            # m is explicit: the bit-packed/variable-payload codecs cannot
            # invert payload shape -> element count
            buf = codec.decode(parts, m)[0] \
                + block(r - 2 - s).astype(jnp.float32)
        if op == ReduceOp.AVG:
            buf = buf / n
        return buf

    def _ring_allgather_1(self, x, codec=None):
        """One ring: input is this rank's block, output is all blocks in
        rank order (``[n * m, ...]``) — the inverse of
        :meth:`_ring_reduce_scatter_1`'s ownership layout.  With ``codec``:
        this rank's block is quantized exactly ONCE; the hops forward the
        payload unchanged (no re-quantization in the broadcast phase), and
        the stacked parts decode in one chunked pass at the end."""
        n = self.nranks()
        r = self.rank()
        perm = [(i, (i + 1) % n) for i in range(n)]
        if codec is None:
            out = jnp.zeros((n,) + x.shape, x.dtype)
            out = lax.dynamic_update_slice_in_dim(out, x[None], r % n, axis=0)
            buf = x
            for s in range(n - 1):
                buf = self.ppermute(buf, perm)
                out = lax.dynamic_update_slice_in_dim(
                    out, buf[None], (r - 1 - s) % n, axis=0
                )
            return out.reshape((n * x.shape[0],) + x.shape[1:])
        cur = [p[0] for p in codec.encode(x[None])]
        stacked = [jnp.zeros((n,) + c.shape, c.dtype) for c in cur]
        stacked = [
            lax.dynamic_update_slice_in_dim(o, c[None], r % n, axis=0)
            for o, c in zip(stacked, cur)
        ]
        for s in range(n - 1):
            cur = [self.ppermute(c, perm) for c in cur]
            stacked = [
                lax.dynamic_update_slice_in_dim(o, c[None], (r - 1 - s) % n,
                                                axis=0)
                for o, c in zip(stacked, cur)
            ]
        return codec.decode(tuple(stacked), x.shape[0]).reshape(-1)

    def _ring_chunk_views(self, x, num_chunks: int, n: int):
        """Split flat ``x`` into ``num_chunks`` independent sub-buffers such
        that concatenating each rank's sub-results reproduces the CONTIGUOUS
        per-rank chunk layout: sub-chunk j is the concatenation over ranks of
        each rank-block's j-th slice (``x.reshape(n, k, -1)[:, j]``)."""
        m = x.shape[0] // n
        assert m % num_chunks == 0, (m, num_chunks)
        view = x.reshape(n, num_chunks, m // num_chunks)
        return [view[:, j].reshape(-1) for j in range(num_chunks)]

    @staticmethod
    def _resolve_codec(codec):
        """Lazy registry resolution (``compression`` imports this module,
        so the codec registry cannot be a module-level import here)."""
        if codec is None:
            return None
        from .compression.codecs import resolve_codec

        return resolve_codec(codec)

    def ring_reduce_scatter(self, x, op: ReduceOp = ReduceOp.SUM,
                            num_chunks: int = 1, codec=None):
        """Chunked ring reduce-scatter of flat ``x`` (``size % nranks == 0``;
        ``num_chunks`` must divide the per-rank block).  Returns this rank's
        contiguous slice — same layout as ``reduce_scatter(..., tiled)``.
        ``codec`` (a name or :class:`~bagua_tpu.compression.codecs.RingCodec`)
        compresses every hop; the output is the fp32 accumulation cast back
        to ``x.dtype``.  Ring-invalid communicators fall back to the fused
        full-precision primitive (a 1-rank tier has no wire to compress)."""
        codec = self._resolve_codec(codec)
        if not self._ring_valid():
            return self.reduce_scatter(x, op)
        n = self.nranks()
        if num_chunks <= 1:
            parts = [x]
        else:
            parts = self._ring_chunk_views(x, num_chunks, n)
        outs = [self._ring_reduce_scatter_1(p, op, codec) for p in parts]
        out = outs[0] if len(outs) == 1 else jnp.concatenate(outs)
        return out.astype(x.dtype) if codec is not None else out

    def ring_allgather(self, x, num_chunks: int = 1, codec=None):
        """Chunked ring all-gather of this rank's flat chunk; inverse of
        :meth:`ring_reduce_scatter` (``[m] -> [nranks * m]`` in rank
        order).  ``codec`` quantizes this rank's chunk once and moves only
        the payload+sidecar per hop (every receiver decodes the same
        payload, so all ranks still agree bitwise on the result)."""
        codec = self._resolve_codec(codec)
        if not self._ring_valid():
            return self.allgather(x, axis=0, tiled=True)
        n = self.nranks()
        if num_chunks <= 1:
            out = self._ring_allgather_1(x, codec)
            return out.astype(x.dtype) if codec is not None else out
        mk = x.shape[0] // num_chunks
        subs = x.reshape(num_chunks, mk)
        gathered = [
            self._ring_allgather_1(subs[j], codec) for j in range(num_chunks)
        ]
        out = jnp.stack([g.reshape(n, mk) for g in gathered], axis=1)
        out = out.reshape(n * x.shape[0])
        return out.astype(x.dtype) if codec is not None else out

    def ring_allreduce(self, x, op: ReduceOp = ReduceOp.AVG,
                       num_chunks: int = 1, codec=None):
        """Chunked double-buffered ring allreduce: reduce-scatter ring then
        all-gather ring per chunk.  Wire bytes equal the monolithic
        allreduce's ring model (``2(n-1)/n`` of the buffer); what changes is
        schedulability — ``num_chunks`` independent chains the
        latency-hiding scheduler can interleave with compute and each
        other.  Buffers that don't split evenly are zero-padded internally
        (sound for SUM/AVG) and sliced back — unlike the scatter/gather
        pair, whose ownership layout forbids silent padding.

        ``codec`` makes compressed bytes what actually cross the wire: the
        reduce-scatter hops carry quantized partial sums (dequantize +
        fp32 accumulate per hop), the finished chunk — already divided for
        AVG — is re-quantized exactly once, and the allgather hops forward
        that payload unchanged.  ``codec=None`` is the exact pre-codec
        construction (HLO-pinned by tests/test_compressed_ring.py)."""
        codec = self._resolve_codec(codec)
        if not self._ring_valid():
            return self.allreduce(x, op)
        n = self.nranks()
        size = x.shape[0]
        pad = (-size) % (n * max(1, num_chunks))
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
        if num_chunks <= 1:
            out = self._ring_allgather_1(
                self._ring_reduce_scatter_1(x, op, codec), codec
            )
            if codec is not None:
                out = out.astype(x.dtype)
            return out[:size] if pad else out
        parts = self._ring_chunk_views(x, num_chunks, n)
        outs = [
            self._ring_allgather_1(self._ring_reduce_scatter_1(p, op, codec),
                                   codec)
            for p in parts
        ]
        # each sub-result is [n, m/num_chunks] in rank order; re-interleave
        # back to the original flat element order
        mk = parts[0].shape[0] // n
        out = jnp.stack([o.reshape(n, mk) for o in outs], axis=1)
        out = out.reshape(x.shape)
        if codec is not None:
            out = out.astype(x.dtype)
        return out[:size] if pad else out

    def broadcast(self, x, src: int = 0):
        """Every rank gets rank ``src``'s value (reference broadcast
        communication.py:270-300)."""
        # select src's contribution via masked psum (one all-reduce; on ICI
        # XLA lowers this to an efficient broadcast tree)
        idx = self.rank()
        contrib = jnp.where(idx == src, x, jnp.zeros_like(x))
        return lax.psum(contrib, self.axes)

    #: Largest step-pairing period precompiled into one program.  shift_one's
    #: period is world/2, so this admits meshes to 256-way gossip out of the
    #: box.  Measured on XLA:CPU (tests/test_compile_scale.py): the
    #: ``lax.switch`` costs one ppermute instruction per period step — compile
    #: time 0.06/0.08/0.31 s at 32/64/256 devices (flat in practice), program
    #: text O(period × nranks).  The cap turns the far-out hazard (a pod-scale
    #: gossip axis compiling thousands of branches) into an explicit error.
    MAX_EXCHANGE_PERIOD = env.get_max_exchange_period()

    def exchange_with_peer(self, x, peer_fn: Callable[[int, int, int], int], step):
        """Pairwise send/recv with a step-dependent symmetric pairing.

        ``peer_fn(rank, nranks, step) -> peer`` must be an involution for each
        step (peer(peer(r)) == r), as in the reference's shift_one exchange
        (decentralized_full_precision_synchronous.rs:79-83).  ``step`` may be a
        traced integer; the pairing must be periodic in ``step`` with period
        dividing ``nranks`` (branches are precompiled with ``lax.switch``; the
        executed path is always exactly ONE ppermute — wire cost does not
        grow with mesh size, only program metadata does, bounded by
        :attr:`MAX_EXCHANGE_PERIOD`).
        """
        n = self.nranks()
        period_perms = []
        seen = {}
        # stop enumerating as soon as the cap is provably exceeded — at pod
        # scale the full table is O(n^2) tuples, pathological to even build
        limit = min(n, self.MAX_EXCHANGE_PERIOD + 1)
        for s in range(limit):
            perm = tuple((r, int(peer_fn(r, n, s))) for r in range(n))
            if perm in seen and s > 0:
                break
            seen[perm] = s
            period_perms.append(perm)
        period = len(period_perms)
        if period > self.MAX_EXCHANGE_PERIOD:
            raise ValueError(
                f"exchange_with_peer: pairing period exceeds the precompile "
                f"cap {self.MAX_EXCHANGE_PERIOD} (program size grows as "
                f"period x nranks).  Raise BAGUA_MAX_EXCHANGE_PERIOD to "
                f"accept the compile cost, or use peer_selection_mode='all' "
                f"on meshes this large."
            )
        branches = [partial(lambda p, v: self.ppermute(v, p), list(p)) for p in period_perms]
        return lax.switch(step % period, branches, x)

    def barrier(self):
        """Device-level barrier: a tiny psum over the axes (reference
        communicators/mod.rs:973-982 uses a 1-element allreduce too)."""
        return lax.psum(jnp.ones((), jnp.int32), self.axes)


#: compile-size guard for the chunked rings (see :func:`ring_chunks_for`)
MAX_RING_CHUNKS = env.get_max_ring_chunks()

#: link classes of a hierarchical mesh's tiers: the ``intra`` axis rides
#: ICI (slice-local interconnect), the ``inter`` axis rides DCN (the
#: cross-slice data-center network, orders of magnitude less bandwidth).
#: Per-tier chunk sizing targets different bytes per link class — a chunk
#: sized for ICI is far too small to amortize a DCN hop.
LINK_ICI = "ici"
LINK_DCN = "dcn"


def largest_divisor_leq(m: int, k: int) -> int:
    """Largest divisor of ``m`` that is <= ``k`` (``m >= 1``, ``k >= 1``).

    Direct O(sqrt(m)) divisor enumeration — the old ``while m % k: k -= 1``
    scan was O(m) for prime per-rank blocks (a 1e6-element prime block
    walked a million candidates on every host-side sizing call)."""
    if k >= m:
        return m
    best = 1
    i = 1
    while i * i <= m:
        if m % i == 0:
            if i <= k and i > best:
                best = i
            j = m // i
            if j <= k and j > best:
                best = j
        i += 1
    return best


def ring_chunks_for(numel: int, itemsize: int, nranks: int,
                    chunk_bytes: Optional[int],
                    link_class: str = LINK_ICI) -> int:
    """Host-side sizing for the chunked ring collectives: the number of
    independent sub-collectives such that each carries ~``chunk_bytes`` of
    this rank's payload per hop (``ring_allreduce`` zero-pads indivisible
    buffers, so the per-rank block is the padded one).  1 = monolithic.

    ``chunk_bytes`` may be an int (one target for every link) or a mapping
    ``{link_class: bytes}`` resolved by ``link_class`` — how the two tiers
    of a hierarchical collective size their chunks against different
    targets (:data:`LINK_ICI` vs :data:`LINK_DCN`).  A class absent from
    the mapping means NO chunking for that class — falling back from a
    missing tier knob to the link-agnostic target is
    :meth:`AlgorithmContext.chunk_bytes_for`'s job, which resolves to an
    int before calling here."""
    if isinstance(chunk_bytes, dict):
        chunk_bytes = chunk_bytes.get(link_class) or 0
    if not chunk_bytes or nranks <= 1:
        return 1
    m = -(-numel // nranks)  # per-rank block after the ring's padding
    k = max(1, int(round(m * itemsize / chunk_bytes)))
    # each sub-ring unrolls into 2(n-1) ppermute instructions, so k is
    # capped: a tiny chunk_bytes against a 10 MiB bucket would otherwise
    # emit thousands of collectives per bucket and stall/OOM the compiler
    k = min(k, m, MAX_RING_CHUNKS)
    # num_chunks must divide the per-rank block
    return largest_divisor_leq(m, k)


class BaguaBackend:
    """Per-process comm backend: mesh + the 3 standard communicators.

    Counterpart of ``get_backend`` (communication.py:47-72) which builds
    global / intra-node / inter-node communicators and a dedicated CUDA
    stream.  There is no comm stream to manage on TPU — XLA schedules
    collectives asynchronously — so this only owns mesh topology.
    """

    def __init__(self, mesh: Optional[Mesh] = None, intra_size: Optional[int] = None):
        if mesh is None:
            from .parallel.mesh import get_global_mesh_if_set

            mesh = get_global_mesh_if_set()
        if mesh is None:
            mesh = hierarchical_mesh(intra_size=intra_size)
        self.mesh = mesh
        names = mesh.axis_names
        if "inter" in names and "intra" in names:
            glob = collapse_trivial_axes(mesh, ("inter", "intra"))
            self.global_communicator = BaguaCommunicator(glob, mesh)
            self.internode_communicator = BaguaCommunicator("inter", mesh)
            self.intranode_communicator = BaguaCommunicator("intra", mesh)
        else:
            dp_axis = names[0]
            self.global_communicator = BaguaCommunicator(dp_axis, mesh)
            self.internode_communicator = self.global_communicator
            self.intranode_communicator = self.global_communicator


_BACKENDS = {}


def get_backend(model_name: str = "") -> BaguaBackend:
    """Per-process backend cache, keyed by model name AND validated against
    the live global mesh: after an elastic resize or ``set_global_mesh`` the
    cached backend's communicators span the DEAD topology — handing them
    back would dispatch collectives over devices that left the world.  A
    cached entry whose mesh is not the currently registered global mesh is
    rebuilt (identity check: an elastic resize always constructs a new
    ``Mesh``, and re-registering the same object is a no-op)."""
    from .parallel.mesh import get_global_mesh_if_set

    live = get_global_mesh_if_set()
    backend = _BACKENDS.get(model_name)
    if backend is not None and live is not None and backend.mesh is not live:
        backend = None
    if backend is None:
        backend = BaguaBackend()
        _BACKENDS[model_name] = backend
    return backend


_autotune_server = None


def start_autotune_server():
    """Start the autotune sidecar in a daemon process on this host
    (reference communication.py:95-104)."""
    global _autotune_server
    if _autotune_server is not None:
        return
    import multiprocessing

    from .service.autotune_service import run_autotune_server

    _autotune_server = multiprocessing.Process(
        target=run_autotune_server,
        kwargs=dict(
            port=env.get_bagua_service_port(),
            world_size=env.get_world_size(),
            autotune_level=env.get_autotune_level(),
            max_samples=env.get_autotune_max_samples(),
            sampling_confidence_time_s=env.get_autotune_sampling_confidence_time_s(),
            warmup_time_s=env.get_autotune_warmup_time_s(),
            is_output_autotune_log=env.is_output_autotune_log(),
            default_bucket_size=env.get_default_bucket_size(),
            tune_algorithm=env.is_autotune_algorithm_on(),
        ),
        daemon=True,
    )
    _autotune_server.start()


@lru_cache(maxsize=None)
def get_hyperparameters_service_client():
    from .service.autotune_service import AutotuneClient

    return AutotuneClient(env.get_master_addr(), env.get_bagua_service_port())


def init_process_group(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    mesh: Optional[Mesh] = None,
):
    """Initialize distributed state; call before other bagua_tpu APIs.

    TPU-native replacement for ``bagua.init_process_group``
    (communication.py:107-137): instead of a NCCL unique-id rendezvous through
    a c10d store, multi-host bring-up is ``jax.distributed.initialize`` (the
    JAX coordination service), after which every host sees the full device
    set and the global mesh spans all chips.
    """
    env_addr = env.get_coordinator_addr()
    if coordinator_address is not None or env_addr:
        addr = coordinator_address or env_addr
        # CPU-simulation multiprocess runs need an explicit cross-process
        # collectives backend on jax versions where the CPU default is
        # "none" ("Multiprocess computations aren't implemented on the CPU
        # backend"); gloo is the stdlib-shipped one.  TPU/GPU unaffected.
        plat = os.environ.get("JAX_PLATFORMS", "") or str(
            getattr(jax.config, "jax_platforms", None) or "")
        if "cpu" in plat.lower():
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # pragma: no cover - option renamed/removed
                pass
        # pass None through when env vars are unset so jax auto-detects;
        # do NOT call jax.process_count() here — it would initialize the
        # local backend and break distributed bring-up
        if num_processes is None and os.environ.get("WORLD_SIZE"):
            num_processes = int(os.environ["WORLD_SIZE"])
        if process_id is None and os.environ.get("RANK"):
            process_id = int(os.environ["RANK"])
        jax.distributed.initialize(
            coordinator_address=addr,
            num_processes=num_processes,
            process_id=process_id,
        )
    if env.get_rank() == 0 and env.get_bagua_service_port() > 0:
        start_autotune_server()
    if mesh is None:
        mesh = build_mesh()
    set_global_mesh(mesh)
    return mesh


# ---------------------------------------------------------------------------
# Eager collective primitives (reference communication.py:230-852).
#
# Semantics: the input's leading axis enumerates ranks (size == communicator
# world size).  ``allreduce(x)[r] == reduce_r' x[r']`` for every r — exactly
# what each process observes after the reference's synchronous collective.
#
# Multi-process: each process passes ITS slice of the rank axis — one row per
# communicator rank it OWNS.  Ranks are mesh positions (devices), so a process
# driving one device passes a leading axis of size 1 (the per-rank call shape
# of the reference API), while a process driving k local devices must pass all
# k of its rows.  _eager validates the local leading dim against the owned
# rank count and stitches the slices into one global array before dispatch,
# so the reference's "every rank calls with its own tensor" usage ports
# directly.
# ---------------------------------------------------------------------------


# compiled eager primitives, keyed on (mesh, axes, op signature, arg avals):
# re-tracing `jit(shard_map(...))` on every standalone-collective call would
# make the reference's synchronous primitive API pay a trace+dispatch cost
# per invocation
_EAGER_CACHE: dict = {}

# (mesh, axes) -> rank rows this process must feed; constant per mesh, and a
# Python scan over every mesh device is too slow to repeat per eager call
_OWNED_RANK_CACHE: dict = {}


def _owned_rank_count(comm: "BaguaCommunicator") -> int:
    """Number of DISTINCT rank-axis positions among this process's devices —
    the per-process row count for eager per-rank call shapes.  Not a
    proportional formula: with extra non-comm mesh axes a process's devices
    can cover several — or repeat the same — rank indices."""
    mesh = comm.mesh
    key = (mesh, comm.axes)
    cached = _OWNED_RANK_CACHE.get(key)
    if cached is not None:
        return cached
    import numpy as _np

    axis_idx = [mesh.axis_names.index(ax) for ax in comm.axes]
    me = jax.process_index()
    owned = {
        tuple(coord[i] for i in axis_idx)
        for coord, d in _np.ndenumerate(mesh.devices)
        if d.process_index == me
    }
    _OWNED_RANK_CACHE[key] = len(owned)
    return len(owned)


def _eager(comm: Optional[BaguaCommunicator], key, fn, *arrays):
    """Run ``fn`` once per rank: inputs' leading axis is the rank axis; inside
    ``fn`` each rank sees its own tensor (leading axis stripped).  ``key``
    identifies the operation (name + static params) for the compile cache."""
    check_abort()  # aborted communicators fail new dispatches fast
    comm = comm if comm is not None else get_backend("").global_communicator
    mesh = comm.mesh
    if jax.process_count() > 1:
        # per-rank call semantics: each process contributes one row per
        # communicator rank (= mesh device) it owns; host arrays are
        # stitched into one global array (already-global jax.Arrays pass
        # through untouched)
        from .parallel.mesh import make_global_array

        expected = _owned_rank_count(comm)
        for a in arrays:
            if isinstance(a, jax.Array) and not a.is_fully_addressable:
                continue
            rows = jnp.shape(a)[0] if jnp.ndim(a) else None
            if rows is not None and rows != expected:
                raise ValueError(
                    f"eager collective: this process owns {expected} of the "
                    f"{comm.nranks()} communicator ranks and must pass "
                    f"exactly that many rows along the leading rank axis, "
                    f"got {rows}"
                )
        in_spec = P(comm.axis_name if len(comm.axes) == 1 else comm.axes)
        arrays = tuple(
            a if isinstance(a, jax.Array) and not a.is_fully_addressable
            else make_global_array(mesh, in_spec, a)
            for a in arrays
        )
    else:
        arrays = tuple(jnp.asarray(a) for a in arrays)
    cache_key = (
        mesh, comm.axes, key,
        tuple((a.shape, a.dtype.name) for a in arrays),
    )
    compiled = _EAGER_CACHE.get(cache_key)
    if compiled is None:
        spec = P(comm.axis_name if len(comm.axes) == 1 else comm.axes)

        def wrapped(*blocks):
            out = fn(*[b[0] for b in blocks])
            return jax.tree.map(lambda o: jnp.expand_dims(o, 0), out)

        compiled = jax.jit(
            shard_map(
                wrapped, mesh=mesh, in_specs=tuple(spec for _ in arrays),
                out_specs=spec, check_vma=False,
            )
        )
        _EAGER_CACHE[cache_key] = compiled
    out = compiled(*arrays)
    _watch_eager(out, key)
    return out


def _watch_eager(out, key) -> None:
    """Fence standalone eager collectives with the global hang watchdog.

    The trainer's steps are watchdog-fenced via ``watch_result``; without
    this, a wedged ``allreduce()`` OUTSIDE the trainer would hang silently —
    the reference's comm monitor covers every scheduled op, not only
    training ones (bagua-core-internal/src/lib.rs:255-265)."""
    from .watchdog import get_comm_timeout_s, get_global_watchdog

    timeout = get_comm_timeout_s()
    if timeout is None:
        return
    leaves = jax.tree_util.tree_leaves(out)
    if not leaves:
        return
    leaf = leaves[0]
    try:
        # fence on ONE local shard, not the stacked global result: the
        # shard's buffer is ready exactly when the collective completed
        # locally, and the waiter's readback then transfers a single
        # rank-row instead of the whole [nranks, ...] output
        fence = leaf.addressable_shards[0].data
    except Exception:
        fence = leaf
    get_global_watchdog(timeout).watch_result(
        fence, f"eager:{key[0] if isinstance(key, tuple) else key}"
    )


def _comm_or_default(comm):
    return comm if comm is not None else get_backend("").global_communicator


def allreduce(send, op: ReduceOp = ReduceOp.AVG, comm: Optional[BaguaCommunicator] = None):
    """Reduce across the rank axis; every rank slice gets the result
    (reference communication.py:427-495)."""
    c = _comm_or_default(comm)
    return _eager(comm, ("allreduce", int(op)), lambda x: c.allreduce(x, op), send)


def allreduce_inplace(tensor, op: ReduceOp = ReduceOp.AVG, comm=None):
    return allreduce(tensor, op, comm)


def allgather(send, comm: Optional[BaguaCommunicator] = None):
    """Each rank slice becomes the concatenation of all slices
    (reference communication.py:498-560)."""
    c = _comm_or_default(comm)
    return _eager(comm, ("allgather",),
                  lambda x: c.allgather(x, axis=0, tiled=True), send)


allgather_inplace = allgather


def reduce_scatter(send, op: ReduceOp = ReduceOp.SUM, comm=None):
    c = _comm_or_default(comm)
    return _eager(comm, ("reduce_scatter", int(op)),
                  lambda x: c.reduce_scatter(x, op, axis=0), send)


reduce_scatter_inplace = reduce_scatter


def alltoall(send, comm=None):
    c = _comm_or_default(comm)
    return _eager(comm, ("alltoall",), lambda x: c.alltoall_tiled(x, 0, 0), send)


alltoall_inplace = alltoall


def alltoall_v(send, send_counts, output_size: Optional[int] = None, comm=None):
    """Ragged all-to-all (reference ``alltoall_v``,
    communicators/mod.rs:632-676).

    ``send``: ``[nranks, L, ...]`` — each rank slice packs its outgoing chunks
    consecutively (chunk for rank 0 first).  ``send_counts``: static
    ``[nranks, nranks]`` matrix (Python/numpy ints); ``send_counts[r][d]`` =
    elements rank r sends to rank d.  Returns ``[nranks, output_size, ...]``
    where each rank slice packs the chunks received from rank 0, 1, ...
    consecutively, zero-padded to ``output_size`` (default: the max total
    receive count — XLA needs one static shape across ranks).
    """
    import numpy as np

    c = _comm_or_default(comm)
    counts = np.asarray(send_counts, dtype=np.int64)
    n = c.nranks()
    if counts.shape != (n, n):
        raise ValueError(f"send_counts must be [{n},{n}], got {counts.shape}")
    recv_counts = counts.T  # recv_counts[d][s] = what d receives from s
    need = int(recv_counts.sum(axis=1).max())
    out_size = need if output_size is None else int(output_size)
    if out_size < need:
        raise ValueError(f"output_size {out_size} < max receive total {need}")
    # static per-rank offset tables, gathered inside the traced fn by rank
    input_offsets = np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(counts, axis=1)[:, :-1]], axis=1
    )
    recv_offsets = np.concatenate(
        [np.zeros((n, 1), np.int64), np.cumsum(recv_counts, axis=1)[:, :-1]],
        axis=1,
    )
    # output_offsets[r][d]: where rank r's chunk lands in rank d's output
    output_offsets = recv_offsets.T.copy()

    # XLA's native ragged-all-to-all exists on TPU; elsewhere (the CPU test
    # mesh) fall back to a padded dense all_to_all + masked scatter with
    # identical semantics.
    native = c.mesh.devices.flat[0].platform == "tpu"
    key = ("alltoall_v", native, counts.tobytes(), out_size)

    def fn_native(x):
        r = c.rank()
        sel = lambda table: jnp.asarray(table)[r]
        output = jnp.zeros((out_size,) + x.shape[1:], x.dtype)
        return c.alltoall_v(
            x, output, sel(input_offsets), sel(counts),
            sel(output_offsets), sel(recv_counts.copy()),
        )

    maxc = max(1, int(counts.max()))

    def fn_padded(x):
        r = c.rank()
        sel = lambda table: jnp.asarray(table)[r]
        my_counts, my_in_off = sel(counts), sel(input_offsets)
        my_recv_counts, my_recv_off = sel(recv_counts.copy()), sel(recv_offsets)
        # pack chunk for each destination into a padded [n, maxc, ...] buffer
        xp = jnp.concatenate(
            [x, jnp.zeros((maxc,) + x.shape[1:], x.dtype)], axis=0
        )
        idx = my_in_off[:, None] + jnp.arange(maxc)[None, :]        # [n, maxc]
        valid_out = jnp.arange(maxc)[None, :] < my_counts[:, None]
        padded = jnp.where(
            valid_out.reshape(n, maxc, *([1] * (x.ndim - 1))),
            xp[idx], 0,
        )
        got = c.alltoall(padded)                                    # [n, maxc, ...]
        # recompose: element j of chunk-from-s lands at recv_off[s]+j,
        # padding lands in a dump slot past the end
        valid_in = jnp.arange(maxc)[None, :] < my_recv_counts[:, None]
        tgt = jnp.where(
            valid_in, my_recv_off[:, None] + jnp.arange(maxc)[None, :], out_size
        )
        out = jnp.zeros((out_size + 1,) + x.shape[1:], x.dtype)
        out = out.at[tgt.reshape(-1)].set(
            got.reshape((n * maxc,) + x.shape[1:])
        )
        return out[:out_size]

    return _eager(comm, key, fn_native if native else fn_padded, send)


def broadcast(tensor, src: int = 0, comm=None):
    c = _comm_or_default(comm)
    return _eager(comm, ("broadcast", src), lambda x: c.broadcast(x, src), tensor)


def reduce(send, dst: int, op: ReduceOp = ReduceOp.SUM, comm=None, recv=None):
    """Only rank ``dst``'s slice holds the reduction (reference
    communication.py:331-375: the collective writes ONLY dst's recv buffer).
    Non-dst output slices reproduce ``recv`` — the functional analog of the
    reference's untouched recv tensor — or zeros when no ``recv`` is given."""
    c = _comm_or_default(comm)

    if recv is None:
        def fn(x):
            red = c.allreduce(x, op)
            return jnp.where(c.rank() == dst, red, jnp.zeros_like(red))

        return _eager(comm, ("reduce", dst, int(op), False), fn, send)

    def fn2(x, r):
        red = c.allreduce(x, op)
        return jnp.where(c.rank() == dst, red, r)

    return _eager(comm, ("reduce", dst, int(op), True), fn2, send, recv)


def gather(send, dst: int, comm=None, recv=None):
    """Rank ``dst``'s output slice holds every rank's data concatenated
    (``[nranks * rows, ...]``); non-dst slices reproduce ``recv`` — the
    reference leaves their recv buffers untouched
    (communication.py:576-614) — or zeros when no ``recv`` is given."""
    c = _comm_or_default(comm)

    if recv is None:
        def fn(x):
            g = c.allgather(x, axis=0, tiled=True)
            return jnp.where(c.rank() == dst, g, jnp.zeros_like(g))

        return _eager(comm, ("gather", dst, False), fn, send)

    def fn2(x, r):
        g = c.allgather(x, axis=0, tiled=True)
        return jnp.where(c.rank() == dst, g, r)

    return _eager(comm, ("gather", dst, True), fn2, send, recv)


def scatter(send, src: int, comm=None):
    """Rank r receives chunk r of rank ``src``'s data.  ``send``'s rank slices
    each hold the full [nranks*chunk] buffer; output slices hold one chunk."""
    c = _comm_or_default(comm)

    def fn(x):
        full = c.broadcast(x, src)
        n = c.nranks()
        chunks = full.reshape((n, -1) + full.shape[1:])
        return jnp.squeeze(lax.dynamic_slice_in_dim(chunks, c.rank(), 1, axis=0), 0)

    return _eager(comm, ("scatter", src), fn, send)


def send_recv(send, peer_perm: List[Tuple[int, int]], comm=None):
    """Point-to-point exchange expressed as a permutation (reference send/recv
    communication.py:233-267 — on TPU p2p is ``ppermute`` over ICI)."""
    c = _comm_or_default(comm)
    perm = tuple((int(a), int(b)) for a, b in peer_perm)
    return _eager(comm, ("send_recv", perm), lambda x: c.ppermute(x, perm), send)


def barrier(comm=None):
    c = _comm_or_default(comm)
    # per-rank call shape: one row per rank THIS process owns (multi-process
    # passes only its slice, like every other eager primitive)
    rows = _owned_rank_count(c) if jax.process_count() > 1 else c.nranks()
    out = _eager(comm, ("barrier",),
                 lambda x: c.barrier() * jnp.ones((1,), jnp.int32),
                 jnp.zeros((rows, 1), jnp.int32))
    jax.block_until_ready(out)
