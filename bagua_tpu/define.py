"""Shared typed definitions.

Counterpart of the reference's ``bagua/bagua_define.py`` (TensorDeclaration :18,
BaguaHyperparameter :34, BaguaCoreTelemetrySpan :53).  Same wire shape so the
autotune HTTP protocol stays compatible.
"""

from __future__ import annotations

import enum
from typing import List

from pydantic import BaseModel


class TensorDtype(str, enum.Enum):
    F32 = "f32"
    F16 = "f16"
    BF16 = "bf16"
    U8 = "u8"
    I32 = "i32"
    I64 = "i64"


DTYPE_BYTES = {
    TensorDtype.F32: 4,
    TensorDtype.F16: 2,
    TensorDtype.BF16: 2,
    TensorDtype.U8: 1,
    TensorDtype.I32: 4,
    TensorDtype.I64: 8,
}


class TensorDeclaration(BaseModel):
    name: str
    num_elements: int
    dtype: TensorDtype

    def __hash__(self):  # used in ordering / dedup
        return hash((self.name, self.num_elements, self.dtype))

    @property
    def nbytes(self) -> int:
        return self.num_elements * DTYPE_BYTES[TensorDtype(self.dtype)]


def get_tensor_declaration_bytes(td: TensorDeclaration) -> int:
    return td.nbytes


class BaguaHyperparameter(BaseModel):
    """Tunable hyperparameters mutated by the autotune service
    (reference bagua_define.py:34-50)."""

    buckets: List[List[TensorDeclaration]] = []
    is_hierarchical_reduce: bool = False
    bucket_size: int = 10 * 1024 ** 2
    #: algorithm family recommended by the autotuner ("" = keep current);
    #: TPU extension over the reference — BASELINE.json requires the
    #: centralized/decentralized/low-precision families to be selectable
    algorithm: str = ""
    #: overlap-scheduler dispatch gate ("auto"|"on"|"off"; "" = keep
    #: current) — rides the recommendation path so re-bucketing and
    #: overlap tuning compose (TPU extension, ISSUE 2)
    overlap: str = ""
    #: chunked-ring sub-collective size in bytes (0 = keep current)
    overlap_chunk_bytes: int = 0
    #: per-bandwidth-tier chunk targets for hierarchical two-level
    #: collectives (docs/hierarchical.md): the slice-local ICI stages and
    #: the cross-slice DCN stage size their ring chunks against different
    #: bytes (0 = keep current / fall back to ``overlap_chunk_bytes``)
    overlap_chunk_bytes_intra: int = 0
    overlap_chunk_bytes_inter: int = 0
    #: per-link-class codec policy (docs/compression.md): what the ring
    #: hops of each bandwidth tier carry on the wire — ``off``/``auto``/a
    #: codec name ("" = keep current).  ``compress_inter`` is the knob the
    #: autopilot's ``compress_dcn`` trend hint actuates through the
    #: recommendation path (compress the slow link when DCN seconds
    #: dominate the step)
    compress_intra: str = ""
    compress_inter: str = ""
    #: bucket-flat residency of the training state ("on"|"off"; "" = keep
    #: current).  A live flip queues a flat<->leaf state migration on the
    #: trainer (same conversion the checkpoint path uses), so the v2
    #: search can trade the per-step flatten against relayout cost
    flat_resident: str = ""

    def update(self, param_dict: dict) -> "BaguaHyperparameter":
        tmp = self.model_dump()
        tmp.update(param_dict)
        for key, value in param_dict.items():
            if key in tmp:
                self.__dict__[key] = value
        return self


class BaguaCoreTelemetrySpan(BaseModel):
    trace_id: int
    action: str
    tensor_name: str
    start_time: int
    end_time: int
