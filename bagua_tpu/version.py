__version__ = "0.1.0"


def show_version() -> str:
    """Build/runtime diagnostics (reference ``show_version``,
    bagua-core-internal/src/lib.rs:103-123: shadow_rs build info + NCCL
    version — here jax/jaxlib/backend in their place)."""
    import jax

    lines = [
        f"bagua_tpu {__version__}",
        f"jax {jax.__version__}",
        f"backend {jax.default_backend()} ({len(jax.devices())} devices)",
    ]
    out = "\n".join(lines)
    print(out)
    return out
