"""BaguaTrainer — the training-loop integration (``with_bagua`` equivalent).

Counterpart of the reference's ``BaguaModule``
(/root/reference/bagua/torch_api/distributed.py:244-508) plus the Rust
``BaguaCommBackend`` scheduler
(/root/reference/rust/bagua-core/bagua-core-internal/src/lib.rs:158-337).

The reference splits one training step across Python hooks, a Rust readiness
scheduler, and a comm worker thread so NCCL calls overlap backward compute.
On TPU the same step is ONE jitted SPMD program: ``shard_map`` over the
data-parallel mesh axes, collectives placed by the algorithm's stages, overlap
done by XLA's async collectives.  What survives of the scheduler is its
*bookkeeping*: bucket plans, re-bucketing on autotune updates, phase switches
(``need_reset``) — all host-side here, each yielding a cached compiled step.
"""

from __future__ import annotations

import logging
import threading
import time
from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, PartitionSpec as P
from ..compat import shard_map

from .. import env
from ..algorithms.base import Algorithm, AlgorithmContext
from ..bucket import BucketPlan, split_bucket_by_bucket_size
from ..communication import BaguaCommunicator, ReduceOp, collapse_trivial_axes
from ..obs import spans as _obs_spans
from ..obs.spans import trace_span
from ..parallel.mesh import build_mesh, hierarchical_mesh, mesh_axis_size
from ..tensor import build_params, _name_of_path
from ..utils import StatisticalAverage

logger = logging.getLogger(__name__)


def _stack_tree(t):
    """Add a leading length-1 per-rank axis to every leaf — the stacked
    state layout the gossip/expert families shard over their rank axis
    (``shard_map`` out_specs put the mesh axis on this new dimension)."""
    return jax.tree.map(lambda x: jnp.asarray(x)[None], t)


def _find_adam_moments(opt_state):
    """Locate adam-family first/second moments inside a nested optax state
    (``ScaleByAdamState``-like: has param-shaped ``mu`` and ``nu``).  Returns
    ``(mu, nu)`` or None — feeds the QAdam switch adapter."""
    if hasattr(opt_state, "mu") and hasattr(opt_state, "nu"):
        return (opt_state.mu, opt_state.nu)
    if isinstance(opt_state, (tuple, list)):
        for item in opt_state:
            found = _find_adam_moments(item)
            if found is not None:
                return found
    return None


#: memo for the flat-safety probe, keyed by the transform itself (optax
#: transforms are NamedTuples of functions — hashable); repeated trainer
#: inits with one optimizer instance pay the probe once
_FLAT_SAFE_MEMO: Dict[Any, bool] = {}


def _optimizer_flattens_safely(optimizer) -> bool:
    """Whether the transform's update commutes with flattening — the
    precondition for running it on bucket-flat state (memoized)."""
    try:
        memo_key = optimizer if isinstance(optimizer, tuple) else None
        hash(memo_key)
    except TypeError:
        memo_key = None
    if memo_key is not None and memo_key in _FLAT_SAFE_MEMO:
        return _FLAT_SAFE_MEMO[memo_key]
    safe = _probe_flatten_safety(optimizer)
    if memo_key is not None:
        _FLAT_SAFE_MEMO[memo_key] = safe
    return safe


def _probe_flatten_safety(optimizer) -> bool:
    """Probe: two update steps on a matrix param must equal the same steps
    on its raveled vector (elementwise transforms commute exactly;
    shape-aware ones diverge on the very first update).  The matrix is
    128x130 because factored second moments (the canonical shape-aware
    family, optax.adafactor) only engage at ``min_dim_size_to_factor`` =
    128 — a tiny probe would wave them through.  Values are full-rank
    pseudo-noise: a rank-1 pattern would make the factored and full
    moments coincide.  Runs on the CPU backend (eager, the same pattern
    as ZeRO's elementwise probe).  A transform the probe cannot run
    (exotic state/dtype requirements) is reported unsafe — falling back
    to the leaf layout only costs the round-trip perf."""
    try:
        try:
            device = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            device = jax.local_devices()[0]
        with jax.default_device(device):
            n = 128 * 130
            base = jnp.sin(jnp.arange(n, dtype=jnp.float32) * 0.37)
            p2 = {"w": (base * 0.5).reshape(128, 130)}
            p1 = {"w": p2["w"].ravel()}
            gs = [
                jnp.cos(jnp.arange(n, dtype=jnp.float32) * k + k)
                .reshape(128, 130) * s
                for k, s in ((0.11, 0.1), (0.41, 1.0))
            ]
            s2, s1 = optimizer.init(p2), optimizer.init(p1)
            for g in gs:
                u2, s2 = optimizer.update({"w": g}, s2, p2)
                p2 = optax.apply_updates(p2, u2)
                u1, s1 = optimizer.update({"w": g.ravel()}, s1, p1)
                p1 = optax.apply_updates(p1, u1)
            return bool(jnp.allclose(p2["w"].ravel(), p1["w"],
                                     rtol=1e-5, atol=1e-7))
    except Exception as e:  # pragma: no cover - transform-dependent
        logger.info("flat-safety probe could not run (%s); keeping the "
                    "leaf layout", e)
        return False


class TrainState(NamedTuple):
    step: jax.Array        # int32 scalar, replicated
    params: Any
    opt_state: Any
    algo_state: Any


class BaguaTrainer:
    """Owns mesh, bucket plan, compiled step cache, and autotune check-ins.

    Args:
        loss_fn: ``loss_fn(params, batch) -> scalar`` (per-shard mean loss).
        optimizer: an optax ``GradientTransformation`` (ignored when the
            algorithm owns its optimizer, as QAdam does).
        algorithm: a :class:`bagua_tpu.algorithms.base.Algorithm`.
        mesh: optional explicit mesh.  Default: hierarchical
            ``('inter','intra')`` mesh when the algorithm asks for
            hierarchical comm, else a flat 1-D ``('dp',)`` mesh — the analog
            of the reference's three communicators (communication.py:47-72).
        dp_axes: mesh axes that carry data parallelism (default: all axes).
        bucket_bytes: bucket size in bytes (default env BAGUA_DEFAULT_BUCKET_SIZE).
    """

    def __init__(
        self,
        loss_fn: Callable,
        optimizer: Optional[optax.GradientTransformation],
        algorithm: Algorithm,
        mesh: Optional[Mesh] = None,
        dp_axes: Optional[Tuple[str, ...]] = None,
        bucket_bytes: Optional[int] = None,
        model_name: str = "bagua_module",
        autotune: Optional[bool] = None,
        donate: bool = True,
        expert_axis: Optional[str] = None,
        expert_params=None,
        expert_keyword: Optional[str] = None,
        seq_axis: Optional[str] = None,
        tp_axis: Optional[str] = None,
        tp_param_dim=None,
        pp_axis: Optional[str] = None,
        pp_param_dim=None,
        accum_steps: int = 1,
        overlap: Optional[str] = None,
        overlap_chunk_bytes: Optional[int] = None,
        overlap_chunk_bytes_intra: Optional[int] = None,
        overlap_chunk_bytes_inter: Optional[int] = None,
        compress_intra: Optional[str] = None,
        compress_inter: Optional[str] = None,
        flat_resident: Optional[str] = None,
        grad_guard: Optional[str] = None,
        grad_guard_budget: int = 3,
    ):
        """``expert_axis``: mesh axis carrying expert parallelism (MoE).
        Expert params are sharded over it and excluded from the data-parallel
        bucket plan (reference ``param.expert`` flags, moe/experts.py:26-29 +
        distributed.py:66).  Which params are experts is decided by
        ``expert_params``: a ``name -> bool`` callable or an explicit
        collection of param names; default = exact-name marking for params
        created by :class:`bagua_tpu.model_parallel.moe.MoEMLP`.
        ``expert_keyword`` (substring matching) is deprecated — it silently
        captured any param whose name contained the keyword.

        ``seq_axis``: mesh axis carrying sequence/context parallelism (ring
        attention / Ulysses).  The batch is replicated over it (each shard
        slices its own sequence chunk, see ``sp_lm_loss_fn``) while gradient
        communication spans it: each shard's grads cover only its chunk's
        contribution, so dp-style averaging over dp × sp restores the full
        gradient.

        ``tp_axis``: mesh axis carrying tensor parallelism (Megatron-style;
        see ``parallel/tensor_parallel.py``).  ``tp_param_dim`` maps a param
        name to the dimension of its GLOBAL array sharded over ``tp_axis``
        (None for replicated params); default: the transformer family's
        ``models.transformer.tp_param_dim``.  TP leaves are excluded from
        the data-parallel bucket plan (each shard owns its slice; grads need
        averaging over dp only), while dense-leaf grads are exact and
        identical across tp thanks to the model's conjugate collectives —
        so the bucket allreduce deliberately does NOT span tp.

        ``pp_axis``: mesh axis carrying pipeline parallelism (GPipe
        microbatch schedule; see ``parallel/pipeline.py``).  Stage-stacked
        leaves (``pp_param_dim(name) == 0``) are sharded and averaged over
        data axes only, like tp slices.  Replicated leaves (embedding,
        head) get PARTIAL grads — each stage contributes only its own use —
        so they are scaled by pp_size and the bucket allreduce DOES span
        pp, turning its average into the required sum.

        ``tp_axis`` and ``pp_axis`` compose (3-D parallelism over
        dp × pp × tp): stage-stacked block kernels that are also
        tensor-parallel carry both placements — ``P(pp, ..., tp, ...)`` —
        with the tp dim (reported in per-layer coordinates) shifted past
        the leading stage dim.  Bucketed (dense) grads still communicate
        over dp + pp only; tp stays out of the bucket plan entirely.

        ``accum_steps``: gradient accumulation.  The per-rank batch leading
        dimension must be ``accum_steps × microbatch``; the step scans the
        microbatches (``lax.scan``, so the backward is compiled once),
        averaging losses and gradients before any algorithm stage runs —
        communication still happens once per step, on the accumulated
        gradient, exactly as if the full batch had fit in memory — unless
        the overlap scheduler restructures the scan (below).

        ``overlap``: the overlap-aware bucket communication scheduler
        (Bagua's core thesis, arXiv 2107.01499: the wins come from WHEN you
        communicate).  ``"off"`` keeps the exact serialized step
        construction — every collective after the full backward/scan.
        ``"on"`` streams per-bucket collectives into compute: with
        ``accum_steps > 1`` the last microbatch is peeled out of the scan
        (bit-identical gradient sum order) so each bucket's collective is
        issued as soon as its accumulated gradient finalizes, overlapping
        with the remaining backward; buckets are re-ordered by observed
        gradient readiness (one-time, host-side) so the first-finalized
        bucket heads the comm sequence.  ``"auto"`` (default, or env
        ``BAGUA_OVERLAP``) resolves to whichever path measured faster —
        see BENCH_OVERLAP.json.  Supported families: gradient_allreduce,
        bytegrad, and flat-resident ZeRO; others always run serialized.

        ``overlap_chunk_bytes``: target per-rank bytes of one independent
        chunked-ring sub-collective (``communication.ring_allreduce``), so
        even the ``accum_steps == 1`` path exposes multiple independent
        collectives the latency-hiding scheduler can interleave.  Default
        0 / env ``BAGUA_OVERLAP_CHUNK_BYTES``: keep the fused XLA
        collectives.  Only applies while the overlap scheduler is active,
        on single-axis comm worlds.

        ``overlap_chunk_bytes_intra`` / ``overlap_chunk_bytes_inter``:
        per-bandwidth-tier chunk targets for the hierarchical two-level
        decomposition (docs/hierarchical.md) — the slice-local ICI stages
        (and the flat single-axis ring) size against the intra target, the
        cross-slice DCN stage against the inter one, because a chunk that
        amortizes an ICI hop is far too small for a DCN hop.  Default 0 /
        env ``BAGUA_OVERLAP_CHUNK_BYTES_INTRA`` / ``..._INTER``: fall back
        to ``overlap_chunk_bytes`` for that tier.  Setting either is, like
        the link-agnostic knob, an explicit opt-in to the ring path.

        ``compress_intra`` / ``compress_inter``: the per-link-class codec
        policy (docs/compression.md) — what the ring hops of each
        bandwidth tier carry on the wire.  ``auto`` (default, or env
        ``BAGUA_COMPRESS_INTRA`` / ``BAGUA_COMPRESS_INTER``) defers to
        the algorithm family: ByteGrad/QAdam compress the cross-slice DCN
        stage natively (quantized ppermute hops, fp32 accumulation) and
        everything else stays full precision — the Bagua relaxation
        applied only where bytes are expensive.  ``off`` forces full
        precision on the tier (even for the compression families); a
        codec name (``minmax_uint8``/``int8``/``fp8_e4m3``/``fp8_e5m2``)
        forces that codec for every family riding the tier — an explicit
        opt-in to lossy gradient communication for exact families.
        Unlike the chunk knobs these apply to the serialized path too
        (compression is a wire format, not a schedule), and both ride the
        step-cache key, ``BaguaHyperparameter``, and the autotune
        recommendation path (the autopilot's ``compress_dcn`` trend hint
        actuates ``compress_inter`` through it).

        ``flat_resident``: the flat-resident training-state layout
        (docs/flat_layout.md).  ``"on"``: params, gradients, and optimizer
        state live as the bucket plan's flat buffers ACROSS steps — the
        step differentiates the loss w.r.t. the flats directly (the
        forward materializes leaf views by fusable slicing; autodiff's
        scatter-add IS the gradient flatten), collectives consume the
        flats with zero repacking in both the serialized and overlap
        paths, and the optimizer updates the flats natively (a
        ``fuse_optimizer`` wrapper is unwrapped — bucket flats already ARE
        the fused layout).  Removes the per-step leaf->flat->leaf round
        trip every bucketed family otherwise pays (~7% measured for ZeRO,
        VERDICT r3 #4).  ``"off"``: the exact leaf pytree construction.
        ``"auto"`` (default, or env ``BAGUA_FLAT_RESIDENT``): resident
        wherever the family supports it (see
        ``Algorithm.supports_flat_resident``) on a mesh without
        model-parallel axes (tp/pp/expert keep the leaf layout — their
        sharded leaves live outside the bucket plan).  Requires an
        ELEMENTWISE optimizer, like ``fuse_optimizer`` and ZeRO (the
        update for element i may only read element i); shape-aware
        transforms (factored second moments) change meaning on flats —
        use ``flat_resident="off"`` for those.  Leaf pytrees for
        eval/checkpoint/user code come from ``unstack_params(state)``.

        ``grad_guard``: the gradient-health sentinel (docs/robustness.md).
        Every step computes a per-bucket ``isfinite`` verdict on the
        gradients — riding the already-reduced bucket buffers where the
        family replicates them (no extra collective), else one fused
        MIN-allreduce of the per-bucket scalars — surfaced as
        ``trainer.step_metrics["grad_healthy"]``.  Policy ``"off"``
        (default, or env ``BAGUA_GRAD_GUARD``) adds nothing to the traced
        program; ``"warn"`` logs unhealthy steps; ``"skip"`` REWINDS them
        (params/opt/algo state keep their pre-step values — exact in flat
        and leaf layouts and under ``accum_steps > 1``, since the verdict
        is computed on the fully-accumulated gradient) and escalates to
        abort after ``grad_guard_budget`` consecutive skips; ``"abort"``
        raises the comm abort flag on the first unhealthy step.  The
        verdict is identical on every rank, so replicated state never
        diverges.  With the guard on and healthy gradients the selects
        pass the new state through bitwise — loss trajectories are
        byte-identical to ``"off"``."""
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.algorithm = algorithm
        if mesh is None:
            from ..parallel.mesh import get_global_mesh_if_set

            mesh = get_global_mesh_if_set()
        if mesh is None:
            mesh = (
                hierarchical_mesh()
                if algorithm.hierarchical
                else build_mesh()
            )
        self.mesh = mesh
        # fail fast on typo'd axis names: silently nulling them would include
        # expert params in the dense DP plan and corrupt MoE training
        for label, ax in (("expert_axis", expert_axis), ("seq_axis", seq_axis),
                          ("tp_axis", tp_axis), ("pp_axis", pp_axis)):
            if ax is not None and ax not in mesh.axis_names:
                raise ValueError(
                    f"{label}={ax!r} is not a mesh axis "
                    f"(mesh axes: {mesh.axis_names})"
                )
        if tp_axis is not None or pp_axis is not None:
            label = "tp_axis" if tp_axis is not None else "pp_axis"
            if expert_axis is not None:
                raise NotImplementedError(
                    f"combining {label} with expert_axis is not supported yet"
                )
            if not algorithm.replicated_params:
                raise NotImplementedError(
                    f"{label} requires a replicated-params algorithm "
                    "(gossip state is per-rank)"
                )
        self.tp_axis = tp_axis
        self.pp_axis = pp_axis
        if tp_param_dim is None and tp_axis is not None:
            from ..models.transformer import tp_param_dim as _default_tp_dim

            tp_param_dim = _default_tp_dim
        if pp_param_dim is None and pp_axis is not None:
            from ..parallel.pipeline import pp_param_dim as _default_pp_dim

            pp_param_dim = _default_pp_dim
        self._tp_param_dim = tp_param_dim
        self._pp_param_dim = pp_param_dim
        self.expert_axis = expert_axis
        self._expert_filter = self._make_expert_filter(expert_params, expert_keyword)
        self.seq_axis = seq_axis
        if dp_axes is None:
            dp_axes = tuple(
                a for a in mesh.axis_names
                if a in ("dp", "inter", "intra")
                and a not in (self.expert_axis, self.seq_axis, self.tp_axis,
                              self.pp_axis)
            )
            if (
                not dp_axes
                and self.expert_axis is None
                and self.seq_axis is None
                and self.tp_axis is None
                and self.pp_axis is None
            ):
                dp_axes = (mesh.axis_names[0],)
        self.dp_axes = tuple(dp_axes)
        if (
            self.expert_axis is not None or self.seq_axis is not None
        ) and not algorithm.replicated_params:
            raise NotImplementedError(
                "expert/sequence parallelism with gossip (per-rank-weight) "
                "algorithms is not supported yet"
            )
        # the batch is sharded over dp AND ep, so dense-grad comm spans both;
        # expert grads are only averaged over dp (experts differ across ep);
        # sp shards contribute partial grads, so comm spans sp too; pp-dense
        # grads are partial per stage, so comm spans pp (after a pp_size
        # prescale that turns the average into the required sum)
        self.comm_axes = self.dp_axes + tuple(
            a for a in (self.expert_axis, self.seq_axis, self.pp_axis)
            if a is not None
        )
        self.world_size = mesh_axis_size(mesh, self.comm_axes)
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = int(accum_steps)
        self.overlap = (overlap or env.get_overlap_mode()).strip().lower()
        if self.overlap not in ("auto", "on", "off"):
            raise ValueError(f"overlap must be auto|on|off, got {overlap!r}")
        self.overlap_chunk_bytes = int(
            env.get_overlap_chunk_bytes() if overlap_chunk_bytes is None
            else overlap_chunk_bytes
        )
        self.overlap_chunk_bytes_intra = int(
            env.get_overlap_chunk_bytes_intra()
            if overlap_chunk_bytes_intra is None else overlap_chunk_bytes_intra
        )
        self.overlap_chunk_bytes_inter = int(
            env.get_overlap_chunk_bytes_inter()
            if overlap_chunk_bytes_inter is None else overlap_chunk_bytes_inter
        )
        from ..compression.codecs import validate_codec_policy

        self.compress_intra = validate_codec_policy(
            env.get_compress_intra() if compress_intra is None
            else compress_intra, "compress_intra"
        )
        self.compress_inter = validate_codec_policy(
            env.get_compress_inter() if compress_inter is None
            else compress_inter, "compress_inter"
        )
        #: error-feedback residual machinery allowed here: on unless the
        #: honesty control (BAGUA_EF_RESIDUAL=off) disables it or the mesh
        #: carries model-parallel/expert axes (their stacked algo-state
        #: layouts have no spec mapping for the per-bucket residual).
        #: Whether a residual is ACTUALLY carried is then the algorithm's
        #: call (Algorithm.ef_codec: a stateful codec resolved on its wire
        #: + supports_ef_state).
        self._ef_enabled = (
            not env.is_ef_residual_disabled()
            and self._shard_axis is None
            and self.expert_axis is None
        )
        self.flat_resident = (
            flat_resident or env.get_flat_resident_mode()
        ).strip().lower()
        if self.flat_resident not in ("auto", "on", "off"):
            raise ValueError(
                f"flat_resident must be auto|on|off, got {flat_resident!r}"
            )
        if self.flat_resident == "on" and not self._flat_supported():
            # fail at construction, not first init: "on" on an unsupported
            # configuration is a user error, never a silent fallback
            raise ValueError(
                "flat_resident='on' is not supported here: "
                f"{type(algorithm).__name__} (supports_flat_resident="
                f"{algorithm.supports_flat_resident}) with "
                f"tp/pp axis={self._shard_axis!r}, "
                f"expert axis={self.expert_axis!r} — model-parallel leaves "
                "live outside the bucket plan; use flat_resident='auto' "
                "or 'off'"
            )
        self.grad_guard = (grad_guard or env.get_grad_guard_mode()).strip().lower()
        if self.grad_guard not in ("off", "warn", "skip", "abort"):
            raise ValueError(
                f"grad_guard must be off|warn|skip|abort, got {grad_guard!r}"
            )
        if grad_guard_budget < 1:
            raise ValueError(
                f"grad_guard_budget must be >= 1, got {grad_guard_budget}"
            )
        self.grad_guard_budget = int(grad_guard_budget)
        self._guard_skips = 0
        #: monotonic count of guard rewinds (never reset): async model
        #: averaging compares it across a round's flight window to veto
        #: applying the round's delta on top of a rewound state
        self._guard_rewinds_total = 0
        self._pending_health: list = []
        #: per-step observability surface (host side): after each
        #: ``train_step`` under an active grad guard, ``grad_healthy`` is
        #: the step's scalar verdict and ``grad_health_buckets`` the
        #: per-bucket vector (async jax arrays — reading them syncs)
        self.step_metrics: Dict[str, Any] = {}
        self._overlap_ordered = False
        self.bucket_bytes = bucket_bytes or env.get_default_bucket_size()
        self.model_name = model_name
        self.donate = donate

        comm = BaguaCommunicator(collapse_trivial_axes(mesh, self.comm_axes), mesh)
        inter = BaguaCommunicator("inter", mesh) if "inter" in mesh.axis_names else None
        intra = BaguaCommunicator("intra", mesh) if "intra" in mesh.axis_names else None
        self._comm, self._inter, self._intra = comm, inter, intra

        self._plan: Optional[BucketPlan] = None
        self._named_params = None
        self._step_cache: Dict[Any, Callable] = {}
        #: XLA cost/memory model results cached per step-cache key —
        #: ``step_cost_analysis`` re-lowered and re-queried on EVERY call
        #: before this cache existed, which the ledger's per-step MFU gauge
        #: would have paid every step
        self._cost_analysis_cache: Dict[Any, Dict[str, Any]] = {}
        self._memory_analysis_cache: Dict[Any, Optional[Dict[str, int]]] = {}
        #: key -> threading.Event for cost/memory analyses a background
        #: harvest thread is computing (the per-step MFU path must not pay
        #: an inline lower+compile on the dispatch hot path; a concurrent
        #: synchronous caller joins the harvest instead of re-compiling)
        self._cost_analysis_pending: Dict[Any, threading.Event] = {}
        self._current_step_key: Optional[Tuple] = None
        self._step_counter = 0
        self._phase = 0

        # configured instances by family name, so an autotune family switch
        # that returns to the user's family restores THEIR settings
        name = getattr(algorithm, "name", None)
        self._user_algorithms = {name: algorithm} if name else {}

        self.autotune = env.get_autotune_level() >= 1 if autotune is None else autotune
        if self.autotune and algorithm.sharded_opt_state:
            # a rebucket would orphan the per-bucket chunk states (they are
            # keyed on bucket boundaries, unlike the param-shaped states of
            # the other families)
            logger.warning(
                "autotune disabled: %s shards optimizer state per bucket, "
                "which autotune rebucketing would invalidate",
                type(algorithm).__name__,
            )
            self.autotune = False
        self._autotune_client = None
        self._autotune_failures = 0
        self._autotune_completed = not self.autotune
        #: previous goodput-ledger snapshot at the last check-in: the
        #: ledger reports CUMULATIVE seconds, the autotune score needs the
        #: WINDOW since the last report (same windowing as the speed)
        self._autotune_ledger_prev = None
        self._telemetry_reported = False
        self._pending_state_migration = None
        self._stashed_opt_state = None
        #: flat-resident layout ACTIVE (resolved from the mode at init());
        #: generalizes the old ZeRO-only ``_zero_flat`` gate to every
        #: supports_flat_resident family
        self._flat_resident = False
        #: whether init() has resolved + built the state layout: before
        #: this, a flat_resident recommendation adjusts the MODE (init
        #: builds the layout directly); after, it queues a live
        #: flat<->leaf state migration (:meth:`_apply_flat_resident`)
        self._flat_layout_live = False
        #: the optimizer the compiled step actually runs: the user's, or a
        #: ``fuse_optimizer`` wrapper's inner transform when the resident
        #: flats already are the fused layout (resolved at init())
        self._opt = optimizer
        self._param_template = None

        from ..watchdog import get_comm_timeout_s, get_global_watchdog

        timeout = get_comm_timeout_s()
        self._watchdog = get_global_watchdog(timeout) if timeout else None
        from ..profiling import StepProfiler

        self._profiler = StepProfiler.from_env()
        # observability plane (docs/observability.md): resolved once — the
        # per-step hooks below gate on this flag so BAGUA_OBS=off restores
        # the exact pre-obs host behavior
        self._obs_enabled = _obs_spans.enabled()
        self._last_beacon_write = 0.0
        #: goodput ledger (docs/observability.md, efficiency plane): every
        #: wall-clock second of this process lands in exactly one class —
        #: fed from the step-cadence windows, the span hook, stall reports,
        #: and the grad guard's rewind verdicts below.  All host-side.
        self._ledger = None
        #: MFU denominator: peak silicon FLOP/s for this chip kind (None on
        #: cpu-sim / unknown silicon -> obs/mfu stays null-with-rationale)
        self._peak_flops = None
        self._mfu_flops: Optional[float] = None
        self._mfu_noted_unavailable = False
        #: the CURRENT step's wall window contained a compile or state
        #: migration: the cadence hook attributes the window there instead
        #: of productive_step (the ledger mirror of _skip_next_speed_sample)
        self._ledger_window_class: Optional[str] = None
        self._footprint_noted = False
        self._mem_poll_dead = False
        self._mem_poll_failures = 0
        if self._obs_enabled:
            from ..obs import export as _obs_export
            from ..obs import http as _obs_http
            from ..obs import ledger as _obs_ledger
            from ..obs import recorder as _obs_recorder

            _obs_export.maybe_start_global_exporter(self)
            # per-process HTTP status plane (off unless the operator sets
            # BAGUA_OBS_HTTP_PORT; the launcher offsets each worker's
            # port): /metrics serves the same prepared snapshot the
            # exporter writes to metrics.prom
            _obs_http.maybe_start_global_http_server()
            _obs_recorder.maybe_install_signal_hook()
            self._ledger = _obs_ledger.install()
            self._peak_flops = _obs_ledger.peak_flops_for_device_kind(
                jax.devices()[0].device_kind
            )
        #: step-time anomaly detector (docs/observability.md): rolling
        #: median/MAD baseline over the RAW host cadence (injected stalls
        #: included — a stall IS the anomaly an operator wants flagged,
        #: while measured_step_dt subtracts it to stay an honest dilation
        #: base) plus the per-phase host durations accumulated below
        self.anomaly_detector = None
        if self._obs_enabled and env.get_obs_anomaly_mode() == "on":
            from ..obs.anomaly import StepAnomalyDetector

            self.anomaly_detector = StepAnomalyDetector()
        #: host phase durations of the step currently being driven
        #: (dispatch / collective / optimizer); harvested into the anomaly
        #: detector when the next cadence sample closes the window
        self._phase_durations: Dict[str, float] = {}
        #: the current step triggered a compile or a state migration: its
        #: wall window is expected to be huge and is neither an anomaly
        #: nor baseline material (the speed tracker's
        #: ``_skip_next_speed_sample`` mirror)
        self._anomaly_skip_window = False
        self._speed_tracker = StatisticalAverage()
        self._last_report_time = time.time()
        self._last_speed_time = time.time()
        self._manual_speed = False
        self._skip_next_speed_sample = True
        self._hyperparams_signature = None
        # host dispatch cadence (one monotonic read per step): the base
        # step time the step.straggle fault point dilates by its factor
        self._last_step_mono: Optional[float] = None
        self._step_dt: Optional[float] = None
        self._last_straggle_sleep = 0.0

    # ---- plan management -----------------------------------------------

    def _ctx(self, plan: BucketPlan, overlap: bool = False) -> AlgorithmContext:
        return AlgorithmContext(
            comm=self._comm,
            internode=self._inter,
            intranode=self._intra,
            plan=plan,
            world_size=self.world_size,
            overlap=overlap,
            overlap_chunk_bytes=(
                self.overlap_chunk_bytes or None if overlap else None
            ),
            intra_chunk_bytes=(
                self.overlap_chunk_bytes_intra or None if overlap else None
            ),
            inter_chunk_bytes=(
                self.overlap_chunk_bytes_inter or None if overlap else None
            ),
            # the codec policy applies to the serialized path too —
            # compression is a wire format, not a schedule (the knobs are
            # normalized, so "auto" reaches codec_for unchanged)
            intra_codec=self.compress_intra,
            inter_codec=self.compress_inter,
            flat_resident=self._flat_resident,
            ef_enabled=self._ef_enabled,
        )

    def _flat_supported(self) -> bool:
        """Whether the flat-resident layout CAN hold this configuration:
        the family implements the contract and every param leaf is in the
        bucket plan (model-parallel axes put sharded leaves outside it, so
        those compositions keep the leaf layout)."""
        return (
            self.algorithm.supports_flat_resident
            and self._shard_axis is None
            and self.expert_axis is None
        )

    def _resolve_flat_resident(self) -> bool:
        """Dispatch gate for the resident layout, resolved once per
        ``init()``.  Explicit on/off wins (``on`` on an unsupported
        configuration already raised at construction); ``auto`` takes the
        resident layout wherever it is supported, the family's measured
        record agrees (``Algorithm.flat_resident_auto``, BENCH_FLAT.json),
        and the trainer optimizer commutes with flattening
        (:func:`_optimizer_flattens_safely` — shape-aware transforms fall
        back to the leaf layout instead of silently changing meaning)."""
        if self.flat_resident == "off":
            return False
        if self.flat_resident == "on":
            # supportedness was validated at construction; the optimizer
            # probe still runs — an explicit "on" with a shape-aware
            # transform is a meaning change the user must not get silently
            if not self.algorithm.owns_optimizer and \
                    not _optimizer_flattens_safely(self._flat_opt()):
                raise ValueError(
                    "flat_resident='on' with an optimizer whose update "
                    "does not commute with flattening (shape-aware "
                    "transform, e.g. factored second moments): updating "
                    "a matrix and updating its raveled vector disagree, "
                    "so bucket-flat state would silently change the "
                    "training math.  Use flat_resident='off' (or an "
                    "elementwise transform)."
                )
            return True
        if not (self._flat_supported() and self.algorithm.flat_resident_auto):
            return False
        if not self.algorithm.owns_optimizer and \
                not _optimizer_flattens_safely(self._flat_opt()):
            logger.info(
                "flat_resident auto: optimizer update does not commute "
                "with flattening (shape-aware transform?) — keeping the "
                "leaf layout"
            )
            return False
        return True

    def _flat_opt(self):
        """The transform that would run on the flats (a fused wrapper's
        inner), for the flat-safety probe."""
        inner = getattr(self.optimizer, "fused_inner", None)
        return inner if inner is not None else self.optimizer

    def _overlap_active(self) -> bool:
        """Dispatch gate for the overlap scheduler.  Explicit on/off wins;
        ``auto`` resolves to the path that measured faster
        (BENCH_OVERLAP.json): overlap when there is an accumulation scan to
        stream collectives into (the peel is bit-exact and measured
        fastest), the serialized construction otherwise — at
        ``accum_steps == 1`` the backward already feeds the per-bucket
        collectives as open dataflow, so restructuring buys nothing unless
        ring chunking is explicitly requested."""
        if not self.algorithm.supports_overlap:
            return False
        if self.algorithm.sharded_opt_state and not self._flat_resident:
            # ZeRO overlap rides the flat-resident (pure-dp) layout only:
            # the leaf layout's comm happens inside optimizer_update after
            # the leaf->flat round trip, outside the overlap window
            return False
        if self.overlap == "off":
            return False
        if self.overlap == "on":
            return True
        # auto: measured dispatch gate (BENCH_OVERLAP.json, interleaved A/B
        # trials on the 8-dev cpu-sim mesh): allreduce measured on-par-to-
        # faster under overlap at accum>1 (best-trial 1.03x, noise-bound) —
        # and the peel is bit-exact, so auto takes it; ZeRO and bytegrad
        # measured slower (0.9x / 0.99x → overlap_auto=False on those
        # families, overridable with overlap="on").  accum==1 keeps the
        # serialized construction (the backward already feeds the bucket
        # collectives as open dataflow); an explicit chunk size is an
        # opt-in to the ring path at any accum.
        return self.algorithm.overlap_auto and (
            self.accum_steps > 1 or self._any_chunk_bytes()
        )

    def _any_chunk_bytes(self) -> bool:
        """Whether ANY ring chunk target is set (link-agnostic or per-tier)
        — each is an explicit opt-in to the chunked ring path."""
        return bool(
            self.overlap_chunk_bytes
            or self.overlap_chunk_bytes_intra
            or self.overlap_chunk_bytes_inter
        )

    def _reorder_plan_for_overlap(self, state, batch) -> None:
        """One-time host-side re-bucketing by observed gradient readiness
        (reverse execution order) so the overlap scheduler's first-issued
        collective is the first-finalized bucket — the trainer-local analog
        of the autotune service's span-driven re-ordering
        (:meth:`_report_tensor_execution_order`), for runs without the
        sidecar.  Static jaxpr analysis, no compiles; never takes down
        training."""
        try:
            from ..telemetry import profile_tensor_execution_order

            params = self.unstack_params(state)
            spans = profile_tensor_execution_order(self.loss_fn, params, batch)
            order = {s["tensor_name"]: i for i, s in enumerate(spans)}
            decls = [t.declaration() for b in self._plan.buckets
                     for t in b.tensors]
            n = len(order)
            decls.sort(key=lambda d: order.get(d.name, n))
            self.rebucket(split_bucket_by_bucket_size(decls, self.bucket_bytes))
            logger.info(
                "overlap: re-bucketed %d tensors by gradient readiness "
                "(%d buckets)", len(decls), len(self._plan.buckets),
            )
        except Exception as e:
            logger.warning("overlap readiness re-bucketing skipped: %s", e)

    @staticmethod
    def _make_expert_filter(expert_params, expert_keyword):
        if expert_params is not None and expert_keyword is not None:
            raise ValueError("pass expert_params OR expert_keyword, not both")
        if expert_keyword is not None:
            import warnings

            warnings.warn(
                "expert_keyword substring matching is deprecated; pass "
                "expert_params (a name filter or collection of names)",
                DeprecationWarning, stacklevel=3,
            )
            return lambda name: expert_keyword in name
        if expert_params is None:
            from ..model_parallel.moe.layer import is_expert_param

            return is_expert_param
        if callable(expert_params):
            return expert_params
        names = frozenset(expert_params)
        return lambda name: name in names

    def _is_expert_name(self, name: str) -> bool:
        return self.expert_axis is not None and self._expert_filter(name)

    @property
    def _shard_axis(self) -> Optional[str]:
        """Truthy when a model-parallel axis (tp and/or pp) is present;
        param slices of such leaves bypass the bucket plan."""
        return self.tp_axis if self.tp_axis is not None else self.pp_axis

    def _shard_entries(self, name: str) -> Tuple[Tuple[int, str], ...]:
        """((dim, axis), ...) placements for a param leaf — pp stage
        stacking at its reported dim, tp slicing at the tp dim.  When a leaf
        is both pp-stacked and tp-sharded (3-D parallelism), the tp dim —
        reported by ``tp_param_dim`` in per-layer coordinates — shifts one
        right past the leading stage dim.

        Under a sharded-opt-state (ZeRO) algorithm, expert leaves are also
        expressed this way — global ``[n_experts, ...]`` sharded at dim 0
        over the expert axis — instead of the stacked per-rank layout the
        other algorithm families use."""
        entries = []
        if self.pp_axis is not None and self._pp_param_dim is not None:
            d = self._pp_param_dim(name)
            if d is not None:
                entries.append((d, self.pp_axis))
        if self.tp_axis is not None and self._tp_param_dim is not None:
            d = self._tp_param_dim(name)
            if d is not None:
                shift = 1 if entries else 0
                entries.append((d + shift, self.tp_axis))
        if (
            self.expert_axis is not None
            and self.algorithm.sharded_opt_state
            and self._expert_filter(name)
        ):
            entries.append((0, self.expert_axis))
        return tuple(entries)

    def _is_sharded(self, name: str) -> bool:
        return bool(self._shard_entries(name))

    def _build_plan(self, params) -> BucketPlan:
        candidates = [
            p for p in build_params(params)
            if not self._is_expert_name(p.name)
            and not self._is_sharded(p.name)
        ]
        named = self.algorithm.init_tensors(candidates)
        self._named_params = named
        decls = [p.declaration() for p in named]
        decl_buckets = split_bucket_by_bucket_size(decls, self.bucket_bytes)
        return self.algorithm.tensors_to_buckets(decl_buckets, named, self.world_size)

    def _tp_param_spec_tree(self, params):
        """Per-leaf PartitionSpecs: tp/pp leaves sharded along their
        reported dims (both, for 3-D-parallel stacked-and-sliced kernels),
        everything else replicated."""
        def leaf_spec(path, leaf):
            entries = self._shard_entries(_name_of_path(path))
            if not entries:
                return P()
            axes = [None] * (max(d for d, _ in entries) + 1)
            for d, ax in entries:
                axes[d] = ax
            return P(*axes)

        return jax.tree_util.tree_map_with_path(leaf_spec, params)

    def _sharded_specs_by_name(self) -> Dict[str, P]:
        """name -> PartitionSpec for every model-parallel (non-replicated)
        param leaf; requires ``self._param_specs``."""
        sharded = {}
        flat = jax.tree_util.tree_flatten_with_path(self._param_specs)[0]
        for path, spec in flat:
            if spec != P():
                sharded[_name_of_path(path)] = spec
        return sharded

    def _tp_match_spec_tree(self, tree, sharded_by_name):
        """Specs for a param-mirroring tree (optimizer state): a leaf whose
        dotted path ends with a tp param's full name inherits its spec."""
        def leaf_spec(path, leaf):
            name = _name_of_path(path)
            for pn, spec in sharded_by_name.items():
                if name == pn or name.endswith("." + pn):
                    return spec
            return P()

        return jax.tree_util.tree_map_with_path(leaf_spec, tree)

    def rebucket(self, decl_buckets) -> None:
        """Apply an autotune bucketing suggestion (reference
        distributed.py:443-502 ``_bagua_reset_algorithm_buckets``).

        Under the flat-resident layout the training state is laid out IN
        the old plan's buffers, so a plan change queues a flat->flat state
        migration (:func:`bagua_tpu.bucket.relayout_flats` — 1-D segment
        repacking, no leaf round trip) that the next ``train_step``
        applies before dispatching the recompiled step."""
        if self.algorithm.sharded_opt_state:
            raise ValueError(
                "cannot rebucket: the algorithm's optimizer state is sharded "
                "per bucket and would be invalidated by new bucket boundaries"
            )
        old_plan = self._plan
        self._plan = self.algorithm.tensors_to_buckets(
            decl_buckets, self._named_params, self.world_size
        )
        if (
            # the error-feedback residual is plan-keyed algo state even
            # under the leaf layout, so an active EF codec makes a plan
            # change a state migration too (relayout_algo_state carries
            # the residual across the new bucket boundaries)
            (self._flat_resident or self._ef_active())
            and old_plan is not None
            and old_plan.signature() != self._plan.signature()
        ):
            self._queue_state_migration(
                self._make_flat_migration(old_plan, self._plan)
            )

    def _ef_active(self) -> bool:
        """Whether the CURRENT configuration carries the error-feedback
        residual in algo_state (a stateful codec resolved on this family's
        wire) — plan-keyed state, so rebuckets and codec-knob flips must
        migrate it."""
        if self._plan is None:
            return False
        return self.algorithm.ef_codec(self._ctx(self._plan)) is not None

    def _sync_ef_state(self, was_active: bool) -> None:
        """Queue a state migration when a knob change flipped whether the
        error-feedback residual is carried: newly active starts from zero
        residuals (the published EF algorithms' init), newly inactive
        drops the accumulated residual — both loud, both applied before
        the next compiled step dispatches."""
        now = self._ef_active()
        if now == was_active:
            return
        plan = self._plan
        world = self.world_size

        if now:
            def add_ef(state: TrainState) -> TrainState:
                if state.algo_state is not None:
                    return state  # already carried (idempotent re-queue)
                logger.info(
                    "error-feedback residual enabled (codec policy flip): "
                    "starting from zero residuals for %d buckets",
                    len(plan.buckets),
                )
                ef = {"buckets": tuple(
                    jnp.zeros((world, b.padded_numel), jnp.float32)
                    for b in plan.buckets
                )}
                return state._replace(algo_state={"ef": ef})

            self._queue_state_migration(add_ef)
        else:
            def drop_ef(state: TrainState) -> TrainState:
                a = state.algo_state
                if not (isinstance(a, dict) and "ef" in a):
                    return state
                logger.info(
                    "error-feedback residual disabled (codec policy "
                    "flip): dropping the accumulated residual"
                )
                rest = {k: v for k, v in a.items() if k != "ef"}
                return state._replace(algo_state=rest or None)

            self._queue_state_migration(drop_ef)

    def _queue_state_migration(self, fn) -> None:
        """Compose ``fn`` onto the pending state migration (earlier-queued
        migrations run first) — an autotune family switch immediately
        followed by its alignment rebucket must apply both, in order."""
        prev = self._pending_state_migration
        self._pending_state_migration = (
            fn if prev is None else (lambda state: fn(prev(state)))
        )

    @staticmethod
    def _is_flat_container(x) -> bool:
        """The ``{"flats", "local"}`` dict marking a bucket-flat-resident
        subtree — the protocol shared with the algorithm stages.  Optimizer
        states mirror the param pytree, so the same marker locates every
        flat buffer group inside arbitrary optax state nesting."""
        return isinstance(x, dict) and set(x.keys()) == {"flats", "local"}

    def _relayout_tree(self, tree, old_plan, new_plan):
        """Migrate every flat-resident subtree of ``tree`` (params, or an
        optimizer state mirroring them) from ``old_plan`` to ``new_plan``.
        Elementwise optimizer state is exactly as relayout-safe as the
        params it mirrors: its flat buffers share the plan's offsets, and
        bucket padding stays zero under elementwise updates."""
        from ..bucket import relayout_flats

        is_zp = self._is_flat_container

        def fix(x):
            if is_zp(x):
                return {
                    "flats": tuple(relayout_flats(old_plan, new_plan,
                                                  x["flats"])),
                    "local": x["local"],
                }
            return x

        return jax.tree.map(fix, tree, is_leaf=is_zp)

    def _make_flat_migration(self, old_plan, new_plan):
        def migrate(state: TrainState) -> TrainState:
            logger.info(
                "flat-resident relayout: migrating training state "
                "%d -> %d buckets", len(old_plan.buckets),
                len(new_plan.buckets),
            )
            if self._stashed_opt_state is not None:
                # a displaced optax state stashed across a qadam switch is
                # plan-laid-out too; keep it restorable after the rebucket
                self._stashed_opt_state = self._relayout_tree(
                    self._stashed_opt_state, old_plan, new_plan
                )
            return state._replace(
                params=self._relayout_tree(state.params, old_plan, new_plan),
                opt_state=self._relayout_tree(state.opt_state, old_plan,
                                              new_plan),
                algo_state=self.algorithm.relayout_algo_state(
                    old_plan, new_plan, state.algo_state
                ),
            )

        return migrate

    # ---- state init ------------------------------------------------------

    def init(self, params) -> TrainState:
        # copy: step buffers are donated, the caller keeps their params alive
        params = jax.tree.map(lambda x: jnp.array(x, copy=True), params)
        # structure/shape/dtype template for rebuilding the leaf pytree from
        # flat-resident layouts (ZeRO) in traced code
        self._param_template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype),
            params,
        )
        self._plan = self._build_plan(params)
        if self.autotune and not self._autotune_completed:
            self._autotune_register_tensors()
            # a family switch during registration needs no migration: the
            # state below is built directly in the new family's layout
            self._pending_state_migration = None
        plan = self._plan
        algo = self.algorithm
        self._flat_resident = self._resolve_flat_resident()
        self._opt = self.optimizer
        if (
            self._flat_resident
            and not algo.owns_optimizer
            and getattr(self.optimizer, "fused_inner", None) is not None
        ):
            # bucket flats already ARE a fused layout (one 1-D buffer per
            # dtype-homogeneous bucket): run the wrapped transform on them
            # natively instead of re-concatenating into the wrapper's
            # private per-dtype buffers every step
            self._opt = self.optimizer.fused_inner
        self._flat_layout_live = True
        ctx = self._ctx(plan)
        mesh = self.mesh

        if algo.owns_optimizer:
            opt_init = algo.init_optimizer_state
        else:
            opt_init = self._opt.init

        if self.expert_axis is not None and not algo.sharded_opt_state:
            # everything is stacked per ep-rank (leading axis sharded over
            # 'ep'): expert leaves enter as global [n_experts, ...] and are
            # split; dense leaves are replicated copies kept in lockstep by
            # the dense-grad allreduce
            ep = self.expert_axis

            def leaf_spec(path, leaf):
                return P(ep) if self._is_expert_name(_name_of_path(path)) else P()

            in_specs = jax.tree_util.tree_map_with_path(leaf_spec, params)

            def init_fn(p):
                a = algo.init_state(ctx, p)
                o = opt_init(p)
                return _stack_tree(p), _stack_tree(o), _stack_tree(a)

            out_spec = P((ep,))
            p_stacked, opt_state, algo_state = jax.jit(
                shard_map(init_fn, mesh=mesh, in_specs=(in_specs,),
                          out_specs=(out_spec, out_spec, out_spec),
                          check_vma=False)
            )(params)
            return TrainState(
                jnp.zeros((), jnp.int32), p_stacked, opt_state, algo_state
            )

        if algo.replicated_params and algo.sharded_opt_state:
            # ZeRO-1 layout: dense params replicated, their optimizer state
            # sharded over the comm axes (stacked leading axis — the same
            # spec machinery as the gossip algorithms' per-rank state).
            # With tp/pp, the "local" state part mirrors the sharded leaves'
            # own placements (state protocol: {"buckets", "local"}).
            #
            # Pure-dp meshes use the FLAT-RESIDENT layout (resolved above,
            # ``flat_resident="auto"`` default): params live as the bucket
            # flat buffers across steps and the step differentiates w.r.t.
            # the flats directly — the forward unflatten is fusable slicing
            # and autodiff's scatter-add IS the gradient flatten, so the
            # per-step leaf->flat->leaf round trip (the measured ~7%
            # single-chip ZeRO overhead, VERDICT r3 #4) disappears.
            # Model-parallel compositions (and flat_resident="off") keep
            # the leaf layout.
            if self._zero_staged() and not self._flat_resident:
                raise NotImplementedError(
                    "hierarchical ZeRO supports the flat-resident (pure-dp) "
                    "layout only; drop hierarchical=True when composing "
                    "with tp/pp/expert axes"
                )
            in_spec = P()
            local_spec = P()
            if self._shard_axis is not None or self.expert_axis is not None:
                self._param_specs = self._tp_param_spec_tree(params)
                sharded = self._sharded_specs_by_name()
                in_spec = self._param_specs
                # axis-free eval_shape on LOCAL slice shapes gives the local
                # state's structure; specs then follow the matching leaf
                local_template = {}
                for p in build_params(params):
                    entries = self._shard_entries(p.name)
                    if entries:
                        shape = list(p.shape)
                        for d, ax in entries:
                            shape[d] //= mesh.shape[ax]
                        local_template[p.name] = jax.ShapeDtypeStruct(
                            tuple(shape), p.dtype
                        )
                local_struct = jax.eval_shape(
                    algo.init_optimizer_state_local, local_template
                )
                local_spec = self._tp_match_spec_tree(local_struct, sharded)
            # staged (hierarchical) ZeRO: chunk states stack over INTRA only
            # and are replicated across inter — must mirror the algorithm's
            # _staged()/_shard_comm() decision exactly
            self._zero_opt_specs = {
                "buckets": (
                    P(("intra",)) if self._zero_staged()
                    else P(self.comm_axes)
                ),
                "local": local_spec,
            }

            if self._flat_resident:

                def init_fn_flat(p):
                    a = algo.init_state(ctx, p)
                    o = algo.init_optimizer_state_sharded(ctx, p)
                    zp = {"flats": tuple(plan.flatten_tree(p)), "local": {}}
                    return zp, {"buckets": _stack_tree(o["buckets"]),
                                "local": o["local"]}, _stack_tree(a)

                zparams, opt_state, algo_state = jax.jit(
                    shard_map(init_fn_flat, mesh=mesh, in_specs=(in_spec,),
                              out_specs=(P(), self._zero_opt_specs,
                                         P(self.comm_axes)),
                              check_vma=False)
                )(params)
                return TrainState(jnp.zeros((), jnp.int32), zparams,
                                  opt_state, algo_state)

            def init_fn(p):
                a = algo.init_state(ctx, p)
                o = algo.init_optimizer_state_sharded(ctx, p)
                return {"buckets": _stack_tree(o["buckets"]),
                        "local": o["local"]}, _stack_tree(a)

            opt_state, algo_state = jax.jit(
                shard_map(init_fn, mesh=mesh, in_specs=(in_spec,),
                          out_specs=(self._zero_opt_specs, P(self.comm_axes)),
                          check_vma=False)
            )(params)
            return TrainState(jnp.zeros((), jnp.int32), params, opt_state, algo_state)

        if algo.replicated_params:
            # algo-state specs: replicated by default; the error-feedback
            # residual's per-bucket flats stack per rank over the comm axes
            aspecs = algo.algo_state_specs(ctx, P(), P(self.comm_axes))
            if self._flat_resident:
                # flat-resident replicated layout (allreduce/bytegrad/
                # qadam): params live as the bucket flats; optimizer state
                # is built directly IN flat layout, so the update runs on
                # the flats natively — never a leaf-shaped moment in sight
                zparams = jax.jit(
                    lambda p: {"flats": tuple(plan.flatten_tree(p)),
                               "local": {}}
                )(params)
                opt_state = jax.jit(opt_init)(zparams)

                def init_fn(p):
                    return algo.init_state(ctx, p)

                algo_state = jax.jit(
                    shard_map(init_fn, mesh=mesh, in_specs=(P(),),
                              out_specs=aspecs, check_vma=False)
                )(params)
                return TrainState(jnp.zeros((), jnp.int32), zparams,
                                  opt_state, algo_state)
            opt_state = jax.jit(opt_init)(params)

            def init_fn(p):
                return algo.init_state(ctx, p)

            algo_state = jax.jit(
                shard_map(init_fn, mesh=mesh, in_specs=(P(),),
                          out_specs=aspecs, check_vma=False)
            )(params)
            if self._shard_axis is not None:
                if algo_state is not None:
                    # optimizer-owned state (QAdam momenta) IS supported —
                    # it rides the suffix-matched opt_state specs; only
                    # algorithm-side state trees have no spec mapping yet
                    raise NotImplementedError(
                        "tensor/pipeline parallelism with algorithms that "
                        "carry init_state trees is not supported yet"
                    )
                self._param_specs = self._tp_param_spec_tree(params)
                self._opt_specs = self._tp_match_spec_tree(
                    opt_state, self._sharded_specs_by_name()
                )
            return TrainState(jnp.zeros((), jnp.int32), params, opt_state, algo_state)

        # per-rank (gossip) state: stack every leaf along a leading rank
        # axis.  Flat-resident gossip keeps the same stacked protocol over
        # the {"flats", "local"} container — each rank's row holds ITS
        # flat weights, which is exactly what the gossip exchanges consume.
        def init_fn(p):
            a = algo.init_state(ctx, p)
            if self._flat_resident:
                p = {"flats": tuple(plan.flatten_tree(p)), "local": {}}
            o = opt_init(p)
            return _stack_tree(p), _stack_tree(o), _stack_tree(a)

        specs = P(self.dp_axes)
        p_stacked, opt_state, algo_state = jax.jit(
            shard_map(init_fn, mesh=mesh, in_specs=(P(),),
                      out_specs=(specs, specs, specs), check_vma=False)
        )(params)
        return TrainState(jnp.zeros((), jnp.int32), p_stacked, opt_state, algo_state)

    # ---- gradient-health sentinel (traced helpers) -----------------------

    def _grad_health_vec(self, plan: BucketPlan, grads):
        """Per-bucket finiteness of ``grads`` as a float32 vector (traced):
        1.0 = every element of the bucket is finite.  Leaves outside the
        bucket plan (model-parallel/expert slices, flat-layout ``local``
        entries) share one trailing slot.  Works on both gradient layouts
        — the ``{"flats", "local"}`` container checks its resident buffers
        directly (zero repacking)."""
        extras = []
        if self._is_flat_container(grads):
            flags = [jnp.isfinite(f).all() for f in grads["flats"]]
            extras = [jnp.isfinite(v).all()
                      for v in jax.tree.leaves(grads["local"])]
        else:
            bucket_of = {t.name: i for i, b in enumerate(plan.buckets)
                         for t in b.tensors}
            per = [[] for _ in plan.buckets]
            for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
                flag = jnp.isfinite(leaf).all()
                i = bucket_of.get(_name_of_path(path))
                (per[i] if i is not None else extras).append(flag)
            flags = [jnp.stack(fl).all() if fl else jnp.bool_(True)
                     for fl in per]
        if extras:
            flags.append(jnp.stack(extras).all())
        if not flags:  # nothing to check (empty plan, no leaves)
            return jnp.ones((1,), jnp.float32)
        return jnp.stack(flags).astype(jnp.float32)

    def _apply_grad_poison(self, plan: BucketPlan, grads, step, specs):
        """Chaos: compile armed ``grad.poison`` specs into the step — at
        the spec's (traced) step number, the first element of the target
        bucket's gradient becomes NaN/Inf.  Off-step the gradient passes
        through bitwise (a full select, not ``+0.0`` — that would flip
        ``-0.0`` gradients)."""
        for spec in specs:
            bad = jnp.float32(jnp.nan if spec.kind == "nan" else jnp.inf)
            # a traced fault cannot mutate host fire-counters, so count is
            # compiled in as a step window: step=K fires exactly at K;
            # step=None fires on the first `count` steps (count<0: every
            # step)
            if spec.step is not None:
                fire = step == jnp.int32(spec.step)
            elif spec.count < 0:
                fire = jnp.bool_(True)
            else:
                fire = step < jnp.int32(spec.count)
            b = spec.bucket % max(1, len(plan.buckets))
            if self._is_flat_container(grads):
                flats = list(grads["flats"])
                f = flats[b]
                flats[b] = jnp.where(fire, f.at[0].set(bad.astype(f.dtype)), f)
                grads = {"flats": tuple(flats), "local": grads["local"]}
            else:
                target = plan.buckets[b].tensors[0].name

                def poison_leaf(path, g, _t=target, _fire=fire, _bad=bad):
                    if _name_of_path(path) != _t:
                        return g
                    poisoned = g.at[(0,) * g.ndim].set(_bad.astype(g.dtype))
                    return jnp.where(_fire, poisoned, g)

                grads = jax.tree_util.tree_map_with_path(poison_leaf, grads)
        return grads

    # ---- step ------------------------------------------------------------

    def _make_step_fn(self, plan: BucketPlan):
        from ..faults import inject as _inject

        algo = self.algorithm
        overlap = self._overlap_active()
        ctx = self._ctx(plan, overlap=overlap)
        mesh = self.mesh
        dp = self.dp_axes
        guard = self.grad_guard
        poison_specs = _inject.armed_traced_specs("grad.poison")
        # post-comm gradients are bitwise-identical on every rank only for
        # dense allreduce-style families on a mesh without model-parallel
        # axes — there the health check rides the already-reduced buffers
        # and needs NO collective of its own (non-finite contributions
        # propagate through the sum); everything else checks locally and
        # combines verdicts with one fused pmin
        replicated_health = (
            algo.grad_health_replicated
            and self.expert_axis is None
            and self._shard_axis is None
        )
        # gossip-style families keep PER-RANK weight replicas, so the guard
        # verdict is per-rank too: each rank rewinds its own replica (the
        # next exchange re-syncs a skipped rank) and no health collective
        # is added
        local_health = not algo.replicated_params
        mp_health = (
            self.expert_axis is not None or self._shard_axis is not None
        )
        health_axes = tuple(a for a in mesh.axis_names if mesh.shape[a] > 1)
        replicated = algo.replicated_params
        expert = self.expert_axis
        # per-shard state is stacked (leading rank axis) for gossip
        # algorithms and for expert parallelism — except under ZeRO, whose
        # layout expresses expert leaves as dim-0-sharded global arrays
        stacked = (
            (not replicated) or expert is not None
        ) and not algo.sharded_opt_state
        # ZeRO-1: only opt/algo state carries the per-rank stacked axis;
        # params stay replicated (model-parallel leaves: sharded in place)
        opt_stacked = replicated and algo.sharded_opt_state
        _unstack = lambda t: jax.tree.map(lambda x: x[0], t)
        _stack = _stack_tree
        # expert grads average over dp (+sp: partial-sequence contributions)
        # but never over ep, where experts differ
        expert_dp = tuple(
            a for a in dp + ((self.seq_axis,) if self.seq_axis else ())
            if mesh.shape[a] > 1
        )
        if self._flat_resident:
            leaf_view = self._flat_leaf_view

            def loss_on(zp, b):
                # flat-resident params: materialize the leaf view (slicing —
                # XLA fuses it); autodiff w.r.t. zp scatters grads straight
                # back into bucket-flat layout
                return self.loss_fn(leaf_view(zp), b)
        else:
            loss_on = self.loss_fn

        def per_shard(state: TrainState, batch):
            params = state.params
            opt_state = state.opt_state
            algo_state = state.algo_state
            if stacked:
                params, opt_state, algo_state = (
                    _unstack(params), _unstack(opt_state), _unstack(algo_state)
                )
            elif opt_stacked:
                opt_state = {"buckets": _unstack(opt_state["buckets"]),
                             "local": opt_state["local"]}
                algo_state = _unstack(algo_state)
            step = state.step

            if self.accum_steps > 1:
                accum = self.accum_steps

                def reshape_mb(x):
                    if x.shape[0] % accum:
                        raise ValueError(
                            f"batch leading dim {x.shape[0]} is not divisible "
                            f"by accum_steps={accum}"
                        )
                    return x.reshape((accum, x.shape[0] // accum) + x.shape[1:])

                microbatches = jax.tree.map(reshape_mb, batch)

                def micro_step(carry, mb):
                    loss_sum, grad_sum = carry
                    l, g = jax.value_and_grad(loss_on)(params, mb)
                    return (loss_sum + l, jax.tree.map(jnp.add, grad_sum, g)), None

                # carry dtype must match micro_step's promoted loss dtype
                mb0 = jax.tree.map(lambda x: x[0], microbatches)
                loss_dtype = jax.eval_shape(loss_on, params, mb0).dtype
                zero = (
                    jnp.zeros((), loss_dtype),
                    jax.tree.map(jnp.zeros_like, params),
                )
                if overlap:
                    # Overlap scheduler: peel the LAST microbatch out of
                    # the scan.  A scan is one opaque while-op whose
                    # results exist only at loop exit, so every collective
                    # must wait for the whole scan; with the tail peeled,
                    # the final backward is open dataflow — each bucket's
                    # accumulated gradient (carry + tail grad, elementwise)
                    # finalizes as the backward produces that bucket's
                    # leaves, and its collective (issued below) can run
                    # while later buckets are still being computed.  The
                    # gradient sum order is unchanged, so the peeled and
                    # scanned constructions are bit-identical.
                    head = jax.tree.map(lambda x: x[:-1], microbatches)
                    tail = jax.tree.map(lambda x: x[-1], microbatches)
                    (loss, grads), _ = jax.lax.scan(micro_step, zero, head)
                    (loss, grads), _ = micro_step((loss, grads), tail)
                else:
                    (loss, grads), _ = jax.lax.scan(
                        micro_step, zero, microbatches
                    )
                loss = loss / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
            else:
                loss, grads = jax.value_and_grad(loss_on)(params, batch)
            if poison_specs:
                # chaos: traced NaN/Inf injection into the accumulated
                # gradient (pre-comm, so detection sees exactly what the
                # collectives would spread)
                grads = self._apply_grad_poison(plan, grads, step,
                                                poison_specs)
            health_vec = None
            if self.pp_axis is not None and mesh.shape[self.pp_axis] > 1:
                # replicated-leaf grads are PARTIAL per pipeline stage: the
                # bucket allreduce spans pp, so prescaling by pp_size turns
                # its average into the required cross-stage sum
                pp_size = mesh.shape[self.pp_axis]

                def pp_dense_grad(path, g):
                    if self._is_sharded(_name_of_path(path)):
                        return g
                    return g * pp_size

                grads = jax.tree_util.tree_map_with_path(pp_dense_grad, grads)
            if overlap:
                # streamed comm stage: one collective per bucket, issued in
                # bucket (readiness) order on exactly that bucket's
                # finalized gradient — the algorithm families plug in via
                # reduce_bucket_grad (allreduce, bytegrad's codec pipeline,
                # ZeRO's reduce-scatter all ride the same machinery).
                # The spans here run at TRACE time (host-side only — the
                # jaxpr is unchanged) and record the launch ORDER and byte
                # accounting of the streamed schedule.
                if self._flat_resident:
                    # flat-resident grads are already the bucket flats.
                    # Launch order is bandwidth-tier-aware: on a two-tier
                    # mesh with the hierarchical path active, DCN-dominant
                    # buckets are streamed first so the slow link is busy
                    # for the whole backward window; the spans record each
                    # launch's tier + per-tier byte estimate so
                    # obs/attribution can split device comm seconds into
                    # ICI vs DCN.  Results assemble in plan order — issue
                    # order never changes the numerics.
                    hier = getattr(algo, "hierarchical", False)
                    order = ctx.bucket_launch_order(
                        hier, dcn_codec=algo.wire_codec_dcn
                    )
                    # error-feedback compensation folds the residual into
                    # the flats BEFORE the streamed collectives (identity
                    # — zero traced ops — unless a stateful codec rides)
                    flats, algo_state = algo.compensate_flats(
                        ctx, list(grads["flats"]), algo_state
                    )
                    reduced = [None] * len(flats)
                    for i in order:
                        # tier estimates report COMPRESSED wire bytes when
                        # a codec rides the tier, so the spans (and
                        # obs/device_comm_dcn_s attribution downstream)
                        # describe what actually crosses the wire
                        tiers = ctx.bucket_tier_bytes(
                            i, hier, dcn_codec=algo.wire_codec_dcn,
                            flat_codec=algo.wire_codec_flat,
                        )
                        with trace_span(
                            "trace/bucket_collective", bucket=i,
                            bytes=tiers["bytes"], tier=tiers["tier"],
                            ici_bytes=tiers["ici_bytes"],
                            dcn_bytes=tiers["dcn_bytes"],
                            dcn_codec=tiers["dcn_codec"],
                        ):
                            reduced[i] = algo.reduce_bucket_grad(
                                ctx, i, flats[i]
                            )
                    grads, algo_state = algo.grads_from_reduced(
                        ctx, reduced, grads, algo_state, step
                    )
                else:
                    with trace_span("trace/comm_stage", overlap=True,
                                    buckets=len(plan.buckets)):
                        grads, algo_state = algo.process_grads_bucketed(
                            ctx, grads, params, algo_state, step
                        )
            else:
                with trace_span("trace/comm_stage", overlap=False,
                                buckets=len(plan.buckets)):
                    grads, algo_state = algo.process_grads(
                        ctx, grads, params, algo_state, step
                    )
            if expert is not None:
                # Expert grads bypass the bucket plan.  The all_to_all
                # backward already SUMS every ep shard's loss contribution
                # into the owning shard's expert grad, while each shard's
                # loss is a local mean — so the global-mean gradient needs a
                # 1/ep_size rescale, then averaging over the dp(+sp) axes
                # where experts are replicated.
                ep_size = mesh.shape[expert]

                def expert_grad(g):
                    g = g / ep_size
                    return jax.lax.pmean(g, expert_dp) if expert_dp else g

                grads = jax.tree_util.tree_map_with_path(
                    lambda path, g: (
                        expert_grad(g)
                        if self._is_expert_name(_name_of_path(path)) else g
                    ),
                    grads,
                )
            if self._shard_axis is not None:
                # tp/pp-slice grads bypass the bucket plan: each shard owns
                # its slice (complete gradient) — average over the data axes
                # only, no rescale
                tp_dp = expert_dp

                def tp_grad(path, g):
                    if not self._is_sharded(_name_of_path(path)) or not tp_dp:
                        return g
                    return jax.lax.pmean(g, tp_dp)

                grads = jax.tree_util.tree_map_with_path(tp_grad, grads)
            if guard != "off" and replicated_health:
                # piggybacked health: the reduced bucket buffers are the
                # SAME array on every rank, and a NaN/Inf contribution from
                # any rank survives the sum — so per-bucket isfinite on
                # them is a globally consistent verdict, no extra
                # collective launched
                health_vec = self._grad_health_vec(plan, grads)
            params, algo_state = algo.process_pre_step(ctx, params, algo_state, step)
            with trace_span("trace/optimizer_apply",
                            owned=algo.owns_optimizer):
                if algo.owns_optimizer:
                    params, opt_state, algo_state = algo.optimizer_update(
                        ctx, params, grads, opt_state, algo_state, step
                    )
                else:
                    updates, opt_state = self._opt.update(grads, opt_state,
                                                          params)
                    params = optax.apply_updates(params, updates)
            params, algo_state = algo.process_post_step(ctx, params, algo_state, step)
            if guard != "off" and not replicated_health:
                # families whose post-comm gradient representation is not
                # rank-replicated detect on the UPDATED params instead:
                # every elementwise optimizer propagates a NaN/Inf gradient
                # into its parameter, params are materialized outputs (so
                # reading them cannot perturb backward fusion the way
                # reductions over raw grad arrays measurably do), and the
                # family's own comm makes the verdict consistent where it
                # must be — ZeRO's allgather spreads a poisoned chunk into
                # every rank's params, QAdam's momentum allreduce is
                # replicated, gossip replicas are per-rank by design (each
                # rank rewinds its own).  Model-parallel slices live only
                # on their shard, so those meshes fuse verdicts with one
                # tiny pmin.
                health_vec = self._grad_health_vec(plan, params)
                if mp_health and health_axes:
                    health_vec = jax.lax.pmin(health_vec, health_axes)

            loss = ctx.comm.allreduce(loss, ReduceOp.AVG)
            if stacked:
                params, opt_state, algo_state = (
                    _stack(params), _stack(opt_state), _stack(algo_state)
                )
            elif opt_stacked:
                opt_state = {"buckets": _stack(opt_state["buckets"]),
                             "local": opt_state["local"]}
                algo_state = _stack(algo_state)
            new_state = TrainState(state.step + 1, params, opt_state,
                                   algo_state)
            if guard == "off":
                return new_state, loss
            if guard == "skip":
                # rewind: an unhealthy step keeps the pre-step params/opt/
                # algo state bitwise (the verdict is rank-uniform, so
                # replicated state cannot diverge); the step counter still
                # advances, so a poison armed at one step cannot re-fire
                # forever.  keep=True selects the new values bitwise —
                # with healthy gradients the trajectory is byte-identical
                # to guard "off".
                keep = jnp.min(health_vec) > 0.5

                def sel(n, o):
                    return jnp.where(keep, n, o)

                new_state = TrainState(
                    new_state.step,
                    jax.tree.map(sel, new_state.params, state.params),
                    jax.tree.map(sel, new_state.opt_state, state.opt_state),
                    jax.tree.map(sel, new_state.algo_state, state.algo_state),
                )
            # a leading row axis: rank-uniform verdicts replicate ([1, b]),
            # per-rank (gossip) verdicts stack over the dp axes ([ranks, b])
            return new_state, loss, health_vec[None]

        if expert is not None and not algo.sharded_opt_state:
            pspec = P((expert,))
            state_specs = TrainState(step=P(), params=pspec, opt_state=pspec,
                                     algo_state=pspec)
        elif opt_stacked:
            # ZeRO-1: bucket chunk states stacked over the comm axes; with
            # tp/pp/ep, params and the "local" state part carry the model-
            # parallel placements
            pspec = (
                self._param_specs
                if self._shard_axis is not None or expert is not None else P()
            )
            state_specs = TrainState(step=P(), params=pspec,
                                     opt_state=self._zero_opt_specs,
                                     algo_state=P(self.comm_axes))
        elif self._shard_axis is not None:
            state_specs = TrainState(
                step=P(), params=self._param_specs,
                opt_state=self._opt_specs, algo_state=P(),
            )
        else:
            pspec = P() if replicated else P(dp)
            # the EF residual (when an error-feedback codec is active) is
            # the one replicated-family algo state with a per-rank stacked
            # leading axis; shard_map slices each rank's [1, pad] row
            state_specs = TrainState(
                step=P(), params=pspec, opt_state=pspec,
                algo_state=algo.algo_state_specs(ctx, pspec,
                                                 P(self.comm_axes)),
            )
        batch_spec = self._batch_spec()
        self._state_specs = state_specs  # reused by eval_step

        health_spec = P(self.dp_axes) if local_health else P()
        out_specs = (
            (state_specs, P()) if guard == "off"
            else (state_specs, P(), health_spec)
        )
        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(state_specs, batch_spec),
            out_specs=out_specs,
            check_vma=False,
        )
        return jax.jit(fn, donate_argnums=(0,) if self.donate else ())

    def _flat_leaf_view(self, zp):
        """Materialize the leaf pytree from the flat-resident ZeRO layout
        (traceable; slicing that XLA fuses).  The ONE implementation of the
        flats->leaves contract, shared by the train step, eval step, and
        ``unstack_params``."""
        from ..tensor import tree_from_named

        got = [int(jnp.shape(f)[-1]) for f in zp["flats"]]
        want = [b.padded_numel for b in self._plan.buckets]
        if got != want:
            raise ValueError(
                f"flat-resident state carries bucket flats of sizes {got} "
                f"but this trainer's plan expects {want} — the state was "
                "built under a different bucket plan (another trainer, or "
                "a pre-rebucket checkpoint).  Restore through "
                "restore_checkpoint(), or convert via unstack_params() on "
                "the trainer that owns the state."
            )
        named = self._plan.unflatten_to_named(zp["flats"])
        named.update(zp["local"])
        return tree_from_named(self._param_template, named)

    def _step_key(self) -> Tuple:
        """The step-cache key for the CURRENT configuration — also keys the
        cost/memory-analysis caches (one XLA cost-model query per compiled
        program, not per call)."""
        from ..faults import inject as _inject

        overlap = self._overlap_active()
        return (
            self._plan.signature(),
            self._phase,
            self.algorithm.hierarchical,
            type(self.algorithm).__name__,
            overlap,
            # chunk bytes (link-agnostic + per-tier) only reach the traced
            # program while overlap is active (_ctx nulls them otherwise) —
            # keying the raw values would recompile bit-identical
            # serialized steps
            self.overlap_chunk_bytes if overlap else 0,
            self.overlap_chunk_bytes_intra if overlap else 0,
            self.overlap_chunk_bytes_inter if overlap else 0,
            # the codec policy changes the traced program in BOTH overlap
            # and serialized constructions (compressed ring hops replace
            # fused collectives), so the raw knob values always key
            self.compress_intra,
            self.compress_inter,
            # the state layout the step is traced against: autotune v2 can
            # flip bucket-flat residency live (_apply_flat_resident), and
            # the flat and leaf constructions are different programs
            self._flat_resident,
            # grad guard: "warn" and "abort" trace the same program (the
            # policy difference is host-side), "skip" adds the rewind
            # selects; armed traced faults compile into the step, so their
            # signatures key it too
            ("skip" if self.grad_guard == "skip" else "observe")
            if self.grad_guard != "off" else "off",
            tuple(s.signature()
                  for s in _inject.armed_traced_specs("grad.poison")),
            # topk's payload shape (k per chunk) is compiled into the
            # step from BAGUA_TOPK_RATIO; keying the effective ratio
            # retraces on an env flip instead of reusing a stale k
            env.get_topk_ratio()
            if "topk" in (self.compress_intra, self.compress_inter)
            else None,
            # compile_key stays LAST: introspection (tests, debugging)
            # reads it as key[-1]
            self.algorithm.compile_key(),
        )

    def _get_step_fn(self):
        key = self._step_key()
        self._current_step_key = key
        if key not in self._step_cache:
            logger.info("bagua_tpu: compiling train step (phase=%s, %d buckets)",
                        self._phase, len(self._plan.buckets))
            with trace_span("step/build", phase=self._phase,
                            buckets=len(self._plan.buckets),
                            overlap=self._overlap_active()):
                self._step_cache[key] = self._make_step_fn(self._plan)
            # the step that triggers this compile produces a garbage-slow
            # speed sample; _auto_record_speed drops it — and the anomaly
            # detector skips the window, and the goodput ledger attributes
            # it to `compile`, for the same reason
            self._skip_next_speed_sample = True
            self._anomaly_skip_window = True
            self._ledger_window_class = "compile"
        return self._step_cache[key]

    def measured_step_dt(self) -> Optional[float]:
        """Host dispatch cadence of the previous step in seconds (injected
        straggle stalls subtracted, so a dilation can never compound into
        its own base).  Steady-state dispatch cadence equals device step
        cadence — each dispatch consumes the previous state — which makes
        this the honest base time for the ``step.straggle`` fault point."""
        return self._step_dt

    def note_injected_stall(self, seconds: float) -> None:
        """Record an injected stall that happened inside the current step
        (e.g. an async boundary's ``step.straggle`` sleep) so the next
        cadence sample subtracts it — see :meth:`measured_step_dt`."""
        self._last_straggle_sleep += float(seconds)
        self._note_stall_phase(seconds)
        if self._ledger is not None and seconds > 0:
            self._ledger.note_class_window("stall", float(seconds))

    def note_phase_duration(self, phase: str, seconds: float) -> None:
        """Attribute host seconds of the current step to a phase
        (``dispatch`` / ``collective`` / ``optimizer``) for the anomaly
        detector's ``straggler_suspect`` breakdown.  Algorithms call this
        around their host-visible waits (async negotiate/catch-up)."""
        if self.anomaly_detector is None or seconds <= 0:
            return
        self._phase_durations[phase] = (
            self._phase_durations.get(phase, 0.0) + float(seconds)
        )

    def _note_stall_phase(self, seconds: float) -> None:
        """Phase-attribute an injected ``step.straggle`` stall: the
        straggler's OWN process is locally slow (``dispatch`` — that is
        what a genuinely slow host looks like), a gated peer is *waiting*
        (``collective``)."""
        if self.anomaly_detector is None or seconds <= 0:
            return
        from ..faults import inject as _inject

        self.note_phase_duration(
            "dispatch" if _inject.straggle_targets_self() else "collective",
            seconds,
        )

    def _note_device_attribution(self, trace_dir: str) -> None:
        """A ``BAGUA_PROFILE_DIR`` auto-capture window just closed: parse
        its xplane once and publish per-bucket device comm time + overlap
        fraction (null-with-rationale on cpu-sim) into the obs summary /
        exporter.  One-shot per window, exception-free, and OFF the
        training step: a large model's xplane.pb can take seconds to
        parse, which inline would stall a dispatch (and read as a
        self-inflicted step anomaly) — a daemon thread publishes when
        done.  The bucket launch schedule is harvested from the ring HERE
        (cheap), not in the thread, so a concurrent recompile cannot skew
        the match."""
        from ..obs.attribution import bucket_launches_from_ring

        try:
            launches = bucket_launches_from_ring()
        except Exception:  # noqa: BLE001
            launches = []

        def _parse():
            try:
                from ..obs import export as _obs_export
                from ..obs.attribution import attribute_device_comm

                record = attribute_device_comm(trace_dir,
                                               bucket_launches=launches)
                _obs_export.note_device_attribution(record)
                if record.get("available"):
                    logger.info(
                        "device attribution: comm %.6fs/step, overlap "
                        "%.1f%% (%s)", record.get("comm_s_per_step") or 0.0,
                        100.0 * (record.get("overlap_fraction") or 0.0),
                        trace_dir,
                    )
                else:
                    logger.info("device attribution unavailable: %s",
                                record.get("rationale"))
            except Exception as e:  # noqa: BLE001
                logger.warning("device attribution failed: %s", e)

        threading.Thread(target=_parse, name="bagua-obs-attribution",
                         daemon=True).start()

    def _note_step_cadence(self) -> None:
        now = time.monotonic()
        if self._last_step_mono is not None:
            raw = now - self._last_step_mono
            dt = raw - self._last_straggle_sleep
            if dt > 0:
                self._step_dt = dt
            window_cls = None
            if self._ledger is not None and raw > 0:
                # goodput ledger: the wall window that just closed belongs
                # to the previous step; class windows noted inside it
                # (checkpoint, async boundaries, stalls) were already
                # deducted by the ledger.  The remainder is productive-step
                # time — unless the window contained a trace+compile or a
                # state migration (XLA compiles lazily on first dispatch,
                # so the build span alone under-counts): then the whole
                # remainder is that class's wall, mirroring
                # _skip_next_speed_sample.
                window_cls = self._ledger_window_class or "productive_step"
                self._ledger_window_class = None
                self._ledger.note_step_window(
                    self._step_counter - 1, raw, window_cls)
            if window_cls in (None, "productive_step"):
                # MFU only from productive windows: a compile/migration
                # window's dt would publish a garbage-low sample that
                # rides the beacon to the fleet view
                self._maybe_note_mfu()
            if self.anomaly_detector is not None and raw > 0:
                # the wall window that just closed belongs to the PREVIOUS
                # step; its phase attributions were accumulated during it.
                # A window that contained a compile or a state migration
                # is skipped outright — an expected one-off stall must not
                # flag (autotune retunes recompile every sample) nor enter
                # the baseline.
                phases, self._phase_durations = self._phase_durations, {}
                if self._anomaly_skip_window:
                    self._anomaly_skip_window = False
                else:
                    self.anomaly_detector.observe(
                        self._step_counter - 1, raw, phases
                    )
        self._last_step_mono = now
        if self._obs_enabled:
            # fleet view: the per-rank step/step-dt summary the health
            # beacon (and the metrics exporter) publish
            from ..obs import export as _obs_export

            _obs_export.note_step(self._step_counter, self._step_dt)

    def _maybe_note_mfu(self) -> None:
        """Per-step MFU gauge: the cached cost-model flops of the current
        compiled step over (measured step cadence x peak silicon FLOP/s).
        Null-with-rationale where the denominator is unknown (cpu-sim,
        unlisted device kinds) — published once, like ``trace_overlap``."""
        if not self._obs_enabled:
            return
        from ..obs import export as _obs_export

        if self._peak_flops is None:
            if not self._mfu_noted_unavailable:
                self._mfu_noted_unavailable = True
                _obs_export.note_mfu({
                    "available": False,
                    "rationale": (
                        "no peak-FLOPS table entry for device kind "
                        f"{jax.devices()[0].device_kind!r} (cpu-sim or "
                        "unlisted silicon) — MFU needs a silicon peak "
                        "denominator"
                    ),
                })
            return
        if not self._mfu_flops or not self._step_dt:
            return
        mfu = self._mfu_flops / self._step_dt / self._peak_flops
        _obs_export.note_mfu({
            "available": True,
            "mfu": round(mfu, 4),
            "flops_per_step": self._mfu_flops,
            "peak_flops": self._peak_flops,
            "step_dt": round(self._step_dt, 6),
        })

    def _maybe_prepare_mfu(self, state: TrainState, batch) -> None:
        """Stash the current compiled step's cost-model flops for the
        cadence hook's MFU gauge.  The cost analysis is cached per
        step-cache key; a MISSING entry is harvested in a background
        daemon thread from abstract avals captured here — jax's AOT
        ``lower().compile()`` does not share the jit dispatch cache, so an
        inline harvest would pay a second full XLA compile on the
        train-step hot path at every new key (first step, autotune
        retunes, phase switches).  Skipped entirely when no silicon peak
        is known — the null-with-rationale record needs no cost model."""
        if self._peak_flops is None:
            self._maybe_note_mfu()  # publish the rationale once
            return
        key = self._current_step_key
        cached = self._cost_analysis_cache.get(key)
        if cached is not None:
            self._mfu_flops = cached.get("flops")
            return
        # pause the gauge until THIS program's flops land: publishing the
        # previous key's flops against the new program's cadence (for the
        # whole duration of a background compile) would be wrong, not late
        self._mfu_flops = None
        if key in self._cost_analysis_pending:
            return
        done = threading.Event()
        self._cost_analysis_pending[key] = done
        fn = self._step_cache.get(key)

        def _abstract(x):
            if not hasattr(x, "shape"):
                return x
            sharding = getattr(x, "sharding", None)
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

        # host metadata only — live buffers are about to be donated to
        # the dispatch, so the thread must not hold them
        a_state, a_batch = jax.tree.map(_abstract, (state, batch))

        def _harvest():
            try:
                try:
                    # deliberately NOT the ledger-mapped span name: this
                    # compile overlaps step windows on another thread, and
                    # a mapped span here would wrongly deduct from them
                    with trace_span("obs/cost_analysis_async"):
                        compiled = fn.lower(a_state, a_batch).compile()
                        analysis = compiled.cost_analysis()
                except Exception as e:  # noqa: BLE001 - backend-dependent
                    logger.warning(
                        "step_cost_analysis unavailable on %r backend: %s",
                        jax.default_backend(), e,
                    )
                    from ..telemetry import counters

                    counters.incr("obs/cost_analysis_unavailable")
                    self._cost_analysis_cache[key] = {}
                    self._memory_analysis_cache[key] = None
                    return
                from ..obs.memory import compiled_memory_analysis

                self._memory_analysis_cache[key] = \
                    compiled_memory_analysis(compiled)
                if isinstance(analysis, (list, tuple)):
                    analysis = analysis[0] if analysis else {}
                self._cost_analysis_cache[key] = \
                    dict(analysis) if analysis else {}
            finally:
                self._cost_analysis_pending.pop(key, None)
                done.set()

        threading.Thread(target=_harvest, name="bagua-obs-cost-analysis",
                         daemon=True).start()

    def _note_static_footprint(self, state: TrainState) -> None:
        """One-shot static HBM footprint of the live training state +
        bucket plan (:func:`bagua_tpu.obs.memory.static_footprint`) into
        the obs summary / exporter gauges.  Host metadata only."""
        self._footprint_noted = True
        try:
            from ..obs import export as _obs_export
            from ..obs.memory import static_footprint

            _obs_export.note_hbm_footprint(static_footprint(self, state))
        except Exception as e:  # noqa: BLE001 - accounting must not kill
            logger.debug("static footprint not computed: %s", e)

    def _maybe_poll_device_memory(self) -> None:
        """Live ``device.memory_stats()`` poll (real TPU: peak bytes +
        headroom gauges), throttled to the beacon cadence.  A STABLE
        unavailable answer (cpu-sim's "no HBM stats") disables polling
        after publishing the rationale once; transient failures (a runtime
        hiccup mid-run) keep polling until a consecutive-failure budget —
        a multi-day run must not lose its capacity gauges to one flake."""
        if self._mem_poll_dead:
            return
        try:
            from ..obs import export as _obs_export
            from ..obs.memory import live_memory_stats

            record = live_memory_stats()
            if record.get("available"):
                self._mem_poll_failures = 0
            elif record.get("transient"):
                self._mem_poll_failures += 1
                if self._mem_poll_failures >= 5:
                    self._mem_poll_dead = True
            else:
                self._mem_poll_dead = True
            _obs_export.note_hbm_live(record)
        except Exception as e:  # noqa: BLE001
            self._mem_poll_failures += 1
            if self._mem_poll_failures >= 5:
                self._mem_poll_dead = True
            logger.debug("device memory poll failed: %s", e)

    def train_step(self, state: TrainState, batch) -> Tuple[TrainState, jax.Array]:
        from ..communication import check_abort
        from ..faults import inject as _inject

        check_abort()  # fail fast once a rank/watchdog flagged an abort
        self._step_counter += 1
        if self._obs_enabled:
            # every span opened while this step is driven (including the
            # watchdog waiter's) carries the step number
            _obs_spans.set_current_step(self._step_counter)
        if self._profiler is not None:
            self._profiler.on_step(self._step_counter - 1)
        # step.straggle: a slow peer gates this step only when the family's
        # step synchronizes with every rank (per-step gradient collective);
        # async families pay at their own negotiated boundaries instead
        self._note_step_cadence()
        if self._profiler is not None and self._obs_enabled:
            closed = self._profiler.consume_closed_dir()
            if closed:
                self._note_device_attribution(closed)
        self._last_straggle_sleep = _inject.maybe_straggle(
            "step", base_dt=self._step_dt,
            gated=self.algorithm.straggler_gates_step,
        )
        self._note_stall_phase(self._last_straggle_sleep)
        if self._ledger is not None and self._last_straggle_sleep > 0:
            self._ledger.note_class_window("stall", self._last_straggle_sleep)
        state = self.algorithm.host_pre_step(self, state)
        if self.algorithm.need_reset(self._step_counter - 1):
            self._phase += 1
            # reference re-runs init_tensors + rebucketing at phase switches
            # (distributed.py:427-435); plan shape is identical here, phase key
            # selects the recompiled step.
        if (
            self.autotune
            and not self._autotune_completed
            and self._step_counter % 100 == 0
        ):
            self._autotune_step(state)
        if (
            self.autotune
            and not self._autotune_completed
            and not self._telemetry_reported
            and env.get_autotune_level() >= 2
        ):
            self._report_tensor_execution_order(state, batch)
        if (
            not self._overlap_ordered
            and self._overlap_active()
            and not self.algorithm.sharded_opt_state
            and not self.autotune
        ):
            # one-time readiness re-bucketing (reverse execution order);
            # skipped under autotune — its recommendation path owns bucket
            # order there (span-driven, _report_tensor_execution_order) and
            # a trainer-local re-split would discard the recommended
            # boundaries — and for sharded-opt-state families, whose chunk
            # states are keyed on bucket boundaries (rebucket would orphan
            # them)
            self._overlap_ordered = True
            self._reorder_plan_for_overlap(state, batch)
        if self._pending_state_migration is not None:
            # queued layout migrations (autotune family switch crossing the
            # optimizer-ownership boundary, flat-resident relayout after a
            # rebucket) convert the live state before the recompiled step
            # consumes it; the span feeds the ledger's state_migration class
            with trace_span("step/state_migration"):
                state = self._pending_state_migration(state)
            self._pending_state_migration = None
            self._anomaly_skip_window = True
            if self._ledger_window_class is None:
                # a migration usually triggers a recompile too, which then
                # claims the window — the migration span already fed its
                # own execution wall either way
                self._ledger_window_class = "state_migration"
        fn = self._get_step_fn()
        if self._obs_enabled:
            self._maybe_prepare_mfu(state, batch)
            if not self._footprint_noted:
                self._note_static_footprint(state)
        # poison accounting reads the persisted state.step BEFORE dispatch:
        # the buffers are donated to fn, and the compiled fault fires on
        # state.step (which resumes from checkpoints), not the
        # trainer-local call counter
        self._note_traced_fault_fires(state)
        _dispatch_t0 = time.monotonic()
        with trace_span("step/dispatch"):
            out = fn(state, batch)
        self.note_phase_duration("dispatch",
                                 time.monotonic() - _dispatch_t0)
        if self.grad_guard != "off":
            new_state, loss, health_vec = out
            self.step_metrics = {
                "grad_healthy": jnp.min(health_vec),
                "grad_health_buckets": jnp.min(health_vec, axis=0),
            }
            self._note_step_health(health_vec)
            out = (new_state, loss)
        if self._watchdog is not None:
            # asynchronous watching: dispatch continues at full speed while
            # the watchdog's waiter thread reads the loss back inside a
            # watched section (a host readback — block_until_ready-family
            # signals can return while work is still queued on tunneled
            # transports, which would blind the watchdog to real hangs).
            # A cross-rank deadlock pins the waiter past the timeout.
            self._watchdog.watch_result(
                out[1], f"train_step[{self._step_counter}]"
            )
        self._auto_record_speed(batch)
        if self._obs_enabled:
            # fleet view, worker half: refresh this rank's beacon so the
            # launcher's heartbeat carries a LIVE step/staleness summary,
            # not only the unhealthy-event snapshots.  Throttled to ~one
            # tiny file write per 2 s; no-op without the launcher-injected
            # beacon path.
            now = time.monotonic()
            if now - self._last_beacon_write > 2.0:
                self._last_beacon_write = now
                self._maybe_poll_device_memory()
                from ..elastic.membership import write_health_beacon

                write_health_beacon()
        return out

    # ---- gradient-health sentinel (host-side policy) ---------------------

    def _note_step_health(self, health_vec) -> None:
        """Queue this step's (async) health verdict and act on the ones
        already complete.  The guard inspects each step's verdict when the
        NEXT step is dispatched — by then the previous program has
        finished, so the readback does not stall the dispatch pipeline."""
        self._pending_health.append((self._step_counter, health_vec))
        while len(self._pending_health) > 1:
            self._consume_health(*self._pending_health.pop(0))

    def flush_grad_health(self) -> None:
        """Drain every not-yet-inspected step verdict (blocking readback).
        Call at a training-loop boundary so the FINAL step's verdict is
        acted on too — per-step inspection always runs one step behind."""
        while self._pending_health:
            self._consume_health(*self._pending_health.pop(0))

    @staticmethod
    def _local_value(arr):
        """Host value of a (possibly multi-process global) array — the
        LOCAL shard when the global cannot be fetched whole, the same
        per-process contract as the watchdog's readback fence."""
        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(arr)
        return np.asarray(arr.addressable_shards[0].data)

    def _consume_health(self, step_no: int, health_vec) -> None:
        from ..communication import abort
        from ..faults import inject as _inject
        from ..telemetry import counters

        # min over verdict rows (rank-uniform verdicts replicate; per-rank
        # gossip verdicts stack — this process acts on ALL its local rows,
        # so multi-device processes see every local replica's verdict)
        _verdict_t0 = time.monotonic()
        with trace_span("step/grad_guard_verdict", step=step_no):
            if getattr(health_vec, "is_fully_addressable", True):
                hv = np.asarray(health_vec)
            else:
                hv = np.concatenate(
                    [np.asarray(s.data)
                     for s in health_vec.addressable_shards], axis=0
                )
        # the verdict readback is host optimizer-adjacent work: it blocks
        # on the previous step's update having completed
        self.note_phase_duration("optimizer",
                                 time.monotonic() - _verdict_t0)
        hv = hv.min(axis=0)
        if self._obs_enabled:
            # host-safe mirror of the verdict: the flight recorder
            # republishes these from abort paths where touching a device
            # array could hang
            from ..obs import export as _obs_export

            _obs_export.note_step_metrics({
                "grad_health_step": step_no,
                "grad_healthy": float(hv.min()),
            })
        if bool(hv.min() > 0.5):
            self._guard_skips = 0
            return
        bad = [i for i, v in enumerate(hv) if v <= 0.5]
        counters.incr("grad_guard/unhealthy_steps")
        abort_msg = None
        if self.grad_guard == "warn":
            logger.warning(
                "grad guard: step %d produced non-finite gradients "
                "(buckets %s) — policy 'warn': the update was APPLIED and "
                "replicated state is now poisoned; use BAGUA_GRAD_GUARD="
                "skip to rewind such steps", step_no, bad,
            )
        elif self.grad_guard == "abort":
            counters.incr("grad_guard/aborts")
            # later queued verdicts describe steps run on the already-
            # poisoned state: acting on them after the operator resets the
            # abort and restores a clean checkpoint would re-trip the
            # guard spuriously
            self._pending_health.clear()
            abort_msg = (
                f"grad guard: step {step_no} produced non-finite gradients "
                f"(buckets {bad})"
            )
        elif self.grad_guard == "skip":
            self._guard_skips += 1
            self._guard_rewinds_total += 1
            counters.incr("grad_guard/skipped_steps")
            if self._ledger is not None:
                # the step's wall was spent, its update discarded: move its
                # recorded productive seconds to the rewind badput class
                self._ledger.reclassify_step_rewind(step_no)
            _inject.record_recovery("grad.poison")
            logger.warning(
                "grad guard: step %d produced non-finite gradients "
                "(buckets %s) — step rewound (params/opt state untouched; "
                "%d/%d consecutive skips)", step_no, bad,
                self._guard_skips, self.grad_guard_budget,
            )
            if self._guard_skips >= self.grad_guard_budget:
                counters.incr("grad_guard/aborts")
                self._pending_health.clear()
                abort_msg = (
                    f"grad guard: {self._guard_skips} consecutive unhealthy "
                    f"steps reached the skip budget "
                    f"({self.grad_guard_budget}) — systematic divergence, "
                    "not a transient bad batch"
                )
        # surface the event to the elastic coordinator AFTER the policy
        # counters above, so the published payload includes this event's
        # skip/abort bookkeeping; the launcher's lease heartbeat carries
        # these counters as a health payload and a rank producing repeated
        # non-finite gradients can be fenced out by the epoch/resize
        # machinery (no-op unless the launcher injected
        # BAGUA_ELASTIC_HEALTH_FILE)
        from ..elastic.membership import write_health_beacon

        write_health_beacon()
        if abort_msg is not None:
            # flight recorder: grad-guard abort and skip-budget escalation
            # both land here — the post-mortem names the offending step and
            # buckets before the abort flag stops every control loop
            from ..obs.recorder import dump_flight_record

            dump_flight_record(
                "grad_guard_abort", reason=abort_msg,
                extra={"step": step_no, "unhealthy_buckets": bad,
                       "policy": self.grad_guard,
                       "consecutive_skips": self._guard_skips},
            )
            abort(abort_msg)

    def _note_traced_fault_fires(self, state: TrainState) -> None:
        """Host-side telemetry for traced faults: the compiled step fires
        ``grad.poison`` on its own; mirror the event into the counters by
        reading the step counter the traced condition actually compares
        against — ``state.step``, which survives checkpoint resumes where
        the trainer-local call counter restarts at 0.  The readback only
        happens while a poison spec is armed (drills), never in clean
        runs."""
        from ..faults import inject as _inject

        specs = _inject.armed_traced_specs("grad.poison")
        if not specs:
            return
        traced_step = int(self._local_value(state.step))
        for spec in specs:
            if spec.step is not None:
                fired = spec.step == traced_step
            else:  # the compiled step-window semantics of _apply_grad_poison
                fired = spec.count < 0 or traced_step < spec.count
            if fired:
                _inject.note_traced_fire(spec)

    def _auto_record_speed(self, batch) -> None:
        """Feed the throughput tracker from the step itself (reference
        measures its own speed with paired events in the forward-pre hook,
        distributed.py:340-358).  The global batch's leading dim is the
        sample count; dispatch cadence equals steady-state step cadence
        because each step consumes the previous state, so the host paces to
        device throughput.  An explicit :meth:`record_speed` call switches
        to manual mode — autotune never silently scores 0 either way."""
        if self._manual_speed or self._autotune_completed:
            # manual mode, or nothing will ever read the tracker (the only
            # consumer is the autotune check-in) — skip the per-step host work
            return
        leaves = jax.tree.leaves(batch)
        if not leaves or not jnp.ndim(leaves[0]):
            return
        now = time.time()
        dt = now - self._last_speed_time
        self._prev_speed_time = self._last_speed_time
        self._last_speed_time = now
        if self._skip_next_speed_sample:
            # this interval spanned trace+compile of a (re)built step — a
            # garbage low sample that would skew the autotune score; start
            # the clock here instead
            self._skip_next_speed_sample = False
            return
        if dt > 0:
            self._speed_tracker.record(leaves[0].shape[0] / dt)

    def step_cost_analysis(self, state: TrainState, batch) -> Dict[str, Any]:
        """XLA's cost model for the current compiled train step ("flops",
        "bytes accessed", ...) — feeds bench.py's achieved-TFLOP/s and MFU
        reporting, the per-step ``obs/mfu`` gauge, and the
        physically-impossible-number sanity bound.  Cached per step-cache
        key (the lower+compile+query round-trip is paid once per compiled
        program, not per call); the same pass harvests
        ``memory_analysis()`` for :meth:`step_memory_analysis`.  Returns {}
        when the backend can't provide one (no reference counterpart;
        NCCL/CUDA expose no per-step cost model) — logged at warning with
        the backend name and counted in ``obs/cost_analysis_unavailable``
        so the silent-{} path is visible in the fleet view."""
        from ..telemetry import counters

        fn = self._get_step_fn()
        key = self._current_step_key
        cached = self._cost_analysis_cache.get(key)
        if cached is not None:
            return dict(cached)
        pending = self._cost_analysis_pending.get(key)
        if pending is not None:
            # a background harvest for this key is already compiling the
            # same program — join it instead of paying a duplicate AOT
            # compile (minutes on large models)
            pending.wait(timeout=1800)
            cached = self._cost_analysis_cache.get(key)
            if cached is not None:
                return dict(cached)
        try:
            with trace_span("step/cost_analysis"):
                compiled = fn.lower(state, batch).compile()
                analysis = compiled.cost_analysis()
        except Exception as e:  # pragma: no cover - backend-dependent
            logger.warning(
                "step_cost_analysis unavailable on %r backend: %s",
                jax.default_backend(), e,
            )
            counters.incr("obs/cost_analysis_unavailable")
            self._cost_analysis_cache[key] = {}
            self._memory_analysis_cache[key] = None
            return {}
        from ..obs.memory import compiled_memory_analysis

        self._memory_analysis_cache[key] = compiled_memory_analysis(compiled)
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        result = dict(analysis) if analysis else {}
        if not result:
            logger.warning(
                "step_cost_analysis empty on %r backend (cost model "
                "returned no entries)", jax.default_backend(),
            )
            counters.incr("obs/cost_analysis_unavailable")
        self._cost_analysis_cache[key] = result
        return dict(result)

    def step_memory_analysis(self, state: TrainState,
                             batch) -> Optional[Dict[str, int]]:
        """XLA's compiled-executable memory analysis for the current step
        (argument/output/temp bytes and a ``peak_bytes`` estimate), cached
        per step-cache key alongside :meth:`step_cost_analysis`.  None when
        the backend provides no analysis (cpu-sim) — the static
        :mod:`bagua_tpu.obs.memory` footprint stays the fit signal there."""
        key = self._step_key()
        if key not in self._memory_analysis_cache:
            self.step_cost_analysis(state, batch)
        return self._memory_analysis_cache.get(key)

    def trace_step(self, state: TrainState, batch):
        """Abstract-eval of the current train-step construction: the jitted
        step's ``ClosedJaxpr``, obtained by tracing only — no compile, no
        execution, ``state``/``batch`` untouched (donation binds at run
        time, not trace time).  This is the entry point the
        :mod:`bagua_tpu.analysis` jaxpr collective-consistency checker uses
        to extract a construction's collective sequence (mesh-axis binding,
        ``cond``-branch divergence, overlap-vs-serialized multiset
        equality)."""
        fn = self._get_step_fn()
        if hasattr(fn, "trace"):  # jax >= 0.4.34 jit-stages API
            return fn.trace(state, batch).jaxpr
        return jax.make_jaxpr(lambda s, b: fn(s, b))(state, batch)

    def _make_eval_fn(self, state_specs, batch_spec):
        algo = self.algorithm
        expert = self.expert_axis
        stacked = (
            (not algo.replicated_params) or expert is not None
        ) and not algo.sharded_opt_state

        if self._flat_resident:
            leaf_view = self._flat_leaf_view

            def loss_on(zp, b):
                return self.loss_fn(leaf_view(zp), b)
        else:
            loss_on = self.loss_fn

        def per_shard(state: TrainState, batch):
            params = state.params
            if stacked:
                params = jax.tree.map(lambda x: x[0], params)
            rows = jax.tree.leaves(batch)[0].shape[0]
            accum = self.accum_steps if rows % self.accum_steps == 0 else 1
            if accum > 1:
                # keep eval's working set at the train step's microbatch
                # size — accum_steps exists because the full batch doesn't
                # fit; mean of equal-size microbatch means == full mean
                microbatches = jax.tree.map(
                    lambda x: x.reshape((accum, x.shape[0] // accum)
                                        + x.shape[1:]),
                    batch,
                )
                loss = jnp.mean(jax.lax.map(
                    lambda mb: loss_on(params, mb), microbatches
                ))
            else:
                loss = loss_on(params, batch)
            return self._comm.allreduce(loss, ReduceOp.AVG)

        fn = shard_map(per_shard, mesh=self.mesh,
                       in_specs=(state_specs, batch_spec), out_specs=P(),
                       check_vma=False)
        return jax.jit(fn)

    def eval_step(self, state: TrainState, batch) -> jax.Array:
        """Forward-only mean loss over the global batch — same sharding as
        ``train_step`` (state untouched, nothing donated).  Evaluation has
        no reference counterpart hook (the reference evaluates on the raw
        torch module); here the jitted step owns the sharded params, so the
        trainer provides the entry point."""
        # keyed like _get_step_fn: a rebucket / phase reset / autotune family
        # switch that changes the state layout must not evaluate with stale
        # specs (build or fetch the compiled step first, then lift its specs)
        self._get_step_fn()
        key = (self._plan.signature(), self._phase,
               self.algorithm.hierarchical, type(self.algorithm).__name__,
               self.algorithm.compile_key())  # eval has no comm-stage overlap
        if getattr(self, "_eval_key", None) != key:
            self._eval_fn = self._make_eval_fn(self._state_specs,
                                               self._batch_spec())
            self._eval_key = key
        from ..communication import check_abort

        check_abort()
        loss = self._eval_fn(state, batch)
        if self._watchdog is not None:
            # same hang-surfacing contract as train_step: a wedged eval
            # allreduce must pin the watchdog's waiter, not hang silently
            self._watchdog.watch_result(loss, "eval_step")
        return loss

    def _report_tensor_execution_order(self, state, batch) -> None:
        """Feed the sidecar the observed gradient-readiness order (the
        reference's OTel tensor_ready span pipeline,
        bagua-opentelemetry/src/exporter/mod.rs:15-59): one-time, host-side,
        off the hot path.  Enabled at BAGUA_AUTOTUNE >= 2 (profiling costs one
        small compile per tensor)."""
        self._telemetry_reported = True
        try:
            from ..communication import get_hyperparameters_service_client
            from ..telemetry import profile_tensor_execution_order

            params = self.unstack_params(state)
            spans = profile_tensor_execution_order(self.loss_fn, params, batch)
            if self._autotune_client is None:
                self._autotune_client = get_hyperparameters_service_client()
            self._autotune_client.report_tensor_execution_order(
                spans, model_name=self.model_name
            )
            logger.info("telemetry: reported execution order for %d tensors",
                        len(spans))
        except Exception as e:  # telemetry must never take down training
            logger.warning("telemetry report failed: %s", e)

    # ---- autotune check-in (reference distributed.py:213-242) ------------

    def _autotune_register_tensors(self):
        """Declare communicated tensors to the sidecar (reference
        distributed.py:387-406)."""
        from ..communication import get_hyperparameters_service_client

        try:
            if self._autotune_client is None:
                self._autotune_client = get_hyperparameters_service_client()
            rsp = self._autotune_client.register_tensors(
                model_name=self.model_name,
                tensor_list=[p.declaration().model_dump() for p in self._named_params],
                capabilities=self._autotune_capabilities(),
            )
            # apply the service's initial recommendation so trainer and
            # service agree on the config the first score is attributed to
            # (reference distributed.py:387-406)
            from ..define import BaguaHyperparameter

            rec = BaguaHyperparameter(**rsp.get("recommended_hyperparameters", {}))
            self._apply_recommendation(rec)
        except Exception as e:  # autotune must never take down training
            logger.warning("autotune register_tensors failed: %s", e)
            self.autotune = False

    def _autotune_capabilities(self) -> Optional[dict]:
        """What this trainer's mesh / family / layout makes legal — sent
        once at tensor registration so the service builds the
        capability-gated v2 knob space for exactly the knobs this trainer
        can apply (a knob the trainer would refuse is never searched).
        ``None`` keeps the legacy two-knob space
        (``BAGUA_AUTOTUNE_SPACE=legacy``)."""
        if env.get_autotune_space() == "legacy":
            return None
        from ..algorithms import SWITCHABLE_ALGORITHMS

        current = getattr(self.algorithm, "name", None) or ""
        families: list = []
        flat_families: list = []
        if current in SWITCHABLE_ALGORITHMS:
            for name, ctor in SWITCHABLE_ALGORITHMS.items():
                proto = self._user_algorithms.get(name) or ctor(False)
                if name != current:
                    # static mirror of _maybe_switch_algorithm's refusals:
                    # a family the trainer would refuse must not be in the
                    # space (its windows would score the refusal, not the
                    # config)
                    if (
                        self.algorithm.owns_optimizer
                        and not proto.owns_optimizer
                        and self.optimizer is None
                    ):
                        continue
                    if proto.replicated_params != self.algorithm.replicated_params:
                        if self.algorithm.owns_optimizer or proto.owns_optimizer:
                            continue
                        if (
                            self.expert_axis is not None
                            or self._shard_axis is not None
                        ):
                            continue
                families.append(name)
                if proto.supports_flat_resident:
                    flat_families.append(name)
        flat_ok = (
            self._flat_supported()
            and self.algorithm.replicated_params
            and not self.algorithm.owns_optimizer
            and not self.algorithm.sharded_opt_state
            and self.optimizer is not None
            and getattr(self.optimizer, "fused_inner", None) is None
            and _optimizer_flattens_safely(self.optimizer)
        )
        return {
            "space": "v2",
            "two_tier": self._inter is not None and self._intra is not None,
            "ef_ok": bool(self._ef_enabled),
            "flat_ok": bool(flat_ok),
            "families": families,
            "flat_families": flat_families,
            "current_algorithm": current,
        }

    def _apply_recommendation(self, recommended) -> None:
        # snapshot EF-residual activeness: any knob below (family switch,
        # codec policy, hierarchical toggle) can flip it, and the flip is a
        # state migration (_sync_ef_state at the end)
        ef_was = self._ef_active()
        self._maybe_switch_algorithm(recommended)
        # overlap knobs ride the same recommendation path as bucketing so
        # the two compose: a re-bucketed plan keeps the overlap mode, and
        # an overlap flip recompiles via the step-cache key
        if recommended.overlap in ("auto", "on", "off"):
            self.overlap = recommended.overlap
        if recommended.overlap_chunk_bytes:
            self.overlap_chunk_bytes = int(recommended.overlap_chunk_bytes)
        if recommended.overlap_chunk_bytes_intra:
            self.overlap_chunk_bytes_intra = int(
                recommended.overlap_chunk_bytes_intra
            )
        if recommended.overlap_chunk_bytes_inter:
            self.overlap_chunk_bytes_inter = int(
                recommended.overlap_chunk_bytes_inter
            )
        # codec policy rides the same path ("" = keep current): the
        # autopilot's compress_dcn trend hint actuates compress_inter here
        # — every rank applies it at its next check-in (the service's
        # per-train_iter decision cache keeps it SPMD-uniform) and the
        # step-cache key re-jits the compressed construction
        from ..compression.codecs import validate_codec_policy

        for attr in ("compress_intra", "compress_inter"):
            value = getattr(recommended, attr, "")
            if value:
                try:
                    setattr(self, attr, validate_codec_policy(value, attr))
                except ValueError as e:
                    logger.warning("autotune recommendation ignored: %s", e)
        if recommended.buckets:
            named_by_name = {p.name: p for p in self._named_params}
            decl_buckets = [
                [d for d in bucket if d.name in named_by_name]
                for bucket in recommended.buckets
            ]
            decl_buckets = [b for b in decl_buckets if b]
            if decl_buckets:
                self.rebucket(decl_buckets)
                self.bucket_bytes = recommended.bucket_size
        # flat-residency rides the recommendation path AFTER any rebucket
        # so the queued flat<->leaf conversion composes against the plan
        # the step will actually run (migrations apply in queue order)
        if getattr(recommended, "flat_resident", ""):
            self._apply_flat_resident(recommended.flat_resident)
        # hierarchical toggle is only meaningful when the mesh has both
        # tiers, and only for families whose staged path is layout-free.
        # ZeRO is excluded: its staged mode changes the OPT-STATE SHARDING
        # (intra vs world chunks), so flipping the flag mid-run would
        # desync the state layout from the compiled step — autotune is
        # force-disabled for sharded-opt-state families anyway, so this is
        # belt-and-braces
        if (
            self._inter is not None
            and self._intra is not None
            and not self.algorithm.sharded_opt_state
        ):
            self.algorithm.hierarchical = bool(recommended.is_hierarchical_reduce)
        self._sync_ef_state(ef_was)

    def _apply_flat_resident(self, want: str) -> None:
        """Apply a ``flat_resident`` recommendation ("on"/"off"; v2 knob).

        Before ``init()`` resolves the layout, this only adjusts the MODE —
        the state is then built directly in the recommended layout, no
        conversion needed.  After, it queues a live flat<->leaf state
        migration (the same structural conversion ``restore_checkpoint``
        uses for cross-layout restores): every param-shaped subtree of the
        TrainState — params and the optimizer moments that mirror them —
        swaps between the leaf pytree and the ``{"flats", "local"}`` bucket
        container, under the CURRENT plan, so no training math changes.
        The flip re-jits through ``_step_key`` (``self._flat_resident`` is
        keyed) and the migration window lands in the goodput ledger's
        ``state_migration`` class, so the search pays for its own curiosity
        honestly.

        Refusal cases (logged, never raised — a recommendation must not
        take down training): families owning their optimizer or sharding
        opt state (their state is not param-mirrored), unsupported meshes,
        optimizers that don't commute with flattening, and fused-wrapper
        optimizers (the wrapper's leaf state and its inner's flat state
        are not positionally convertible — a live flip would re-init
        momentum)."""
        if want not in ("on", "off"):
            return
        if not self._flat_layout_live:
            # registration-time recommendation: init() is about to build
            # the state — steer _resolve_flat_resident instead of migrating
            if want == "off" or (
                self._flat_supported()
                and not self.algorithm.owns_optimizer
                and not self.algorithm.sharded_opt_state
                and _optimizer_flattens_safely(self._flat_opt())
            ):
                self.flat_resident = want
            else:
                logger.info(
                    "autotune: flat_resident=%s not supported by this "
                    "configuration; keeping mode %r", want, self.flat_resident,
                )
            return
        want_on = want == "on"
        if want_on == self._flat_resident:
            return
        algo = self.algorithm
        if (
            algo.owns_optimizer
            or algo.sharded_opt_state
            or not algo.replicated_params
        ):
            logger.info(
                "autotune: live flat_resident=%s ignored — %s state is not "
                "param-mirrored replicated", want, type(algo).__name__,
            )
            return
        if getattr(self.optimizer, "fused_inner", None) is not None:
            logger.info(
                "autotune: live flat_resident flip ignored — fused-wrapper "
                "optimizer state is not convertible in place",
            )
            return
        if want_on and not (
            self._flat_supported()
            and self.optimizer is not None
            and _optimizer_flattens_safely(self.optimizer)
        ):
            logger.info(
                "autotune: flat_resident=on refused — layout unsupported "
                "or optimizer does not commute with flattening",
            )
            return
        if self._param_template is None or self._plan is None:
            return
        param_def = jax.tree_util.tree_structure(self._param_template)
        if param_def == jax.tree_util.tree_structure(0):
            logger.info(
                "autotune: flat_resident flip needs a structured param "
                "tree (bare-leaf params cannot be located structurally)",
            )
            return
        plan, template = self._plan, self._param_template
        is_zp = self._is_flat_container

        def is_param_tree(x):
            try:
                return jax.tree_util.tree_structure(x) == param_def
            except Exception:  # unhashable/exotic leaves
                return False

        if want_on:

            def convert(state):
                logger.info("autotune: relaying state leaf -> bucket-flat")

                def to_flat(x):
                    if is_param_tree(x):
                        return {"flats": tuple(plan.flatten_tree(x)),
                                "local": {}}
                    return x

                return jax.tree.map(to_flat, state, is_leaf=is_param_tree)
        else:
            from ..tensor import tree_from_named

            def convert(state):
                logger.info("autotune: relaying state bucket-flat -> leaf")

                def from_flat(x):
                    if is_zp(x):
                        named = plan.unflatten_to_named(list(x["flats"]))
                        named.update(x["local"])
                        return tree_from_named(template, named)
                    return x

                return jax.tree.map(from_flat, state, is_leaf=is_zp)

        self._queue_state_migration(convert)
        self._flat_resident = want_on
        logger.info("autotune: flat_resident -> %s (migration queued)", want)

    def _maybe_switch_algorithm(self, recommended) -> None:
        """Swap the algorithm family if the autotuner asked for one
        (BAGUA_AUTOTUNE_ALGORITHM=1).  Stateless replicated families swap
        freely; QAdam rides the state-migration adapter
        (:meth:`_prepare_state_migration`)."""
        from ..algorithms import SWITCHABLE_ALGORITHMS

        target = recommended.algorithm
        current = getattr(self.algorithm, "name", None)
        if (
            not target
            or target == current
            or current not in SWITCHABLE_ALGORITHMS
            or target not in SWITCHABLE_ALGORITHMS
        ):
            return
        old_algorithm = self.algorithm
        new_owns = (
            self._user_algorithms[target].owns_optimizer
            if target in self._user_algorithms
            else SWITCHABLE_ALGORITHMS[target](False).owns_optimizer
        )
        if old_algorithm.owns_optimizer and not new_owns and self.optimizer is None:
            # the user never supplied an optax optimizer (their family owns
            # the update rule); there is nothing to switch back to
            logger.info(
                "autotune: cannot switch %s -> %s without a trainer optimizer",
                current, target,
            )
            return
        if self._flat_resident:
            new_supports = (
                self._user_algorithms[target].supports_flat_resident
                if target in self._user_algorithms
                else SWITCHABLE_ALGORITHMS[target](False).supports_flat_resident
            )
            if not new_supports:
                # the live state is laid out as bucket flats; a family
                # without the flat contract cannot consume it
                logger.info(
                    "autotune: cannot switch %s -> %s — flat-resident "
                    "state needs a supports_flat_resident family",
                    current, target,
                )
                return
        new_replicated = (
            self._user_algorithms[target].replicated_params
            if target in self._user_algorithms
            else SWITCHABLE_ALGORITHMS[target](False).replicated_params
        )
        if old_algorithm.replicated_params != new_replicated:
            # replicated <-> stacked (allreduce <-> async): the state
            # migration below re-lays the whole TrainState out; refuse the
            # combinations it does not cover
            if old_algorithm.owns_optimizer or new_owns:
                logger.info(
                    "autotune: cannot switch %s -> %s — a replication-"
                    "boundary switch cannot also cross the optimizer-"
                    "ownership boundary", current, target,
                )
                return
            if self.expert_axis is not None or self._shard_axis is not None:
                logger.info(
                    "autotune: cannot switch %s -> %s — replication-"
                    "boundary switches need a pure data-parallel mesh",
                    current, target,
                )
                return
        logger.info("autotune: switching algorithm %s -> %s", current, target)
        if target in self._user_algorithms:
            # switching BACK to a family the user configured: reuse their
            # instance so settings beyond the search space (comm_dtype,
            # average, ...) survive the round trip
            self.algorithm = self._user_algorithms[target]
            self.algorithm.hierarchical = bool(recommended.is_hierarchical_reduce)
        else:
            self.algorithm = SWITCHABLE_ALGORITHMS[target](
                bool(recommended.is_hierarchical_reduce)
            )
        self._prepare_state_migration(old_algorithm, self.algorithm)
        self._prepare_replication_migration(old_algorithm, self.algorithm)
        if hasattr(old_algorithm, "reset_schedule"):
            # leaving a scheduled family: drop its in-flight round (it was
            # launched against the stacked layout being migrated away) and
            # forget the negotiated period
            old_algorithm.reset_schedule()
        if hasattr(self.algorithm, "reset_schedule"):
            # entering (or re-entering) a scheduled family mid-run: the
            # averaging period re-calibrates against the CURRENT cadence,
            # and no stale pending round survives from a previous stint
            self.algorithm.reset_schedule()
        if not recommended.buckets:
            # rebuild the plan under the new family's alignment (ByteGrad
            # pads buckets to the world size); skipped when the caller is
            # about to apply the recommendation's own buckets anyway
            self.rebucket([[t.declaration() for t in b.tensors]
                           for b in self._plan.buckets])

    def _prepare_state_migration(self, old, new) -> None:
        """Queue an opt-state layout migration for the next ``train_step``
        when a family switch crosses the trainer-optimizer / owned-optimizer
        boundary (allreduce|bytegrad <-> qadam).

        To QAdam: its momenta are param-shaped, so they are adopted from an
        adam-family optax state when one is found (``mu``/``nu``), else start
        at zeros; either way QAdam's own warmup contract is respected by
        re-anchoring ``warmup_steps`` at the switch step (q_adam.py:113-145 —
        the second moment must build in full precision before the compressed
        phase freezes it).  The displaced optax state is stashed and restored
        on the way back (slightly stale momentum beats a cold restart)."""
        if old.owns_optimizer == new.owns_optimizer:
            return
        from ..algorithms.q_adam import QAdamAlgorithm, QAdamOptState

        if new.owns_optimizer:
            assert isinstance(new, QAdamAlgorithm), type(new)
            # re-anchor warmup at the switch point (configured warmup counts
            # from here, not from training start).  The RELATIVE warmup is
            # remembered on first migration so repeated round trips through
            # qadam don't compound the absolute anchor.
            if not hasattr(new, "_base_warmup"):
                new._base_warmup = new.warmup_steps
            new._compressed = False
            new.warmup_steps = self._step_counter + new._base_warmup

            def to_owned(state):
                # stash a COPY: the adopted moments alias the live buffers,
                # which the next (donating) train step deletes
                self._stashed_opt_state = jax.tree.map(
                    jnp.copy, state.opt_state
                )
                moments = _find_adam_moments(state.opt_state)
                if moments is None:
                    zeros = jax.tree.map(jnp.zeros_like, state.params)
                    moments = (zeros, jax.tree.map(jnp.zeros_like, state.params))
                return state._replace(
                    opt_state=QAdamOptState(exp_avg=moments[0],
                                            exp_avg_sq=moments[1])
                )

            self._queue_state_migration(to_owned)
        else:

            def from_owned(state):
                stashed, self._stashed_opt_state = self._stashed_opt_state, None
                if stashed is not None:
                    return state._replace(opt_state=stashed)
                return state._replace(
                    opt_state=jax.jit(self._opt.init)(state.params)
                )

            self._queue_state_migration(from_owned)

    def _prepare_replication_migration(self, old, new) -> None:
        """Queue a replicated <-> stacked TrainState migration for the
        next ``train_step`` when a family switch crosses the replication
        boundary (gradient_allreduce/bytegrad <-> async model averaging).
        The switch itself is a re-jit — the new family's name/compile_key
        select a fresh compiled step through the step-cache key — and this
        migration converts the live buffers to the layout that step's
        shard_map specs expect.

        To a stacked (gossip) family: every rank's row adopts the
        replicated copy — the rows start bit-identical, exactly as
        ``init`` would build them.  Back to a replicated family: a
        synchronous catch-up average collapses the (possibly diverged)
        rows — the same consensus the async family's bounded-staleness cap
        forces, so the switch point has the semantics of one extra
        catch-up sync.  Integer leaves (step counters) advance in lockstep
        and reduce with MAX: an exact consensus, where integer AVG is not.
        The caller (:meth:`_maybe_switch_algorithm`) has already refused
        flat-resident state, optimizer-ownership crossings, and
        model-parallel meshes."""
        if old.replicated_params == new.replicated_params:
            return
        mesh, specs = self.mesh, P(self.dp_axes)
        ctx = self._ctx(self._plan)

        if not new.replicated_params:

            def migrate(state: TrainState) -> TrainState:
                logger.info(
                    "replication migration: replicated -> per-rank stacked "
                    "(%s)", type(new).__name__,
                )

                def stack_fn(p, o, a):
                    return _stack_tree(p), _stack_tree(o), _stack_tree(a)

                p, o, a = jax.jit(shard_map(
                    stack_fn, mesh=mesh, in_specs=(P(), P(), P()),
                    out_specs=(specs, specs, specs), check_vma=False,
                ))(state.params, state.opt_state, state.algo_state)
                return TrainState(state.step, p, o, a)
        else:

            def migrate(state: TrainState) -> TrainState:
                logger.info(
                    "replication migration: stacked -> replicated via "
                    "catch-up average (%s)", type(new).__name__,
                )

                def avg_fn(p, o, a):
                    def avg(x):
                        x = x[0]
                        if jnp.issubdtype(x.dtype, jnp.inexact):
                            return ctx.comm.allreduce(x, ReduceOp.AVG)
                        return ctx.comm.allreduce(x, ReduceOp.MAX)

                    return (jax.tree.map(avg, p), jax.tree.map(avg, o),
                            jax.tree.map(avg, a))

                p, o, a = jax.jit(shard_map(
                    avg_fn, mesh=mesh, in_specs=(specs, specs, specs),
                    out_specs=(P(), P(), P()), check_vma=False,
                ))(state.params, state.opt_state, state.algo_state)
                return TrainState(state.step, p, o, a)

        self._queue_state_migration(migrate)

    def _autotune_step(self, state):
        from ..communication import get_hyperparameters_service_client
        from ..define import BaguaHyperparameter

        rank = env.get_rank()
        now = time.time()
        # windowed throughput since the last report (reference
        # distributed.py:223), NOT a cumulative total — the score must
        # reflect only the current hyperparameter config
        speed = self._speed_tracker.get(now - self._last_report_time)
        self._last_report_time = now
        # perf hints: anomaly detections since the last check-in ride
        # along, so the scorer can tell "this config is slow" from
        # "rank 5 got slow for environmental reasons" — tuning against
        # the wrong one oscillates
        from ..obs import anomaly as _obs_anomaly

        hints = _obs_anomaly.drain_perf_hints()
        hints_delivered = False
        try:
            if self._autotune_client is None:
                self._autotune_client = get_hyperparameters_service_client()
            client = self._autotune_client
            rsp = client.report_metrics(
                model_name=self.model_name,
                rank=rank,
                train_iter=self._step_counter,
                hyperparameters=self._current_hyperparameters().model_dump(),
                speed=speed,
                perf_hints=hints or None,
                obs=self._autotune_obs_window(),
            )
            hints_delivered = True
            rsp = client.ask_hyperparameters(
                model_name=self.model_name, rank=rank, train_iter=self._step_counter
            )
            recommended = BaguaHyperparameter(**rsp["recommended_hyperparameters"])
            self._autotune_completed = bool(rsp.get("is_autotune_completed", False))
            self._apply_recommendation(recommended)
            self._autotune_failures = 0
        except Exception as e:  # autotune must never take down training
            if hints and not hints_delivered:
                # a transient sidecar hiccup must not discard the taint
                # signal — the next successful check-in carries it
                _obs_anomaly.requeue_perf_hints(hints)
            self._autotune_failures += 1
            logger.warning("autotune check-in failed (%d/3): %s",
                           self._autotune_failures, e)
            if self._autotune_failures >= 3:
                # a dead sidecar would otherwise stall every 100th step on
                # connection timeouts for the rest of the run
                logger.warning("autotune disabled after repeated failures")
                self.autotune = False

    def _autotune_obs_window(self) -> Optional[dict]:
        """The rank's windowed efficiency observations for the check-in
        (the v2 scoring input): goodput fraction of the window since the
        last report — delta of the CUMULATIVE ledger classes, so compile
        and migration badput the current config caused lands in its own
        score — plus MFU, the DCN share of the step, HBM headroom, and the
        rank-local anomaly flag from the obs summary.  ``None`` when the
        obs plane is off (``BAGUA_OBS=off``), goodput reporting is
        disabled (``BAGUA_AUTOTUNE_GOODPUT=off``), or no window has
        elapsed yet — the service then scores on summed speed as before.
        """
        if self._ledger is None or not env.get_autotune_goodput():
            return None
        try:
            rep = self._ledger.report()
        except Exception:  # the score input must never take down training
            return None
        if not rep:
            return None
        classes = dict(rep.get("classes") or {})
        snap = {"wall_s": float(rep.get("wall_s") or 0.0), "classes": classes}
        prev, self._autotune_ledger_prev = self._autotune_ledger_prev, snap
        if prev is None:
            # first check-in: the window opens at the ledger's first noted
            # second, so the initial config's own compile lands in its own
            # score — and EVERY window is goodput-scored from window one
            # (one speed-scaled sample would dominate best() forever)
            prev = {"wall_s": 0.0, "classes": {}}
        dwall = snap["wall_s"] - prev["wall_s"]
        if dwall <= 0:
            return None
        from ..obs.ledger import GOODPUT_CLASSES

        dgood = sum(
            classes.get(c, 0.0) - prev["classes"].get(c, 0.0)
            for c in GOODPUT_CLASSES
        )
        obs = {
            "goodput_fraction": max(0.0, min(1.0, dgood / dwall)),
            "window_wall_s": round(dwall, 3),
        }
        try:
            from ..obs import export as _obs_export

            summary = _obs_export.local_obs_summary() or {}
        except Exception:
            summary = {}
        if summary.get("mfu") is not None:
            obs["mfu"] = summary["mfu"]
        dcn = summary.get("device_comm_dcn_s_per_step")
        if dcn is not None:
            obs["dcn_s_per_step"] = dcn
            dt = summary.get("step_dt_p50")
            if dt:
                obs["dcn_share"] = max(0.0, min(1.0, float(dcn) / float(dt)))
        if summary.get("hbm_headroom_bytes") is not None:
            obs["hbm_headroom_bytes"] = summary["hbm_headroom_bytes"]
        if summary.get("straggler_suspect"):
            # the service discards (re-measures) anomaly-flagged windows
            obs["anomaly"] = True
        return obs

    def _current_hyperparameters(self):
        from ..define import BaguaHyperparameter

        buckets = [
            [t.declaration().model_dump() for t in b.tensors] for b in self._plan.buckets
        ] if self._plan else []
        from ..define import TensorDeclaration

        return BaguaHyperparameter(
            buckets=[[TensorDeclaration(**d) for d in b] for b in buckets],
            is_hierarchical_reduce=bool(self.algorithm.hierarchical),
            bucket_size=self.bucket_bytes,
            overlap=self.overlap,
            overlap_chunk_bytes=int(self.overlap_chunk_bytes),
            overlap_chunk_bytes_intra=int(self.overlap_chunk_bytes_intra),
            overlap_chunk_bytes_inter=int(self.overlap_chunk_bytes_inter),
            compress_intra=self.compress_intra,
            compress_inter=self.compress_inter,
            flat_resident="on" if self._flat_resident else "off",
        )

    def _batch_spec(self) -> P:
        if self.expert_axis is not None:
            return P(self.dp_axes + (self.expert_axis,))
        return P(self.dp_axes)

    def shard_batch(self, local_batch):
        """Stitch this process's local batch slice into global arrays laid
        out for the train step — the multi-host input path (each process
        feeds its own data shard, as each reference rank feeds its own
        DataLoader split).  Single-process: an explicit device_put with the
        step's input sharding (saves the jit-time relayout)."""
        from ..parallel.mesh import make_global_array

        spec = self._batch_spec()
        shards = 1
        for ax_entry in spec:
            for ax in (ax_entry if isinstance(ax_entry, tuple) else (ax_entry,)):
                if ax is not None:
                    shards *= self.mesh.shape[ax]
            break  # only the leading (batch) dim is sharded

        def check_and_make(x):
            # single-process only: with multiple processes each feeds its
            # own slice, so the per-process row count is a fraction of the
            # global requirement.  Only the shard count is enforced here —
            # accum_steps divisibility is a train-path concern (eval_step
            # consumes any shardable batch) and the step raises its own
            # clear error
            rows = (
                jnp.shape(x)[0]
                if jnp.ndim(x) and jax.process_count() == 1 else None
            )
            if rows is not None and rows % shards:
                raise ValueError(
                    f"batch leading dim {rows} must be divisible by "
                    f"{shards} (the number of batch shards)"
                )
            return make_global_array(self.mesh, spec, x)

        return jax.tree.map(check_and_make, local_batch)

    def _zero_staged(self) -> bool:
        """Whether hierarchical (intra-sharded) ZeRO is active — the
        host-side mirror of ``ZeroOptimizerAlgorithm._staged``; the opt
        state's stacked axis and the algorithm's shard comm must agree.

        The staged collectives span EXACTLY inter × intra, so any extra
        comm axis (sequence parallelism folds ``sp`` into comm_axes for
        partial-grad summation) must fall back to the flat path — staged
        rs/allreduce would silently skip the sp reduction."""
        return bool(
            getattr(self.algorithm, "sharded_opt_state", False)
            and getattr(self.algorithm, "hierarchical", False)
            and self._inter is not None
            and self._intra is not None
            and self._inter is not self._intra
            and self.world_size
            == self._inter.nranks() * self._intra.nranks()
        )

    def checkpoint_layout_metadata(self) -> dict:
        """Layout descriptor to store alongside checkpoints of this trainer's
        ``TrainState`` (pass as ``metadata=`` to
        :meth:`BaguaCheckpointManager.save` and ``expect_metadata=`` on
        restore).

        Flat-resident layouts store params (and optimizer state) as bucket
        flat buffers whose shapes depend on the bucket plan
        (``bucket_bytes`` split + alignment padding): a checkpoint saved
        under one plan can only restore DIRECTLY under the identical plan.
        This signature makes that restriction *detectable* — a raw
        ``BaguaCheckpointManager.restore`` at a different plan/world size
        fails with an actionable error instead of an opaque orbax shape
        mismatch (or, worse, a silent mis-restore) — while the
        ``flat_layout`` descriptor recorded alongside makes it *portable*:
        :meth:`restore_checkpoint` uses it to re-lay-out or leaf-convert
        the state across plans.  Plan-independent layouts record the
        signature too, so any future rebucketing divergence is caught."""
        import hashlib

        if self._plan is None:
            raise RuntimeError(
                "checkpoint_layout_metadata() needs the bucket plan — call "
                "trainer.init(params) first"
            )
        meta = {
            "layout": "flat" if self._flat_resident else "leaf",
            "plan_signature": hashlib.blake2b(
                repr(self._plan.signature()).encode(), digest_size=8
            ).hexdigest(),
            "world_size": int(self._comm.nranks()),
            "bucket_bytes": int(self.bucket_bytes),
            "plan_dependent": bool(self._flat_resident),
            # recorded for every layout: stacked (per-rank) states carry a
            # world-sized leading rank axis, which the cross-world restore
            # paths must know about even for plan-independent leaf layouts
            "stacked": not self.algorithm.replicated_params,
        }
        if self._flat_resident:
            # the full flat layout (bucket -> ordered (name, shape, dtype)
            # + alignment): everything restore_checkpoint needs to unpack
            # or relayout these buffers WITHOUT this trainer's plan
            meta["flat_layout"] = self._plan.layout_descriptor()
        if getattr(self.algorithm, "sharded_opt_state", False):
            # opt-state chunk layout depends on the SHARD count, which for
            # hierarchical ZeRO is the intra size, not the world size — a
            # restart at the same world but different intra must mismatch
            meta["opt_shards"] = int(
                self._intra.nranks() if self._zero_staged()
                else self._comm.nranks()
            )
        if self._ef_active():
            # the error-feedback residual in algo_state is plan- AND
            # world-keyed even under the otherwise plan-independent leaf
            # layout; this sidecar lets restore_checkpoint relayout it
            # across plans, or zero-reset it across world resizes, instead
            # of dying on an opaque orbax shape mismatch
            meta["ef"] = {
                "world": int(self._comm.nranks()),
                "flat_layout": self._plan.layout_descriptor(),
            }
        return meta

    # ---- layout-aware checkpointing --------------------------------------

    def _require_no_pending_migration(self, what: str) -> None:
        """Between a ``rebucket()`` and the next ``train_step``, the live
        state still holds the OLD plan's buffers while ``self._plan`` is
        the new one — a sidecar written in that window would describe the
        wrong layout and a later restore would silently corrupt weights."""
        if self._pending_state_migration is not None:
            raise RuntimeError(
                f"{what} with a state migration pending (a rebucket/"
                "family switch queued a layout change): run one "
                "train_step first so the resident state is migrated to "
                "the new bucket plan"
            )

    def save_checkpoint(self, manager, step: int, state: TrainState) -> bool:
        """Save ``state`` with this trainer's layout sidecar — the portable
        path: a checkpoint saved here restores through
        :meth:`restore_checkpoint` into ANY compatible trainer layout
        (flat or leaf, same plan or not)."""
        self._require_no_pending_migration("save_checkpoint")
        return manager.save(
            int(step), state, metadata=self.checkpoint_layout_metadata()
        )

    def restore_checkpoint(self, manager, state_like: TrainState,
                           step: Optional[int] = None):
        """Restore ``step`` (default: latest) into THIS trainer's state
        layout, converting via the saved layout sidecar when the on-disk
        layout differs:

        - same layout and (for flat) same plan/world: direct restore, the
          sidecar validated as in :meth:`BaguaCheckpointManager.restore`;
        - flat checkpoint -> flat trainer under another plan or world
          size: flat->flat relayout of params and optimizer state
          (:func:`bagua_tpu.bucket.relayout_flats` — no leaf round trip);
        - flat checkpoint -> leaf trainer (``flat_resident="off"``):
          leaves rebuilt from the sidecar's recorded bucket layout — the
          canonical-leaf fallback that keeps flat checkpoints portable;
        - leaf checkpoint -> flat trainer: leaves flattened into the
          current plan.

        Cross-layout conversion relies on optimizer state mirroring the
        param pytree (elementwise optax transforms, QAdam momenta).
        Sharded-opt-state ZeRO's per-chunk states stay plan-locked — a
        cross-plan ZeRO restore raises the manager's actionable layout
        error.  Per-rank (gossip) LEAF state additionally restores across
        an elastic WORLD RESIZE when its rank rows are bit-identical (the
        ``AsyncModelAverageAlgorithm.sync_for_checkpoint`` protocol): row 0
        is verified against every other row and re-tiled onto the live
        world; rows that diverged raise actionably.  Other stacked
        conversions stay identical-plan only.  After a successful restore
        the algorithm's :meth:`~bagua_tpu.algorithms.base.Algorithm.
        on_restore` hook runs — async model averaging resets its
        negotiated schedule there, so the resumed run opens a fresh
        calibration window instead of consuming a stale in-flight round or
        launch anchor.  Returns ``(step, state)``."""
        if self._plan is None:
            raise RuntimeError(
                "restore_checkpoint() needs the bucket plan — call "
                "trainer.init(params) first"
            )
        self._require_no_pending_migration("restore_checkpoint")
        if step is not None:
            result = self._restore_checkpoint_at(manager, state_like,
                                                 int(step))
        else:
            # integrity fallback: with no explicit step, ride the manager's
            # newest-first walk — a corrupted latest checkpoint degrades to
            # the previous verified one instead of crashing the resume
            result = manager._restore_newest_verified(
                lambda s: self._restore_checkpoint_at(manager, state_like, s)
            )
        self.algorithm.on_restore(self)
        return result

    def _restore_checkpoint_at(self, manager, state_like: TrainState,
                               step: int):
        # error-feedback residual adapter: the residual's algo_state slot
        # is plan- AND world-keyed, so the restore targets the SAVED ef
        # structure (from the "ef" sidecar) and the fixup converts it into
        # the live one — relayout across plans, zero-reset across worlds,
        # zero-init when the checkpoint predates the codec flip, drop (with
        # a warning) when the live trainer no longer carries a residual
        saved_meta = manager.read_layout(step)
        adapted, ef_fixup = self._ef_restore_adapter(state_like, saved_meta)
        step, restored = self._restore_checkpoint_body(manager, adapted,
                                                       step)
        return step, ef_fixup(restored)

    def _ef_restore_adapter(self, state_like: TrainState,
                            saved: Optional[dict]):
        """``(adapted_state_like, fixup)`` for the error-feedback residual:
        ``adapted_state_like`` mirrors the CHECKPOINT's ef presence/shape
        (so orbax restores structurally), ``fixup`` converts the restored
        state back to the LIVE layout.  Identity when neither side carries
        a residual — and when the checkpoint has no sidecar at all, where
        nothing can be known and the direct restore stays the loud
        arbiter."""
        identity = (state_like, lambda s: s)
        a = state_like.algo_state
        has_live = isinstance(a, dict) and "ef" in a
        saved_ef = (saved or {}).get("ef")
        if saved is None or (not has_live and saved_ef is None):
            return identity
        if not has_live and not (isinstance(a, dict) or a is None):
            # non-dict algo state (stacked families) cannot host a saved
            # residual slot; the direct restore will surface the mismatch
            return identity

        ef_plan = None
        saved_container = None
        if saved_ef is not None:
            ef_plan = BucketPlan.from_layout_descriptor(
                saved_ef["flat_layout"]
            )
            saved_container = {"ef": {"buckets": tuple(
                jax.ShapeDtypeStruct((int(saved_ef["world"]),
                                      b.padded_numel), np.dtype(np.float32))
                for b in ef_plan.buckets
            )}}

        if has_live:
            rest = {k: v for k, v in a.items() if k != "ef"}
            adapted_algo = (
                {**rest, **saved_container} if saved_container is not None
                else (rest or None)
            )
        else:
            adapted_algo = (
                {**a, **saved_container} if isinstance(a, dict)
                else saved_container
            )
        live_world = int(self._comm.nranks())
        live_plan = self._plan

        def fixup(state: TrainState) -> TrainState:
            a2 = state.algo_state
            if not has_live:
                # live trainer carries no residual: drop the restored one
                if isinstance(a2, dict) and "ef" in a2:
                    logger.warning(
                        "restore_checkpoint: discarding the checkpoint's "
                        "error-feedback residual — no stateful codec is "
                        "active in this trainer (compress knobs / "
                        "BAGUA_EF_RESIDUAL).  Re-enable the codec policy "
                        "before restoring to keep the accumulated error."
                    )
                    rest2 = {k: v for k, v in a2.items() if k != "ef"}
                    return state._replace(algo_state=rest2 or None)
                return state
            zeros = {"buckets": tuple(
                jnp.zeros(tuple(b.shape), jnp.float32)
                for b in a["ef"]["buckets"]
            )}
            if saved_container is None:
                logger.warning(
                    "restore_checkpoint: checkpoint carries no "
                    "error-feedback residual (saved before the stateful "
                    "codec was enabled): starting from ZERO residuals — "
                    "convergence-neutral, the error feedback re-warms "
                    "within a few steps"
                )
                merged = dict(a2) if isinstance(a2, dict) else {}
                merged["ef"] = zeros
                return state._replace(algo_state=merged)
            restored_ef = a2["ef"]
            if int(saved_ef["world"]) != live_world:
                logger.warning(
                    "restore_checkpoint: error-feedback residual was saved "
                    "at world_size=%d, trainer runs %d (elastic resize): "
                    "zero-resetting the residual — convergence-neutral, "
                    "the error feedback re-warms within a few steps",
                    int(saved_ef["world"]), live_world,
                )
                return state._replace(
                    algo_state={**a2, "ef": zeros}
                )
            if ef_plan.signature() != live_plan.signature():
                logger.info(
                    "restore_checkpoint: relaying out the error-feedback "
                    "residual %d -> %d buckets",
                    len(ef_plan.buckets), len(live_plan.buckets),
                )
                migrated = self.algorithm.relayout_algo_state(
                    ef_plan, live_plan, {"ef": restored_ef}
                )
                return state._replace(
                    algo_state={**a2, "ef": migrated["ef"]}
                )
            return state

        return state_like._replace(algo_state=adapted_algo), fixup

    def _restore_checkpoint_body(self, manager, state_like: TrainState,
                                 step: int):
        expected = self.checkpoint_layout_metadata()
        saved = manager.read_layout(step)
        # the manager owns legacy-alias normalization ("zero_flat"->"flat")
        saved_layout = (manager._normalize_layout(saved) or {}).get("layout")

        def direct():
            return manager.restore(
                state_like, step=step, expect_metadata=expected,
                mesh=self.mesh,
            )

        same_layout = saved_layout == expected["layout"]
        # the signature pins the concrete flat shapes — a world-size change
        # under an identical plan (alignment-1 buckets) restores directly
        same_plan = (
            saved is not None
            and saved.get("plan_signature") == expected["plan_signature"]
        )
        saved_world = (saved or {}).get("world_size")
        if (
            not self.algorithm.replicated_params
            and not self._flat_resident
            and saved_layout == "leaf"
            and saved_world
            and int(saved_world) != self._comm.nranks()
        ):
            # stacked (per-rank) leaf state across an elastic world resize:
            # the leading rank axis is world-sized, so the direct restore
            # would hit an opaque orbax shape mismatch — take the
            # row-identity re-tiling path instead
            return self._restore_stacked_resized(
                manager, state_like, step, saved, int(saved_world)
            )
        if saved is None or (same_layout and (saved_layout == "leaf"
                                              or same_plan)):
            return direct()
        if saved_layout not in ("flat", "leaf"):
            return direct()
        if self.algorithm.sharded_opt_state:
            # per-chunk optimizer states are keyed on bucket boundaries AND
            # rank count; no host-side conversion exists — surface the
            # manager's actionable error instead of silently mis-restoring
            return direct()
        stacked = not self.algorithm.replicated_params
        if stacked or saved.get("stacked"):
            # gossip state carries a leading rank axis; cross-plan/layout
            # conversion of stacked rows is not supported
            return direct()
        if saved_layout == "flat" and "flat_layout" not in saved:
            return direct()  # legacy sidecar without the bucket descriptor
        if (
            saved_layout != expected["layout"]
            and getattr(self.optimizer, "fused_inner", None) is not None
        ):
            # a fuse_optimizer wrapper's LEAF-layout state is per-dtype
            # buffers inside _FusedState — neither param-shaped nor a flat
            # container — so cross-layout conversion cannot locate it;
            # raise here instead of an opaque orbax structure mismatch
            want = "on" if saved_layout == "flat" else "off"
            raise ValueError(
                "restore_checkpoint cannot convert across layouts for a "
                "fuse_optimizer-wrapped trainer: the wrapper's leaf-layout "
                "state is per-dtype fused buffers with no leaf/flat "
                "mirror.  Restore into a trainer with the checkpoint's own "
                f"layout (flat_resident='{want}'), or re-save after "
                "unwrapping."
            )
        param_def = jax.tree_util.tree_structure(self._param_template)
        if param_def == jax.tree_util.tree_structure(0):
            # a bare-leaf param "tree" cannot be located structurally
            return direct()

        old_plan = (
            BucketPlan.from_layout_descriptor(saved["flat_layout"])
            if saved_layout == "flat" else None
        )
        is_zp = self._is_flat_container

        def is_param_tree(x):
            try:
                return jax.tree_util.tree_structure(x) == param_def
            except Exception:  # unhashable/exotic leaves
                return False

        def flat_sds(plan):
            return {
                "flats": tuple(
                    jax.ShapeDtypeStruct((b.padded_numel,), np.dtype(b.dtype))
                    for b in plan.buckets
                ),
                "local": {},
            }

        # 1. rebuild the SAVED state's structure from the live template:
        # optimizer state mirrors the params, so substituting at every
        # flat-container (current=flat) or param-shaped (current=leaf)
        # position reproduces the on-disk pytree
        if self._flat_resident:
            saved_like = jax.tree.map(
                lambda x: (
                    (self._param_template if saved_layout == "leaf"
                     else flat_sds(old_plan)) if is_zp(x) else x
                ),
                state_like, is_leaf=is_zp,
            )
        else:
            saved_like = jax.tree.map(
                lambda x: flat_sds(old_plan) if is_param_tree(x) else x,
                state_like, is_leaf=is_param_tree,
            )
        # expect the SAVED layout here: this restore deliberately targets
        # the on-disk structure (the conversion below re-lays it out)
        step, restored = manager.restore(saved_like, step=step,
                                         expect_metadata=saved,
                                         mesh=self.mesh)

        # 2. convert the restored state into the live layout
        from ..tensor import tree_from_named

        def from_flat(x):
            if is_zp(x):
                named = old_plan.unflatten_to_named(list(x["flats"]))
                named.update(x["local"])
                return tree_from_named(self._param_template, named)
            return x

        def to_flat(x):
            if is_param_tree(x):
                return {"flats": tuple(self._plan.flatten_tree(x)),
                        "local": {}}
            return x

        if self._flat_resident and saved_layout == "leaf":
            converted = jax.tree.map(to_flat, restored,
                                     is_leaf=is_param_tree)
        elif self._flat_resident:
            # replicated families only reach here (gossip took direct()),
            # so every plan-keyed buffer is behind a flat-container marker
            converted = self._relayout_tree(restored, old_plan, self._plan)
        else:
            converted = jax.tree.map(from_flat, restored, is_leaf=is_zp)
        logger.info(
            "restore_checkpoint: converted step %s from %s layout to %s",
            step, saved_layout, expected["layout"],
        )
        return step, converted

    def _restore_stacked_resized(self, manager, state_like: TrainState,
                                 step: int, saved: dict, saved_world: int):
        """Elastic world-resize restore for stacked (per-rank) LEAF states
        — the async model-average / gossip families, whose every
        params/opt/algo leaf carries a leading world-sized rank axis.

        Protocol: the checkpoint must have been saved with rank-identical
        rows (``AsyncModelAverageAlgorithm.sync_for_checkpoint`` — a
        blocking synchronous model average — right before the save).  The
        restore rebuilds the SAVED world's stacked shapes, verifies every
        row of every leaf is bit-identical to row 0, and re-tiles row 0
        onto the live world size.  Divergent rows raise actionably: they
        mean per-rank replicas that genuinely cannot be resized, and
        silently picking one row would discard other ranks' progress."""
        from jax.sharding import NamedSharding

        live_n = self._comm.nranks()
        stacked_trees = (state_like.params, state_like.opt_state,
                         state_like.algo_state)
        bad = [
            tuple(jnp.shape(x)) for x in jax.tree.leaves(stacked_trees)
            if not jnp.ndim(x) or jnp.shape(x)[0] != live_n
        ]
        if bad:
            raise ValueError(
                f"cross-world stacked restore expects every params/opt/algo "
                f"leaf to carry a leading rank axis of {live_n}, found "
                f"shapes {bad[:3]} — restore at the saved world size "
                f"({saved_world}) instead"
            )

        def to_saved(x):
            return jax.ShapeDtypeStruct(
                (saved_world,) + tuple(jnp.shape(x)[1:]), jnp.result_type(x)
            )

        saved_like = state_like._replace(
            params=jax.tree.map(to_saved, state_like.params),
            opt_state=jax.tree.map(to_saved, state_like.opt_state),
            algo_state=jax.tree.map(to_saved, state_like.algo_state),
        )
        # expect the SAVED metadata: this restore deliberately targets the
        # on-disk world; the re-tiling below moves it onto the live one
        step, restored = manager.restore(saved_like, step=step,
                                         expect_metadata=saved,
                                         mesh=self.mesh)

        def retile(sx, like):
            a = np.asarray(sx)
            row0 = a[0]
            b0 = row0.tobytes()
            for r in range(1, a.shape[0]):
                if b0 != a[r].tobytes():
                    raise ValueError(
                        f"stacked checkpoint step {step} (world "
                        f"{saved_world}) has DIVERGENT per-rank rows — it "
                        "cannot restore onto a resized world "
                        f"({live_n} ranks).  Save resize-portable async "
                        "checkpoints via algorithm.sync_for_checkpoint("
                        "trainer, state) (a blocking synchronous model "
                        "average) right before save_checkpoint, or restore "
                        "at the original world size."
                    )
            out = jnp.asarray(
                np.broadcast_to(row0, (live_n,) + row0.shape).copy()
            )
            sh = getattr(like, "sharding", None)
            if isinstance(sh, NamedSharding):
                out = jax.device_put(out, sh)
            return out

        converted = state_like._replace(
            step=restored.step,
            params=jax.tree.map(retile, restored.params, state_like.params),
            opt_state=jax.tree.map(retile, restored.opt_state,
                                   state_like.opt_state),
            algo_state=jax.tree.map(retile, restored.algo_state,
                                    state_like.algo_state),
        )
        from ..telemetry import counters

        counters.incr("ckpt/stacked_resize_restores")
        logger.info(
            "restore_checkpoint: re-tiled stacked step %s from world %d "
            "onto world %d (rank rows verified bit-identical)",
            step, saved_world, live_n,
        )
        return step, converted

    def unstack_params(self, state: TrainState):
        """Return params in user shape (for eval/checkpoint): rank 0's copy
        for replicated/gossip state; global ``[n_experts, ...]`` expert leaves
        re-assembled from their ep shards."""
        if self._flat_resident:
            # flat-resident layouts: materialize the leaf pytree lazily
            # (this is the ONLY place the unflatten happens off the hot
            # path — eval/checkpoint/user inspection).  The jitted
            # unflatten is cached per bucket plan so periodic
            # checkpoint/eval calls don't retrace it every time.
            zp = state.params
            if not self.algorithm.replicated_params:
                # gossip state is stacked per rank; rank 0's row is the
                # user-facing copy, as in the leaf layout below
                zp = jax.tree.map(lambda x: x[0], zp)
            cache_key = self._plan.signature()
            cached = getattr(self, "_unflatten_cache", None)
            if cached is None or cached[0] != cache_key:
                cached = (cache_key, jax.jit(self._flat_leaf_view))
                self._unflatten_cache = cached
            return cached[1](zp)
        if self.expert_axis is None or self.algorithm.sharded_opt_state:
            # ZeRO keeps expert leaves as global [n_experts, ...] arrays
            # (sharded in place), so no re-assembly is needed
            if self.algorithm.replicated_params:
                return state.params
            return jax.tree.map(lambda x: x[0], state.params)

        def fix(path, leaf):
            if self._is_expert_name(_name_of_path(path)):
                return leaf.reshape((-1,) + leaf.shape[2:])
            return leaf[0]

        return jax.tree_util.tree_map_with_path(fix, state.params)

    def record_speed(self, n_samples: float):
        """Manual override of the automatic per-step speed tracking: count
        ``n_samples`` since the previous call (reference's speed metrics,
        distributed.py:340-358).  Use when the batch pytree's leading dim is
        not the sample count (e.g. token-weighted scoring)."""
        now = time.time()
        if not self._manual_speed:
            # first manual call: discard auto-recorded samples (possibly in
            # different units), but DO record this one — against the
            # interval the auto path measured for the same step (its
            # pre-advance timestamp), not the microseconds since it ran —
            # so a check-in landing before the second call never scores 0
            self._manual_speed = True
            self._speed_tracker = StatisticalAverage()
            dt = now - getattr(self, "_prev_speed_time", self._last_speed_time)
            self._last_speed_time = now
            if dt > 0:
                self._speed_tracker.record(n_samples / dt)
            return
        dt = now - self._last_speed_time
        self._last_speed_time = now
        if dt > 0:
            self._speed_tracker.record(n_samples / dt)
