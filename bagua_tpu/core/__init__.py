from .backend import BaguaTrainer, TrainState  # noqa: F401
