"""Pallas TPU kernels for the MinMaxUInt8 chunked codec.

The perf-critical piece for ByteGrad/QAdam parity (SURVEY.md §7.5): the
reference fuses this on GPU as CUB DeviceReduce min/max + a quantize kernel
(/root/reference/rust/bagua-core/bagua-core-internal/kernels/bagua_kernels.cu:269-572)
— two passes over HBM.  Plain-XLA ``compress_chunked`` also lowers to two
passes (a reduce then an elementwise map).  These kernels do it in ONE: each
grid step pulls its chunk into VMEM once, computes the masked min/max on the
VPU, quantizes in-register, and writes only the u8 payload + two scalars back
to HBM — halving the codec's HBM traffic, which is what bounds it (the math
is trivially elementwise).

Layout matches :mod:`.minmax_uint8` (same quantization formula, same
``(mn, mx, payload)`` triple), so the two implementations are drop-in
interchangeable and golden-tested against each other.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

EPS = 1e-7
LEVELS = 255.0

_LANE = 128
_U8_SUBLANE = 32  # min u8 tile is (32, 128)


def _padded_rows(chunk: int) -> int:
    rows = -(-chunk // _LANE)
    return -(-rows // _U8_SUBLANE) * _U8_SUBLANE


# Scalars can't be standalone (1,1) TPU outputs (min tile is (8,128)), so
# min/max travel in one (8,128) f32 "stats" block per chunk: row 0 = mn,
# row 1 = mx (lane 0).  16 KiB per chunk of stats — noise next to the payload.
_STATS_ROWS = 8


def _compress_kernel(x_ref, stats_ref, payload_ref, *, chunk: int):
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    mn = jnp.min(jnp.where(mask, x, jnp.inf))
    mx = jnp.max(jnp.where(mask, x, -jnp.inf))
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    level = jnp.clip(jnp.round(x * scale), lower, upper)
    row = jax.lax.broadcasted_iota(jnp.int32, (_STATS_ROWS, _LANE), 0)
    stats_ref[:] = jnp.where(row == 0, mn, mx)
    # Mosaic has no direct f32<->u8 cast; hop through i32
    payload_ref[:] = (level - lower).astype(jnp.int32).astype(jnp.uint8)


def _decompress_kernel(stats_ref, payload_ref, out_ref):
    mn = stats_ref[0, 0]
    mx = stats_ref[1, 0]
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    vals = payload_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = (vals + lower) / scale


@functools.partial(jax.jit, static_argnums=(1, 2))
def compress_chunked_pallas(
    x: jax.Array, n_chunks: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused per-chunk min/max + quantize; same contract as
    :func:`bagua_tpu.compression.compress_chunked`."""
    assert x.size % n_chunks == 0, (x.size, n_chunks)
    chunk = x.size // n_chunks
    rows = _padded_rows(chunk)
    padded = rows * _LANE
    xp = jnp.pad(
        x.reshape(n_chunks, chunk).astype(jnp.float32),
        ((0, 0), (0, padded - chunk)),
    ).reshape(n_chunks * rows, _LANE)

    stats, payload = pl.pallas_call(
        functools.partial(_compress_kernel, chunk=chunk),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_chunks * _STATS_ROWS, _LANE), jnp.float32),
            jax.ShapeDtypeStruct((n_chunks * rows, _LANE), jnp.uint8),
        ],
        interpret=interpret,
    )(xp)
    payload = payload.reshape(n_chunks, padded)[:, :chunk]
    stats = stats.reshape(n_chunks, _STATS_ROWS, _LANE)
    return stats[:, 0, 0], stats[:, 1, 0], payload


@functools.partial(jax.jit, static_argnums=(3,))
def decompress_chunked_pallas(
    mn: jax.Array, mx: jax.Array, payload: jax.Array, interpret: bool = False
) -> jax.Array:
    """Inverse of :func:`compress_chunked_pallas`; returns flat f32."""
    n_chunks, chunk = payload.shape
    rows = _padded_rows(chunk)
    padded = rows * _LANE
    pp = jnp.pad(payload, ((0, 0), (0, padded - chunk))).reshape(
        n_chunks * rows, _LANE
    )
    # lay out as [n_chunks*_STATS_ROWS, _LANE] with [0,0]=mn, [1,0]=mx
    block = jnp.zeros((n_chunks, _STATS_ROWS, _LANE), jnp.float32)
    block = block.at[:, 0, 0].set(mn.astype(jnp.float32))
    block = block.at[:, 1, 0].set(mx.astype(jnp.float32))
    out = pl.pallas_call(
        _decompress_kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_chunks * rows, _LANE), jnp.float32),
        interpret=interpret,
    )(block.reshape(n_chunks * _STATS_ROWS, _LANE), pp)
    return out.reshape(n_chunks, padded)[:, :chunk].reshape(-1)
