"""Pallas TPU kernels for the MinMaxUInt8 chunked codec.

The perf-critical piece for ByteGrad/QAdam parity (SURVEY.md §7.5): the
reference fuses this on GPU as CUB DeviceReduce min/max + a quantize kernel
(/root/reference/rust/bagua-core/bagua-core-internal/kernels/bagua_kernels.cu:269-572)
— two passes over HBM.  These kernels do it in ONE grid pass: each grid step
pulls its chunk into VMEM once, computes the masked min/max on the VPU,
quantizes in-register, and writes only the u8 payload + two scalars back to
HBM.

**Measured reality (kernel-level xplane profile, v5e, BENCH_COMM.json r5):**
the picture is size-dependent, and at the two ends it is opposite:

- **small chunks (128 KiB)**: grid overhead dominates — Pallas compress
  LOSES to the XLA lowering (171 vs 219 GB/s), because XLA fuses the naive
  two-pass ``compress_chunked`` to near-single-pass HBM traffic anyway
  (measured ~1.29x input vs the 1.25x ideal).
- **ByteGrad's default operating point (~1 MiB chunks)**: modest Pallas win
  (+8%, 339 vs 312 GB/s).
- **large chunks (8 MiB, the tiled two-pass path)**: XLA's chunk-reduction
  schedule collapses (35 GB/s, 1.9 ms/call) while the tiled Pallas kernels
  hold 247 GB/s — a **7x** kernel-time win; this is where the Pallas codec
  pays for itself.

The Pallas *decompress* lost to the XLA elementwise lowering at every
measured size (221 vs 383 GB/s at 8 MB), so
:func:`bagua_tpu.compression.minmax_uint8._codec` routes decompress to jnp
and compress to Pallas only at >=1 MiB chunks.  Both paths pay one u8
payload re-layout (flat <-> (rows,128) tiling) that bounds further gains.
(Mosaic custom-calls report no ``memory_access_breakdown``, so Pallas HBM
ratios cannot be read off the profile; the comparison above uses kernel
time, which IS instrumented.)

Chunks bigger than VMEM can't do it in one: past ``_MAX_FUSED_ROWS`` the
codec switches to a TILED two-pass — a min/max accumulation kernel (output
block revisited across the tile grid axis, legal because the tile axis
iterates fastest) followed by an elementwise quantize kernel.  Same HBM
traffic as the XLA lowering at those sizes, but no VMEM ceiling: the fused
path keeps its advantage where it matters (ByteGrad's default ~10 MB
buckets yield ~1 MB per-rank chunks).

Layout matches :mod:`.minmax_uint8` (same quantization formula, same
``(mn, mx, payload)`` triple), so the two implementations are drop-in
interchangeable and golden-tested against each other.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

EPS = 1e-7
LEVELS = 255.0

_LANE = 128
_U8_SUBLANE = 32  # min u8 tile is (32, 128)


def _padded_rows(chunk: int) -> int:
    rows = -(-chunk // _LANE)
    return -(-rows // _U8_SUBLANE) * _U8_SUBLANE


# Scalars can't be standalone (1,1) TPU outputs (min tile is (8,128)), so
# min/max travel in one (8,128) f32 "stats" block per chunk: row 0 = mn,
# row 1 = mx (lane 0).  16 KiB per chunk of stats — noise next to the payload.
_STATS_ROWS = 8

# fused single-pass ceiling: a (rows, 128) f32 block costs rows*512 bytes in
# VMEM and Mosaic stacks ~5x that (double buffering + the i32 quantize
# intermediate); 2048 rows (1 MiB f32) keeps the kernel comfortably inside
# the 16 MiB scoped-vmem budget.  Larger chunks take the tiled two-pass.
_MAX_FUSED_ROWS = 2048
_TILE_ROWS = 2048


def _compress_kernel(x_ref, stats_ref, payload_ref, *, chunk: int):
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    mn = jnp.min(jnp.where(mask, x, jnp.inf))
    mx = jnp.max(jnp.where(mask, x, -jnp.inf))
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    level = jnp.clip(jnp.round(x * scale), lower, upper)
    row = jax.lax.broadcasted_iota(jnp.int32, (_STATS_ROWS, _LANE), 0)
    stats_ref[:] = jnp.where(row == 0, mn, mx)
    # Mosaic has no direct f32<->u8 cast; hop through i32
    payload_ref[:] = (level - lower).astype(jnp.int32).astype(jnp.uint8)


def _minmax_tile_kernel(x_ref, stats_ref, *, chunk: int):
    """Pass 1 of the tiled codec: accumulate a chunk's min/max over its
    tiles.  The stats block maps to the same (chunk-indexed) output block
    for every tile step j, so it accumulates in VMEM across the fast grid
    axis and spills once per chunk."""
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    base = j * rows * lanes
    flat_idx = (
        base
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    mn_t = jnp.min(jnp.where(mask, x, jnp.inf))
    mx_t = jnp.max(jnp.where(mask, x, -jnp.inf))
    row = jax.lax.broadcasted_iota(jnp.int32, (_STATS_ROWS, _LANE), 0)
    tile_stats = jnp.where(row == 0, mn_t, mx_t)

    @pl.when(j == 0)
    def _init():
        stats_ref[:] = tile_stats

    @pl.when(j > 0)
    def _accum():
        cur = stats_ref[:]
        stats_ref[:] = jnp.where(
            row == 0, jnp.minimum(cur, mn_t), jnp.maximum(cur, mx_t)
        )


def _quantize_tile_kernel(stats_ref, x_ref, payload_ref):
    """Pass 2 of the tiled codec: elementwise quantize against the chunk's
    final min/max (padding quantizes garbage that the caller slices off)."""
    mn = stats_ref[0, 0]
    mx = stats_ref[1, 0]
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    x = x_ref[:].astype(jnp.float32)
    level = jnp.clip(jnp.round(x * scale), lower, upper)
    payload_ref[:] = (level - lower).astype(jnp.int32).astype(jnp.uint8)


def _decompress_kernel(stats_ref, payload_ref, out_ref):
    mn = stats_ref[0, 0]
    mx = stats_ref[1, 0]
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    vals = payload_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = (vals + lower) / scale


@functools.partial(jax.jit, static_argnums=(1, 2))
def compress_chunked_pallas(
    x: jax.Array, n_chunks: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused per-chunk min/max + quantize; same contract as
    :func:`bagua_tpu.compression.compress_chunked`."""
    assert x.size % n_chunks == 0, (x.size, n_chunks)
    chunk = x.size // n_chunks
    rows = _padded_rows(chunk)
    if rows > _MAX_FUSED_ROWS:
        # round up to a whole number of tiles so the 2-D grid divides evenly
        rows = -(-rows // _TILE_ROWS) * _TILE_ROWS
    padded = rows * _LANE
    xp = jnp.pad(
        x.reshape(n_chunks, chunk).astype(jnp.float32),
        ((0, 0), (0, padded - chunk)),
    ).reshape(n_chunks * rows, _LANE)

    if rows <= _MAX_FUSED_ROWS:
        stats, payload = pl.pallas_call(
            functools.partial(_compress_kernel, chunk=chunk),
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_chunks * _STATS_ROWS, _LANE),
                                     jnp.float32),
                jax.ShapeDtypeStruct((n_chunks * rows, _LANE), jnp.uint8),
            ],
            interpret=interpret,
        )(xp)
    else:
        n_tiles = rows // _TILE_ROWS
        stats = pl.pallas_call(
            functools.partial(_minmax_tile_kernel, chunk=chunk),
            grid=(n_chunks, n_tiles),
            in_specs=[
                pl.BlockSpec((_TILE_ROWS, _LANE),
                             lambda i, j: (i * n_tiles + j, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_STATS_ROWS, _LANE), lambda i, j: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(
                (n_chunks * _STATS_ROWS, _LANE), jnp.float32
            ),
            interpret=interpret,
        )(xp)
        payload = pl.pallas_call(
            _quantize_tile_kernel,
            grid=(n_chunks, n_tiles),
            in_specs=[
                pl.BlockSpec((_STATS_ROWS, _LANE), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((_TILE_ROWS, _LANE),
                             lambda i, j: (i * n_tiles + j, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_TILE_ROWS, _LANE),
                                   lambda i, j: (i * n_tiles + j, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n_chunks * rows, _LANE),
                                           jnp.uint8),
            interpret=interpret,
        )(stats, xp)
    payload = payload.reshape(n_chunks, padded)[:, :chunk]
    stats = stats.reshape(n_chunks, _STATS_ROWS, _LANE)
    return stats[:, 0, 0], stats[:, 1, 0], payload


def _absmax_kernel(x_ref, stats_ref, *, chunk: int):
    """Fused per-chunk absmax (the int8/fp8 codecs' only reduction).  Same
    stats-block layout as the min/max kernels: row 0 carries the value."""
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    am = jnp.max(jnp.where(mask, jnp.abs(x), -jnp.inf))
    stats_ref[:] = jnp.full((_STATS_ROWS, _LANE), am, jnp.float32)


def _absmax_tile_kernel(x_ref, stats_ref, *, chunk: int):
    """Tiled absmax accumulation past the fused VMEM ceiling (the
    ``_minmax_tile_kernel`` pattern: the stats block maps to the same
    chunk-indexed output for every tile step, so it accumulates in VMEM)."""
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    base = j * rows * lanes
    flat_idx = (
        base
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    am = jnp.max(jnp.where(mask, jnp.abs(x), -jnp.inf))
    tile_stats = jnp.full((_STATS_ROWS, _LANE), am, jnp.float32)

    @pl.when(j == 0)
    def _init():
        stats_ref[:] = tile_stats

    @pl.when(j > 0)
    def _accum():
        stats_ref[:] = jnp.maximum(stats_ref[:], tile_stats)


@functools.partial(jax.jit, static_argnums=(1, 2))
def absmax_chunked_pallas(
    x: jax.Array, n_chunks: int, interpret: bool = False
) -> jax.Array:
    """Per-chunk absmax of flat ``x`` (``size % n_chunks == 0``) — the
    reduction half of the int8/fp8 ring codecs.  The elementwise quantize/
    cast that follows stays on the XLA lowering (measured faster than
    Pallas for pure maps at every size, see the module docstring)."""
    assert x.size % n_chunks == 0, (x.size, n_chunks)
    chunk = x.size // n_chunks
    rows = _padded_rows(chunk)
    tiled = rows > _MAX_FUSED_ROWS
    if tiled:
        rows = -(-rows // _TILE_ROWS) * _TILE_ROWS
    padded = rows * _LANE
    xp = jnp.pad(
        x.reshape(n_chunks, chunk).astype(jnp.float32),
        ((0, 0), (0, padded - chunk)),
    ).reshape(n_chunks * rows, _LANE)
    if not tiled:
        stats = pl.pallas_call(
            functools.partial(_absmax_kernel, chunk=chunk),
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(
                (n_chunks * _STATS_ROWS, _LANE), jnp.float32
            ),
            interpret=interpret,
        )(xp)
    else:
        n_tiles = rows // _TILE_ROWS
        stats = pl.pallas_call(
            functools.partial(_absmax_tile_kernel, chunk=chunk),
            grid=(n_chunks, n_tiles),
            in_specs=[
                pl.BlockSpec((_TILE_ROWS, _LANE),
                             lambda i, j: (i * n_tiles + j, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((_STATS_ROWS, _LANE), lambda i, j: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(
                (n_chunks * _STATS_ROWS, _LANE), jnp.float32
            ),
            interpret=interpret,
        )(xp)
    return stats.reshape(n_chunks, _STATS_ROWS, _LANE)[:, 0, 0]


# ---- 1-bit sign codec (ISSUE 17) ----------------------------------------
#
# Wire layout (shared with the jnp fallback in codecs.OneBitEfCodec — the
# two paths are byte-identical, so a chunk packed here decodes through
# either): a chunk of m elements packs into B = ceil(m/1024)*128 bytes,
# bit-PLANAR over 8 sublane groups — byte j carries bit b = sign of flat
# element b*B*8/8... precisely: with the padded chunk viewed as
# [8*br, 128] rows (br = B/128), bit b of payload row r comes from input
# row b*br + r.  Planar packing keeps both pack and unpack pure
# shift+or over CONTIGUOUS sublane slices — no lane-crossing relayouts.


def _sign_rows(chunk: int) -> int:
    """Padded f32 rows of one chunk for the sign codec: a multiple of 8
    so the 8 bit planes are whole sublane slices."""
    return 8 * (-(-chunk // (8 * _LANE)))


def _sign_pack_kernel(x_ref, stats_ref, payload_ref, *, chunk: int):
    """Fused mean-abs reduction + planar sign pack, one VMEM pass.  The
    scale rides the shared stats-block layout (row 0, lane 0); padding
    lanes pack arbitrary sign bits that decode slices off."""
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    flat_idx = (
        jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    scale = jnp.sum(jnp.where(mask, jnp.abs(x), 0.0)) / chunk
    stats_ref[:] = jnp.full((_STATS_ROWS, _LANE), scale, jnp.float32)
    bits = (x >= 0).astype(jnp.int32)
    br = rows // 8
    packed = bits[0:br, :]
    for b in range(1, 8):
        packed = packed | (bits[b * br:(b + 1) * br, :] << b)
    payload_ref[:] = packed.astype(jnp.uint8)


def _sumabs_tile_kernel(x_ref, stats_ref, *, chunk: int):
    """Tiled mean-abs accumulation past the fused VMEM ceiling (the
    ``_absmax_tile_kernel`` pattern); the pack itself is elementwise and
    stays on the XLA lowering at those sizes (module docstring)."""
    j = pl.program_id(1)
    x = x_ref[:].astype(jnp.float32)
    rows, lanes = x.shape
    base = j * rows * lanes
    flat_idx = (
        base
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 0) * lanes
        + jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    )
    mask = flat_idx < chunk
    s = jnp.sum(jnp.where(mask, jnp.abs(x), 0.0)) / chunk
    tile_stats = jnp.full((_STATS_ROWS, _LANE), s, jnp.float32)

    @pl.when(j == 0)
    def _init():
        stats_ref[:] = tile_stats

    @pl.when(j > 0)
    def _accum():
        stats_ref[:] = stats_ref[:] + tile_stats


def _jnp_sign_pack(x2d: jax.Array) -> jax.Array:
    """Planar pack on the XLA lowering — the byte-identical fallback (and
    the pack half of the tiled path)."""
    k, m = x2d.shape
    rows = _sign_rows(m)
    br = rows // 8
    xp = jnp.pad(x2d, ((0, 0), (0, rows * _LANE - m)))
    bits = (xp >= 0).reshape(k, 8, br * _LANE).astype(jnp.uint8)
    packed = bits[:, 0, :]
    for b in range(1, 8):
        packed = packed | (bits[:, b, :] << b)
    return packed


@functools.partial(jax.jit, static_argnums=(1, 2))
def sign_compress_chunked_pallas(
    x: jax.Array, n_chunks: int, interpret: bool = False
) -> Tuple[jax.Array, jax.Array]:
    """Per-chunk (mean-abs scale, planar-packed sign bits) of flat ``x``
    (``size % n_chunks == 0``).  Fused one-pass inside the VMEM ceiling;
    past it the reduction tiles and the pack rides XLA."""
    assert x.size % n_chunks == 0, (x.size, n_chunks)
    chunk = x.size // n_chunks
    rows = _sign_rows(chunk)
    x2d = x.reshape(n_chunks, chunk).astype(jnp.float32)
    if rows <= _MAX_FUSED_ROWS:
        br = rows // 8
        xp = jnp.pad(x2d, ((0, 0), (0, rows * _LANE - chunk))).reshape(
            n_chunks * rows, _LANE
        )
        stats, payload = pl.pallas_call(
            functools.partial(_sign_pack_kernel, chunk=chunk),
            grid=(n_chunks,),
            in_specs=[
                pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((br, _LANE), lambda i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n_chunks * _STATS_ROWS, _LANE),
                                     jnp.float32),
                jax.ShapeDtypeStruct((n_chunks * br, _LANE), jnp.uint8),
            ],
            interpret=interpret,
        )(xp)
        scale = stats.reshape(n_chunks, _STATS_ROWS, _LANE)[:, 0, 0]
        return scale, payload.reshape(n_chunks, br * _LANE)
    # tiled reduction + XLA pack
    trows = -(-rows // _TILE_ROWS) * _TILE_ROWS
    n_tiles = trows // _TILE_ROWS
    xp = jnp.pad(x2d, ((0, 0), (0, trows * _LANE - chunk))).reshape(
        n_chunks * trows, _LANE
    )
    stats = pl.pallas_call(
        functools.partial(_sumabs_tile_kernel, chunk=chunk),
        grid=(n_chunks, n_tiles),
        in_specs=[
            pl.BlockSpec((_TILE_ROWS, _LANE),
                         lambda i, j: (i * n_tiles + j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((_STATS_ROWS, _LANE), lambda i, j: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (n_chunks * _STATS_ROWS, _LANE), jnp.float32
        ),
        interpret=interpret,
    )(xp)
    scale = stats.reshape(n_chunks, _STATS_ROWS, _LANE)[:, 0, 0]
    return scale, _jnp_sign_pack(x2d)


def _sign_unpack_kernel(stats_ref, payload_ref, out_ref):
    """Planar sign unpack: the inverse sublane layout, scaled by the
    chunk's mean-abs (a NaN/Inf scale poisons the whole chunk — the
    grad-guard propagation contract)."""
    scale = stats_ref[0, 0]
    p = payload_ref[:].astype(jnp.int32)
    planes = [((p >> b) & 1).astype(jnp.float32) for b in range(8)]
    bits = jnp.concatenate(planes, axis=0)
    out_ref[:] = (bits * 2.0 - 1.0) * scale


@functools.partial(jax.jit, static_argnums=(2,))
def sign_decompress_chunked_pallas(
    scale: jax.Array, payload: jax.Array, interpret: bool = False
) -> jax.Array:
    """Inverse of :func:`sign_compress_chunked_pallas`; returns the
    PADDED [n_chunks, rows*128] f32 block (the codec slices to m).  Only
    the fused size range routes here — larger chunks unpack through the
    XLA lowering like every other decompress."""
    n_chunks, B = payload.shape
    br = B // _LANE
    rows = 8 * br
    pp = payload.reshape(n_chunks * br, _LANE)
    block = jnp.zeros((n_chunks, _STATS_ROWS, _LANE), jnp.float32)
    block = block.at[:, 0, 0].set(scale.astype(jnp.float32))
    out = pl.pallas_call(
        _sign_unpack_kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((br, _LANE), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n_chunks * rows, _LANE),
                                       jnp.float32),
        interpret=interpret,
    )(block.reshape(n_chunks * _STATS_ROWS, _LANE), pp)
    return out.reshape(n_chunks, rows * _LANE)


@functools.partial(jax.jit, static_argnums=(3,))
def decompress_chunked_pallas(
    mn: jax.Array, mx: jax.Array, payload: jax.Array, interpret: bool = False
) -> jax.Array:
    """Inverse of :func:`compress_chunked_pallas`; returns flat f32."""
    n_chunks, chunk = payload.shape
    rows = _padded_rows(chunk)
    tiled = rows > _MAX_FUSED_ROWS
    if tiled:
        rows = -(-rows // _TILE_ROWS) * _TILE_ROWS
    padded = rows * _LANE
    pp = jnp.pad(payload, ((0, 0), (0, padded - chunk))).reshape(
        n_chunks * rows, _LANE
    )
    # lay out as [n_chunks*_STATS_ROWS, _LANE] with [0,0]=mn, [1,0]=mx
    block = jnp.zeros((n_chunks, _STATS_ROWS, _LANE), jnp.float32)
    block = block.at[:, 0, 0].set(mn.astype(jnp.float32))
    block = block.at[:, 1, 0].set(mx.astype(jnp.float32))
    if tiled:
        n_tiles = rows // _TILE_ROWS
        grid = (n_chunks, n_tiles)
        stats_spec = pl.BlockSpec((_STATS_ROWS, _LANE), lambda i, j: (i, 0),
                                  memory_space=pltpu.VMEM)
        data_spec = pl.BlockSpec((_TILE_ROWS, _LANE),
                                 lambda i, j: (i * n_tiles + j, 0),
                                 memory_space=pltpu.VMEM)
    else:
        grid = (n_chunks,)
        stats_spec = pl.BlockSpec((_STATS_ROWS, _LANE), lambda i: (i, 0),
                                  memory_space=pltpu.VMEM)
        data_spec = pl.BlockSpec((rows, _LANE), lambda i: (i, 0),
                                 memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        _decompress_kernel,
        grid=grid,
        in_specs=[stats_spec, data_spec],
        out_specs=data_spec,
        out_shape=jax.ShapeDtypeStruct((n_chunks * rows, _LANE), jnp.float32),
        interpret=interpret,
    )(block.reshape(n_chunks * _STATS_ROWS, _LANE), pp)
    return out.reshape(n_chunks, padded)[:, :chunk].reshape(-1)
