"""MinMaxUInt8 chunked codec + compressed scatter-gather allreduce.

TPU-native equivalent of the reference's CUDA codec
(/root/reference/rust/bagua-core/bagua-core-internal/kernels/bagua_kernels.cu:269-572:
CUB per-chunk min/max reduction, then scale-quantize into a per-chunk
[min,max | u8 payload] layout) and of the compressed comm op
(comm_ops/centralized_low_precision_synchronous.rs:16-74: compress →
alltoall → decompress → chunk-reduce → compress own chunk → allgather →
decompress).

Quantization math matches the reference's golden model
(tests/internal/compressor.py):

    scale = 255 / (max - min + eps)
    upper = round(max * scale);  lower = upper - 255
    level = clamp(round(x * scale), lower, upper)
    payload = uint8(level - lower);   x' = (payload + lower) / scale

The payload layout differs deliberately: instead of the reference's packed
32-byte-aligned header+payload byte buffer (a CUDA pointer-arithmetic
concern), min/max travel as a separate small f32 array — XLA fuses the
quantize with the preceding producer, and the two collectives (u8 payload +
f32 minmax) are batched into one ICI transfer by the compiler.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from ..communication import BaguaCommunicator

EPS = 1e-7
LEVELS = 255.0


def compress_chunked(x: jax.Array, n_chunks: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Compress flat f32/bf16 ``x`` (size divisible by ``n_chunks``) into
    per-chunk uint8 payloads.

    Returns ``(mn, mx, payload)`` with ``mn``/``mx`` shaped ``[n_chunks]``
    (f32) and ``payload`` shaped ``[n_chunks, chunk]`` (u8).
    """
    assert x.size % n_chunks == 0, (x.size, n_chunks)
    chunks = x.reshape(n_chunks, -1).astype(jnp.float32)
    mn = chunks.min(axis=1)
    mx = chunks.max(axis=1)
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    level = jnp.round(chunks * scale[:, None])
    level = jnp.clip(level, lower[:, None], upper[:, None])
    payload = (level - lower[:, None]).astype(jnp.uint8)
    return mn, mx, payload


def decompress_chunked(mn: jax.Array, mx: jax.Array, payload: jax.Array) -> jax.Array:
    """Inverse of :func:`compress_chunked`; returns flat f32 of
    ``payload.size`` elements."""
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    vals = (payload.astype(jnp.float32) + lower[:, None]) / scale[:, None]
    return vals.reshape(-1)


# measured crossover (BENCH_r05 kernel-level codec profile, v5e): the fused
# Pallas compress beats the XLA lowering from ~1 MiB chunks up (+9% kernel
# time) but LOSES below (grid/dispatch overhead dominates at 128 KB chunks);
# jnp decompress (one elementwise map, fully fused by XLA) beat the Pallas
# decompress at every measured size.  The crossover is BYTE-based (it is
# grid/dispatch overhead vs bytes streamed), so the gate scales by the
# input itemsize — a bf16/f16 flat must reach the same 1 MiB of payload,
# not half of it, before the Pallas path pays off (ADVICE.md).
_PALLAS_MIN_CHUNK_BYTES = 1 << 20  # 1 MiB


def _codec(comm: BaguaCommunicator):
    """Pick the codec implementation per MEASURED kernel profile (see
    module docstring of :mod:`.pallas_codec` and ``BENCH_COMM.json``):
    Pallas compress on TPU for chunks ≥1 MiB, the XLA lowering otherwise
    and for every decompress.  ``BAGUA_DISABLE_PALLAS_CODEC=1`` forces the
    jnp path for A/B checks.  The gate itself is
    :func:`.codecs._pallas_ok` — ONE place for the crossover, shared with
    the ring codecs — fed this communicator's mesh platform."""
    from .codecs import _pallas_ok

    platform = comm.mesh.devices.flat[0].platform

    def compress(v, n):
        if _pallas_ok((v.size // n) * v.dtype.itemsize, platform):
            from .pallas_codec import compress_chunked_pallas

            return compress_chunked_pallas(v, n)
        return compress_chunked(v, n)

    return compress, decompress_chunked


def quantize_with_bounds(
    x2d: jax.Array, mn: jax.Array, mx: jax.Array
) -> jax.Array:
    """Quantize ``[k, m]`` chunks against GIVEN per-chunk bounds — the
    codec's quantize half without its min/max reduction pass.  Values
    outside the bounds clamp to the grid edge (same clip the full codec
    applies), so sound bounds cost at most one extra grid step of error."""
    scale = LEVELS / (mx - mn + EPS)
    upper = jnp.round(mx * scale)
    lower = upper - LEVELS
    level = jnp.clip(
        jnp.round(x2d.astype(jnp.float32) * scale[:, None]),
        lower[:, None], upper[:, None],
    )
    return (level - lower[:, None]).astype(jnp.uint8)


def compressed_scatter_gather_allreduce(
    comm: BaguaCommunicator, x: jax.Array, average: bool = True
) -> jax.Array:
    """8-bit compressed allreduce over ``comm``'s axis (traced, inside
    shard_map).

    Pipeline (mirrors centralized_low_precision_synchronous.rs:31-70):
    compress all nranks chunks → all_to_all → decompress → reduce own chunk →
    quantize own chunk → all_gather → decompress.  ``x`` must be flat with
    ``size % nranks == 0`` (the bucket layer pads with world-size alignment).

    The allgather leg REUSES the scatter leg's scales (ISSUE 15): the
    reduced chunk provably lies within the mean/sum of its sources'
    ``[mn, mx]`` bounds (each dequantized source is clamped to its own
    grid), so the second quantize runs against those derived bounds —
    ONE min/max reduction pass per bucket instead of two, measurable on
    large buckets where the reduction is the codec's memory-bound half
    (BENCH_COMM r5).  Bound slack: a dequantized source can overshoot its
    bound by half a source grid step (``upper = round(mx·scale)``), and
    the derived grid is at most the mean source range wide — the clamp
    below absorbs both, keeping the error within one grid step of the
    recompute-min/max form.  Bits differ from that form, so the loss
    goldens carry regeneration provenance (tests/test_loss_goldens.py).
    """
    n = comm.nranks()
    compress, decompress = _codec(comm)
    mn, mx, payload = compress(x, n)
    # each rank ends up with every rank's chunk r (r = own rank index)
    payload_t = comm.alltoall(payload, split_axis=0, concat_axis=0)
    mn_t = comm.alltoall(mn, split_axis=0, concat_axis=0)
    mx_t = comm.alltoall(mx, split_axis=0, concat_axis=0)
    vals = decompress(mn_t, mx_t, payload_t).reshape(n, -1)
    red = vals.mean(axis=0) if average else vals.sum(axis=0)
    # quantize own reduced chunk against the sources' combined bounds (no
    # second min/max pass) and share it with everyone
    mn2 = (jnp.mean(mn_t) if average else jnp.sum(mn_t)).reshape(1)
    mx2 = (jnp.mean(mx_t) if average else jnp.sum(mx_t)).reshape(1)
    payload2 = quantize_with_bounds(red.reshape(1, -1), mn2, mx2)
    payload_all = comm.allgather(payload2, axis=0, tiled=True)  # [n, chunk]
    mn_all = comm.allgather(mn2, axis=0, tiled=True)            # [n]
    mx_all = comm.allgather(mx2, axis=0, tiled=True)
    return decompress(mn_all, mx_all, payload_all).astype(x.dtype)
