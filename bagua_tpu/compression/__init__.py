from .codecs import (  # noqa: F401
    CODECS,
    POLICY_VALUES,
    RingCodec,
    get_codec,
    resolve_codec,
    validate_codec_policy,
)
from .minmax_uint8 import (  # noqa: F401
    compress_chunked,
    compressed_scatter_gather_allreduce,
    decompress_chunked,
)
