from .minmax_uint8 import (  # noqa: F401
    compress_chunked,
    compressed_scatter_gather_allreduce,
    decompress_chunked,
)
