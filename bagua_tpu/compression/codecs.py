"""Ring-hop codec registry — the wire formats of the compressed collectives.

The Bagua paper's core relaxation is communication compression
(arXiv 2107.01499; 1-bit Adam, arXiv 2102.02888).  Until ISSUE 15 the
codecs ran as a *separate stage around* full-precision collectives; the
compressed ring collectives (``BaguaCommunicator.ring_*(codec=)``) instead
quantize ON the hop: every ``ppermute`` carries a codec payload plus its
small f32 sidecar, the receiver dequantizes and accumulates in fp32, and
the reduce-scatter result is re-quantized exactly once for the allgather
phase.  This module owns the payload formats.

Codec contract (all methods traced-safe):

* ``encode(x2d)`` — ``[k, m]`` float input -> a tuple of arrays, small f32
  sidecars first, the payload LAST, every part with leading dim ``k`` so
  the parts of one chunk travel (and stack) together.
* ``decode(parts, m=None)`` — exact inverse layout; returns ``[k, m]``
  **float32**.  Dequantize-to-f32 is the accumulation-dtype contract: ring
  hops add their local block in fp32, so quantization error never
  compounds through the accumulator dtype, only through the per-hop
  re-quantization.  ``m`` is the chunk element count: the uniform codecs
  infer it from the payload shape and ignore the argument, but the
  bit-packed and variable-payload codecs (``variable_payload = True``)
  cannot invert payload-shape -> m and REQUIRE it.
* ``wire_bytes(numel)`` — host-side bytes one encoded chunk of ``numel``
  elements puts on the wire (payload + sidecar); the byte-accounting
  source for ``bucket_tier_bytes``, the launch spans, and the benches.
  Codecs whose payload is not one byte per element (onebit_ef's packed
  bits, topk's index+value pairs) override it — accounting consumes the
  codec's ACTUAL per-hop bytes, never a numel*itemsize guess.

Stateful codecs (``error_feedback = True``): the codec itself stays a
pure wire format, but it only CONVERGES when the per-bucket
error-feedback residual folds the quantization error back into the next
step's gradient (EF-SignSGD, arXiv 1901.09847; 1-bit Adam, arXiv
2102.02888).  The residual lives in the algorithm state
(:meth:`bagua_tpu.algorithms.base.Algorithm.compensate_flats`), not here
— encode/decode see the already-compensated flats.

Non-finite contract: a NaN/Inf element poisons (at least) its own decoded
element and, for the scale-based codecs, its whole chunk — conservative on
purpose, so the gradient-health sentinel still sees the poison after a
compressed collective.

Pallas fast path: the min/max **reduction** is where a fused kernel pays
(BENCH_COMM r5: +8% at 1 MiB chunks, 7x at 8 MiB); purely elementwise maps
(quantize against known bounds, every decompress, the fp8 cast) measured
FASTER through the XLA lowering at every size, so only the reduction side
gates on :data:`~bagua_tpu.compression.minmax_uint8._PALLAS_MIN_CHUNK_BYTES`.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from .minmax_uint8 import (
    _PALLAS_MIN_CHUNK_BYTES,
    compress_chunked,
    decompress_chunked,
)


def _pallas_ok(chunk_bytes: int, platform: Optional[str] = None) -> bool:
    """The ONE gate for the fused Pallas reduction kernels: TPU, not
    disabled, and the per-chunk payload past the measured crossover —
    shared with :func:`..minmax_uint8._codec` so the crossover can never
    be retuned in one place and not the other.  ``platform`` lets a
    mesh-aware caller pass its comm mesh's platform; default is the
    ambient backend."""
    from .. import env

    if chunk_bytes < _PALLAS_MIN_CHUNK_BYTES:
        return False
    if platform is None:
        try:
            platform = jax.devices()[0].platform
        except Exception:  # pragma: no cover - backend not initialized
            return False
    return platform == "tpu" and not env.is_pallas_codec_disabled()


def _absmax_sidecar(x: jax.Array, chunk_bytes: int,
                    fmax: float) -> Tuple[jax.Array, jax.Array]:
    """Shared scaled-quantize front half of the int8/fp8 codecs: per-chunk
    absmax (fused Pallas past the crossover) mapped onto a grid of
    ``fmax``.  Returns ``(sidecar, safe)`` — ``safe`` is the
    division-ready scale (1.0 for all-zero chunks), ``sidecar`` the wire
    copy, which deliberately keeps a NaN absmax (a NaN fails every
    comparison, so ``safe`` would silently become 1 and the cast would
    flush the poison to a finite value — the sidecar NaN makes DECODE
    propagate it, the grad-guard contract)."""
    k, m = x.shape
    if _pallas_ok(chunk_bytes):
        from .pallas_codec import absmax_chunked_pallas

        absmax = absmax_chunked_pallas(x.reshape(-1), k)
    else:
        absmax = jnp.abs(x).max(axis=1)
    scale = absmax / fmax
    safe = jnp.where(scale > 0, scale, 1.0)
    return jnp.where(jnp.isnan(scale), scale, safe), safe


class RingCodec:
    """One wire format for the compressed ring hops."""

    #: registry key (the user-facing knob value)
    name: str = ""
    #: dtype of the payload array (the bulk of the wire bytes)
    payload_itemsize: int = 1
    #: f32 sidecar scalars per encoded chunk
    sidecar_floats: int = 0
    #: True for codecs that only converge with the per-bucket
    #: error-feedback residual (the algorithm layer engages it)
    error_feedback: bool = False
    #: True when the payload shape is not [k, m] — decode REQUIRES ``m``
    #: and byte accounting must go through ``wire_bytes``, never
    #: numel * itemsize
    variable_payload: bool = False
    #: True for codecs whose wire format depends on a BAGUA_* env knob:
    #: :func:`get_codec` re-constructs them per lookup so the knob is
    #: read when the codec is *resolved* (trainer construction / step
    #: trace), not frozen at process import — matching every other
    #: BAGUA_* knob and the podsim numpy mirror.
    env_tuned: bool = False

    def encode(self, x2d: jax.Array) -> Tuple[jax.Array, ...]:
        raise NotImplementedError

    def decode(self, parts: Tuple[jax.Array, ...],
               m: Optional[int] = None) -> jax.Array:
        raise NotImplementedError

    def wire_bytes(self, numel: int) -> int:
        """Wire bytes of ONE encoded chunk of ``numel`` elements."""
        return int(numel) * self.payload_itemsize + 4 * self.sidecar_floats

    def payload_numel(self, numel: int) -> int:
        """Host-side element count of the PAYLOAD array for an
        ``numel``-element chunk — what a traced collective's operand shape
        shows (bagua-lint's per-bucket attribution matches on it).  The
        uniform codecs carry one payload element per input element; the
        bit-packed/sparse codecs override."""
        return int(numel)

    def __repr__(self) -> str:  # stable in logs / span attrs
        return f"<RingCodec {self.name}>"


class MinMaxUInt8Codec(RingCodec):
    """The reference MinMaxUInt8 format: per-chunk ``[mn, mx]`` f32 sidecar
    + u8 levels (``tests/internal/compressor.py`` golden math).  Fused
    Pallas min/max+quantize past the measured chunk-size crossover."""

    name = "minmax_uint8"
    payload_itemsize = 1
    sidecar_floats = 2

    def encode(self, x2d):
        k, m = x2d.shape
        flat = x2d.reshape(-1)
        if _pallas_ok(m * x2d.dtype.itemsize):
            from .pallas_codec import compress_chunked_pallas

            mn, mx, payload = compress_chunked_pallas(flat, k)
        else:
            mn, mx, payload = compress_chunked(flat, k)
        return mn, mx, payload

    def decode(self, parts, m=None):
        mn, mx, payload = parts
        return decompress_chunked(mn, mx, payload).reshape(payload.shape)


class Int8Codec(RingCodec):
    """Symmetric absmax int8: per-chunk f32 ``scale`` sidecar, payload
    ``round(x / scale)`` clipped to [-127, 127].  One fewer sidecar float
    than MinMaxUInt8 and a zero-centered grid (a zero gradient stays
    exactly zero — MinMaxUInt8's grid need not contain 0).  The absmax
    reduction takes the fused Pallas kernel past the crossover."""

    name = "int8"
    payload_itemsize = 1
    sidecar_floats = 1

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        sidecar, safe = _absmax_sidecar(
            x, x2d.shape[1] * x2d.dtype.itemsize, 127.0
        )
        q = jnp.clip(jnp.round(x / safe[:, None]), -127.0, 127.0)
        return sidecar, q.astype(jnp.int8)

    def decode(self, parts, m=None):
        scale, payload = parts
        return payload.astype(jnp.float32) * scale[:, None]


class Fp8Codec(RingCodec):
    """Scaled fp8: per-chunk f32 ``scale`` sidecar mapping the chunk's
    absmax onto the format's max finite value, payload ``x / scale`` cast
    to the fp8 dtype.  ``e4m3`` (3 mantissa bits, higher resolution) suits
    gradient payloads; ``e5m2`` keeps bf16's exponent spread for
    heavy-tailed chunks.  The scaling keeps denormal-range inputs
    representable (the payload always spans the full fp8 range), and a
    non-finite input propagates: ``inf/inf -> nan`` lands IN the payload.
    The cast is elementwise, so the only reduction (absmax) gates on the
    Pallas crossover like int8."""

    payload_itemsize = 1
    sidecar_floats = 1

    def __init__(self, name: str, dtype):
        self.name = name
        self.dtype = dtype
        self.fmax = float(jnp.finfo(dtype).max)

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        sidecar, safe = _absmax_sidecar(
            x, x2d.shape[1] * x2d.dtype.itemsize, self.fmax
        )
        return sidecar, (x / safe[:, None]).astype(self.dtype)

    def decode(self, parts, m=None):
        scale, payload = parts
        return payload.astype(jnp.float32) * scale[:, None]


def _onebit_payload_bytes(m: int) -> int:
    """Packed-payload bytes of one m-element chunk: ceil(m/1024)*128 —
    the planar layout pads to whole 8x(8,128) bit-plane groups so pack
    and unpack stay contiguous sublane slices on TPU (pallas_codec)."""
    return -(-int(m) // 1024) * 128


class OneBitEfCodec(RingCodec):
    """Sign/1-bit codec: per-chunk f32 mean-abs ``scale`` sidecar + a
    bit-packed sign payload (~32x fewer wire bytes than f32; the Bagua
    paper's signature relaxation).  Decode is ``scale * sign(x)`` — the
    L1-optimal magnitude for a sign quantizer (EF-SignSGD §4).  An
    all-zero chunk round-trips exactly (scale 0); a NaN/Inf element
    drives the mean-abs scale non-finite, poisoning the whole decoded
    chunk — the grad-guard propagation contract, same as the absmax
    codecs.  Pack/unpack + the mean-abs reduction take the fused Pallas
    kernels past the shared crossover; below it (or off-TPU) the
    byte-identical jnp planar pack runs.

    ``error_feedback = True``: without the per-bucket residual this is
    biased sign-SGD and diverges — the algorithm layer engages
    ``compensate_flats`` wherever this codec rides."""

    name = "onebit_ef"
    payload_itemsize = 1  # uint8, but ~m/8 of them: wire_bytes overrides
    sidecar_floats = 1
    error_feedback = True
    variable_payload = True

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        k, m = x.shape
        if _pallas_ok(m * x2d.dtype.itemsize):
            from .pallas_codec import sign_compress_chunked_pallas

            scale, payload = sign_compress_chunked_pallas(x.reshape(-1), k)
        else:
            from .pallas_codec import _jnp_sign_pack

            scale = jnp.abs(x).sum(axis=1) / m
            payload = _jnp_sign_pack(x)
        return scale, payload

    def decode(self, parts, m=None):
        scale, payload = parts
        k, B = payload.shape
        if m is None:
            m = 8 * B  # full padded block (no slicing possible)
        if _pallas_ok(_onebit_payload_bytes(m) * 8 * 4):
            from .pallas_codec import sign_decompress_chunked_pallas

            out = sign_decompress_chunked_pallas(scale, payload)
            return out[:, :m]
        shifts = jnp.arange(8, dtype=jnp.uint8).reshape(1, 8, 1)
        bits = (payload[:, None, :] >> shifts) & jnp.uint8(1)
        signs = bits.reshape(k, 8 * B)[:, :m].astype(jnp.float32) * 2.0 - 1.0
        return signs * scale[:, None]

    def wire_bytes(self, numel: int) -> int:
        return _onebit_payload_bytes(numel) + 4 * self.sidecar_floats

    def payload_numel(self, numel: int) -> int:
        # lane-padded uint8 byte count: the traced ppermute operand shape
        return _onebit_payload_bytes(int(numel))


class TopKCodec(RingCodec):
    """Top-k sparsification — the first VARIABLE-PAYLOAD ring codec:
    parts are ``(int32 indices, f32 values)`` of the ``kk`` largest-
    magnitude elements per chunk, ``kk = clamp(ceil(m * ratio), 1, m)``
    with ``ratio`` the compression knob (``BAGUA_TOPK_RATIO``, default
    1% -> ~50x fewer DCN bytes).  Values travel exact f32, so there is
    no scale sidecar and no quantization error on the SELECTED elements
    — all the loss is the dropped tail, which is exactly what the
    error-feedback residual re-injects next step
    (``error_feedback = True``; stateless top-k loses the small-gradient
    mass forever).  Non-finite elements are force-selected (their sort
    magnitude becomes +inf), so a poisoned element always survives
    decode — the grad-guard contract without a scale sidecar to carry
    it."""

    payload_itemsize = 4
    sidecar_floats = 0
    error_feedback = True
    variable_payload = True
    env_tuned = True  # ratio from BAGUA_TOPK_RATIO at resolution time

    def __init__(self, ratio: Optional[float] = None, name: str = "topk"):
        from .. import env

        self.name = name
        self.ratio = float(env.get_topk_ratio() if ratio is None else ratio)
        if not 0.0 < self.ratio <= 1.0:
            raise ValueError(
                f"topk ratio must be in (0, 1], got {self.ratio}"
            )

    def k_for(self, numel: int) -> int:
        """Selected elements for an m-element chunk (host-static: the
        payload shape is compiled into the step)."""
        n = int(numel)
        return max(1, min(n, int(math.ceil(n * self.ratio))))

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        k, m = x.shape
        kk = self.k_for(m)
        mag = jnp.where(jnp.isfinite(x), jnp.abs(x), jnp.inf)
        _, idx = jax.lax.top_k(mag, kk)
        vals = jnp.take_along_axis(x, idx, axis=1)
        return idx.astype(jnp.int32), vals

    def decode(self, parts, m=None):
        idx, vals = parts
        if m is None:
            raise ValueError(
                "topk is variable-payload: decode(parts, m) needs the "
                "chunk element count"
            )
        k, kk = idx.shape
        out = jnp.zeros((k, int(m)), jnp.float32)
        rows = jnp.arange(k, dtype=jnp.int32)[:, None]
        return out.at[rows, idx].set(vals.astype(jnp.float32))

    def wire_bytes(self, numel: int) -> int:
        # int32 index + f32 value per selected element
        return 8 * self.k_for(numel)

    def payload_numel(self, numel: int) -> int:
        # each of the two part arrays carries k_for(m) elements per row
        return self.k_for(numel)


CODECS: Dict[str, RingCodec] = {
    c.name: c
    for c in (
        MinMaxUInt8Codec(),
        Int8Codec(),
        Fp8Codec("fp8_e4m3", jnp.float8_e4m3fn),
        Fp8Codec("fp8_e5m2", jnp.float8_e5m2),
        OneBitEfCodec(),
        TopKCodec(),
    )
}

#: the autopilot's compress_dcn escalation ladder: each sustained
#: DCN-dominance verdict climbs one rung (docs/compression.md) — 8-bit
#: first (cheap, stateless), fp8 next (same bytes, cheaper decode),
#: then the stateful 1-bit/sparse codecs where the residual machinery
#: buys the last 4-8x.
CODEC_LADDER = ("minmax_uint8", "fp8_e4m3", "onebit_ef", "topk")

#: codec-policy knob values beyond the codec names themselves:
#: ``off`` forces full precision on the tier (even where the algorithm
#: family compresses natively), ``auto`` defers to the family default —
#: DCN compressed for the compression families (ByteGrad/QAdam), ICI
#: full-precision for everyone (docs/compression.md).
POLICY_OFF = "off"
POLICY_AUTO = "auto"
POLICY_VALUES = (POLICY_OFF, POLICY_AUTO) + tuple(sorted(CODECS))


def get_codec(name: str) -> RingCodec:
    codec = CODECS.get(name)
    if codec is None:
        raise ValueError(
            f"unknown ring codec {name!r} (available: {sorted(CODECS)})"
        )
    if codec.env_tuned:
        # a fresh instance re-reads the codec's env knobs (topk's
        # BAGUA_TOPK_RATIO): the import-time singleton would freeze the
        # value for the whole process, silently ignoring a knob set
        # before trainer construction.  The backend keys the step cache
        # on the effective ratio so a changed knob retraces.
        return type(codec)()
    return codec


def resolve_codec(
    codec: Union[None, str, RingCodec]
) -> Optional[RingCodec]:
    """None passes through (full precision); names resolve via the
    registry; codec instances pass through."""
    if codec is None:
        return None
    if isinstance(codec, RingCodec):
        return codec
    return get_codec(codec)


def validate_codec_policy(value: str, knob: str) -> str:
    """Normalize + validate one per-tier codec-policy knob value
    (``BAGUA_COMPRESS_{INTRA,INTER}`` / the trainer kwargs)."""
    v = (value or POLICY_AUTO).strip().lower()
    if v not in POLICY_VALUES:
        raise ValueError(
            f"{knob} must be one of {'|'.join(POLICY_VALUES)}, got {value!r}"
        )
    return v
