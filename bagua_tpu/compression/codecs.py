"""Ring-hop codec registry — the wire formats of the compressed collectives.

The Bagua paper's core relaxation is communication compression
(arXiv 2107.01499; 1-bit Adam, arXiv 2102.02888).  Until ISSUE 15 the
codecs ran as a *separate stage around* full-precision collectives; the
compressed ring collectives (``BaguaCommunicator.ring_*(codec=)``) instead
quantize ON the hop: every ``ppermute`` carries a codec payload plus its
small f32 sidecar, the receiver dequantizes and accumulates in fp32, and
the reduce-scatter result is re-quantized exactly once for the allgather
phase.  This module owns the payload formats.

Codec contract (all methods traced-safe):

* ``encode(x2d)`` — ``[k, m]`` float input -> a tuple of arrays, small f32
  sidecars first, the payload LAST, every part with leading dim ``k`` so
  the parts of one chunk travel (and stack) together.
* ``decode(parts)`` — exact inverse layout; returns ``[k, m]`` **float32**.
  Dequantize-to-f32 is the accumulation-dtype contract: ring hops add
  their local block in fp32, so quantization error never compounds through
  the accumulator dtype, only through the per-hop re-quantization.
* ``wire_bytes(numel)`` — host-side bytes one encoded chunk of ``numel``
  elements puts on the wire (payload + sidecar); the byte-accounting
  source for ``bucket_tier_bytes``, the launch spans, and the benches.

Non-finite contract: a NaN/Inf element poisons (at least) its own decoded
element and, for the scale-based codecs, its whole chunk — conservative on
purpose, so the gradient-health sentinel still sees the poison after a
compressed collective.

Pallas fast path: the min/max **reduction** is where a fused kernel pays
(BENCH_COMM r5: +8% at 1 MiB chunks, 7x at 8 MiB); purely elementwise maps
(quantize against known bounds, every decompress, the fp8 cast) measured
FASTER through the XLA lowering at every size, so only the reduction side
gates on :data:`~bagua_tpu.compression.minmax_uint8._PALLAS_MIN_CHUNK_BYTES`.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp

from .minmax_uint8 import (
    _PALLAS_MIN_CHUNK_BYTES,
    compress_chunked,
    decompress_chunked,
)


def _pallas_ok(chunk_bytes: int, platform: Optional[str] = None) -> bool:
    """The ONE gate for the fused Pallas reduction kernels: TPU, not
    disabled, and the per-chunk payload past the measured crossover —
    shared with :func:`..minmax_uint8._codec` so the crossover can never
    be retuned in one place and not the other.  ``platform`` lets a
    mesh-aware caller pass its comm mesh's platform; default is the
    ambient backend."""
    from .. import env

    if chunk_bytes < _PALLAS_MIN_CHUNK_BYTES:
        return False
    if platform is None:
        try:
            platform = jax.devices()[0].platform
        except Exception:  # pragma: no cover - backend not initialized
            return False
    return platform == "tpu" and not env.is_pallas_codec_disabled()


def _absmax_sidecar(x: jax.Array, chunk_bytes: int,
                    fmax: float) -> Tuple[jax.Array, jax.Array]:
    """Shared scaled-quantize front half of the int8/fp8 codecs: per-chunk
    absmax (fused Pallas past the crossover) mapped onto a grid of
    ``fmax``.  Returns ``(sidecar, safe)`` — ``safe`` is the
    division-ready scale (1.0 for all-zero chunks), ``sidecar`` the wire
    copy, which deliberately keeps a NaN absmax (a NaN fails every
    comparison, so ``safe`` would silently become 1 and the cast would
    flush the poison to a finite value — the sidecar NaN makes DECODE
    propagate it, the grad-guard contract)."""
    k, m = x.shape
    if _pallas_ok(chunk_bytes):
        from .pallas_codec import absmax_chunked_pallas

        absmax = absmax_chunked_pallas(x.reshape(-1), k)
    else:
        absmax = jnp.abs(x).max(axis=1)
    scale = absmax / fmax
    safe = jnp.where(scale > 0, scale, 1.0)
    return jnp.where(jnp.isnan(scale), scale, safe), safe


class RingCodec:
    """One wire format for the compressed ring hops."""

    #: registry key (the user-facing knob value)
    name: str = ""
    #: dtype of the payload array (the bulk of the wire bytes)
    payload_itemsize: int = 1
    #: f32 sidecar scalars per encoded chunk
    sidecar_floats: int = 0

    def encode(self, x2d: jax.Array) -> Tuple[jax.Array, ...]:
        raise NotImplementedError

    def decode(self, parts: Tuple[jax.Array, ...]) -> jax.Array:
        raise NotImplementedError

    def wire_bytes(self, numel: int) -> int:
        """Wire bytes of ONE encoded chunk of ``numel`` elements."""
        return int(numel) * self.payload_itemsize + 4 * self.sidecar_floats

    def __repr__(self) -> str:  # stable in logs / span attrs
        return f"<RingCodec {self.name}>"


class MinMaxUInt8Codec(RingCodec):
    """The reference MinMaxUInt8 format: per-chunk ``[mn, mx]`` f32 sidecar
    + u8 levels (``tests/internal/compressor.py`` golden math).  Fused
    Pallas min/max+quantize past the measured chunk-size crossover."""

    name = "minmax_uint8"
    payload_itemsize = 1
    sidecar_floats = 2

    def encode(self, x2d):
        k, m = x2d.shape
        flat = x2d.reshape(-1)
        if _pallas_ok(m * x2d.dtype.itemsize):
            from .pallas_codec import compress_chunked_pallas

            mn, mx, payload = compress_chunked_pallas(flat, k)
        else:
            mn, mx, payload = compress_chunked(flat, k)
        return mn, mx, payload

    def decode(self, parts):
        mn, mx, payload = parts
        return decompress_chunked(mn, mx, payload).reshape(payload.shape)


class Int8Codec(RingCodec):
    """Symmetric absmax int8: per-chunk f32 ``scale`` sidecar, payload
    ``round(x / scale)`` clipped to [-127, 127].  One fewer sidecar float
    than MinMaxUInt8 and a zero-centered grid (a zero gradient stays
    exactly zero — MinMaxUInt8's grid need not contain 0).  The absmax
    reduction takes the fused Pallas kernel past the crossover."""

    name = "int8"
    payload_itemsize = 1
    sidecar_floats = 1

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        sidecar, safe = _absmax_sidecar(
            x, x2d.shape[1] * x2d.dtype.itemsize, 127.0
        )
        q = jnp.clip(jnp.round(x / safe[:, None]), -127.0, 127.0)
        return sidecar, q.astype(jnp.int8)

    def decode(self, parts):
        scale, payload = parts
        return payload.astype(jnp.float32) * scale[:, None]


class Fp8Codec(RingCodec):
    """Scaled fp8: per-chunk f32 ``scale`` sidecar mapping the chunk's
    absmax onto the format's max finite value, payload ``x / scale`` cast
    to the fp8 dtype.  ``e4m3`` (3 mantissa bits, higher resolution) suits
    gradient payloads; ``e5m2`` keeps bf16's exponent spread for
    heavy-tailed chunks.  The scaling keeps denormal-range inputs
    representable (the payload always spans the full fp8 range), and a
    non-finite input propagates: ``inf/inf -> nan`` lands IN the payload.
    The cast is elementwise, so the only reduction (absmax) gates on the
    Pallas crossover like int8."""

    payload_itemsize = 1
    sidecar_floats = 1

    def __init__(self, name: str, dtype):
        self.name = name
        self.dtype = dtype
        self.fmax = float(jnp.finfo(dtype).max)

    def encode(self, x2d):
        x = x2d.astype(jnp.float32)
        sidecar, safe = _absmax_sidecar(
            x, x2d.shape[1] * x2d.dtype.itemsize, self.fmax
        )
        return sidecar, (x / safe[:, None]).astype(self.dtype)

    def decode(self, parts):
        scale, payload = parts
        return payload.astype(jnp.float32) * scale[:, None]


CODECS: Dict[str, RingCodec] = {
    c.name: c
    for c in (
        MinMaxUInt8Codec(),
        Int8Codec(),
        Fp8Codec("fp8_e4m3", jnp.float8_e4m3fn),
        Fp8Codec("fp8_e5m2", jnp.float8_e5m2),
    )
}

#: codec-policy knob values beyond the codec names themselves:
#: ``off`` forces full precision on the tier (even where the algorithm
#: family compresses natively), ``auto`` defers to the family default —
#: DCN compressed for the compression families (ByteGrad/QAdam), ICI
#: full-precision for everyone (docs/compression.md).
POLICY_OFF = "off"
POLICY_AUTO = "auto"
POLICY_VALUES = (POLICY_OFF, POLICY_AUTO) + tuple(sorted(CODECS))


def get_codec(name: str) -> RingCodec:
    codec = CODECS.get(name)
    if codec is None:
        raise ValueError(
            f"unknown ring codec {name!r} (available: {sorted(CODECS)})"
        )
    return codec


def resolve_codec(
    codec: Union[None, str, RingCodec]
) -> Optional[RingCodec]:
    """None passes through (full precision); names resolve via the
    registry; codec instances pass through."""
    if codec is None:
        return None
    if isinstance(codec, RingCodec):
        return codec
    return get_codec(codec)


def validate_codec_policy(value: str, knob: str) -> str:
    """Normalize + validate one per-tier codec-policy knob value
    (``BAGUA_COMPRESS_{INTRA,INTER}`` / the trainer kwargs)."""
    v = (value or POLICY_AUTO).strip().lower()
    if v not in POLICY_VALUES:
        raise ValueError(
            f"{knob} must be one of {'|'.join(POLICY_VALUES)}, got {value!r}"
        )
    return v
