"""Unified observability plane (docs/observability.md).

Three coupled pieces, instrumented into the real code paths:

* :mod:`~bagua_tpu.obs.spans` — host-side step-span tracer
  (``trace_span``) with a bounded ring buffer; the trainer, overlap
  scheduler, async boundaries, checkpoint paths, elastic rendezvous, and
  watchdog sections all open spans.
* :mod:`~bagua_tpu.obs.recorder` — crash flight recorder: on watchdog
  abort, grad-guard escalation, health-fence stop, armed-fault fire, or
  SIGTERM, dump spans + counters + step metrics to
  ``BAGUA_OBS_DUMP_DIR``.
* :mod:`~bagua_tpu.obs.export` — ``METRIC_REGISTRY`` (every counter/gauge
  name, lint-enforced), the background metrics exporter
  (JSONL + Prometheus textfile), and the coordinator-side fleet snapshot.

Master switch: ``BAGUA_OBS`` (default on; ``off`` restores the exact
pre-obs host behavior — the compiled step program is identical either way).
Import-light: no jax anywhere in the package.
"""

from .export import (  # noqa: F401
    METRIC_REGISTRY,
    MetricsExporter,
    local_obs_summary,
    render_prometheus,
    validate_fleet_snapshot,
    write_fleet_snapshot,
)
from .recorder import (  # noqa: F401
    dump_flight_record,
    validate_flight_record,
)
# NOTE: the span ring instance is ``spans.recorder`` — deliberately NOT
# re-exported here, where it would shadow the ``obs.recorder`` submodule
from .spans import SpanRecorder, span_ring, trace_span  # noqa: F401
