"""Unified observability plane (docs/observability.md).

Three coupled pieces, instrumented into the real code paths:

* :mod:`~bagua_tpu.obs.spans` — host-side step-span tracer
  (``trace_span``) with a bounded ring buffer; the trainer, overlap
  scheduler, async boundaries, checkpoint paths, elastic rendezvous, and
  watchdog sections all open spans.
* :mod:`~bagua_tpu.obs.recorder` — crash flight recorder: on watchdog
  abort, grad-guard escalation, health-fence stop, armed-fault fire, or
  SIGTERM, dump spans + counters + step metrics to
  ``BAGUA_OBS_DUMP_DIR``.
* :mod:`~bagua_tpu.obs.export` — ``METRIC_REGISTRY`` (every counter/gauge
  name, lint-enforced), the background metrics exporter
  (JSONL + Prometheus textfile), and the coordinator-side fleet snapshot.

Plus the analysis layer on top of those signals:

* :mod:`~bagua_tpu.obs.timeline` — merge per-rank span dumps into one
  clock-aligned Perfetto/Chrome trace (``python -m bagua_tpu.obs.timeline``).
* :mod:`~bagua_tpu.obs.anomaly` — rolling median/MAD step-time anomaly
  detector: ``straggler_suspect`` phase breakdowns into the health beacon,
  throttled flight dumps, perf hints for the autotune service.
* :mod:`~bagua_tpu.obs.attribution` — device-time attribution: per-bucket
  device comm seconds + overlap fraction from profiler xplanes
  (null-with-rationale on cpu-sim).
* :mod:`~bagua_tpu.obs.regress` — bench-trend sentinel against the
  committed ``BENCH_*.json``/``EFFICIENCY.json`` records
  (``python -m bagua_tpu.obs.regress``).

And the efficiency plane over all of it:

* :mod:`~bagua_tpu.obs.ledger` — goodput/badput wall-clock ledger: every
  second lands in one class (productive-step, compile, checkpoint,
  rendezvous, catchup-sync, rewind, stall, idle), exported as gauges,
  rolled up fleet-wide, rendered by ``python -m bagua_tpu.obs.ledger``;
  plus the peak-silicon tables behind the per-step ``obs/mfu`` gauge.
* :mod:`~bagua_tpu.obs.memory` — HBM accounting: static per-plan
  footprint (exact on cpu-sim), per-step-cache ``memory_analysis()``,
  live ``device.memory_stats()`` peaks/headroom on real TPU.

And the fleet-historical layer (ISSUE 14):

* :mod:`~bagua_tpu.obs.historian` — coordinator-side time-series rings
  over the fleet-snapshot stream with windowed rate/percentile/slope
  queries; publishes trend gauges (``obs/goodput_slope``,
  ``obs/hbm_headroom_slope``, ``obs/dcn_comm_share``) back into each
  snapshot and persists through the restart store.
* :mod:`~bagua_tpu.obs.http` — per-process HTTP status plane
  (``/metrics`` from the same prepared snapshot as ``metrics.prom``,
  ``/healthz``, ``/ledger``; the coordinator adds ``/fleet`` and
  ``/history``), gated by ``BAGUA_OBS_HTTP_PORT``.

Master switch: ``BAGUA_OBS`` (default on; ``off`` restores the exact
pre-obs host behavior — the compiled step program is identical either way).
Import-light: no jax anywhere in the package (``attribution``/``regress``
import it lazily for parsing/probing only).
"""

from .export import (  # noqa: F401
    METRIC_REGISTRY,
    MetricsExporter,
    local_obs_summary,
    render_prometheus,
    validate_fleet_snapshot,
    write_fleet_snapshot,
)
from .export import LEDGER_CLASSES  # noqa: F401
from .historian import Historian, maybe_build_historian  # noqa: F401
from .http import ObsHTTPServer, maybe_start_global_http_server  # noqa: F401
from .memory import live_memory_stats, plan_flat_bytes, static_footprint  # noqa: F401
from .recorder import (  # noqa: F401
    dump_flight_record,
    validate_flight_record,
)
# NOTE: the span ring instance is ``spans.recorder`` — deliberately NOT
# re-exported here, where it would shadow the ``obs.recorder`` submodule
from .spans import SpanRecorder, span_ring, trace_span  # noqa: F401
from .anomaly import StepAnomalyDetector, fleet_straggler_suspects  # noqa: F401,E402
# NOTE: obs.timeline, obs.regress, and obs.ledger are NOT imported here —
# all three are `python -m` entry points, and a package-level import would
# leave a second copy of the module executing under runpy (the ledger
# singleton and its validate_efficiency live in obs.ledger; consumers
# import the module lazily)
